"""Quantized-compute GEMMs: the per-block-scale machinery moved from
the wire into the matmul itself.

The repo already quantizes int8 with per-block scales in two places —
the ZeRO-Offload compressed wire (PR 1) and int8 weight-only serving
(PR 12, `inference/quant.py`) — but until now the MXU never saw the
quantized values: quantization only compressed bytes in flight.  This
module is the ONE home of that scale layout and of the dequant
epilogues that consume it, shared by training and inference:

  scale layout (the PR-1 block machinery, per kernel [.., K, N]):
      weights:      one fp32 scale per (K-block, output-column)
                    -> scales [.., nb, N], nb = ceil(K / block)
      activations:  one fp32 scale per row (per token)
                    -> x_scales [.., rows, 1]

  epilogue families:
      * `int8_matmul`  — weight-only: x stays in the compute dtype,
        int8 weights are cast and contracted per K-block and the
        per-block scale multiplies each block's partial sum (the
        serving path; `inference/quant.py` re-exports this).
      * `quantized_matmul` / `quantized_dense` — quantized compute:
        BOTH operands int8, the MXU contracts int8xint8 -> int32 and
        the dequant (x-row scale x weight-block scale) rides the GEMM
        epilogue.  On TPU this is a Pallas kernel (grid (M/bm, N/bn,
        nb), K innermost, fp32 accumulator scratch; int8 tiles obey
        the (32, 128) tiling floor so `block`/`block_n` must be
        128-multiples); elsewhere an XLA fallback reproduces the SAME
        quantization numerics with the dequantized operands feeding
        one fp32 GEMM (integer values ≤127 and block partial sums are
        exact in fp32, so fallback and kernel agree to fp32 roundoff).

Training (`quantized_dense`) wraps the forward in a straight-through
custom VJP: the forward runs the quantized GEMM off the CURRENT
weights (re-quantized every step inside the trace), the backward
treats quantization as identity — d x = g @ W_eff^T with
W_eff = dequant(quantize(W)) recomputed from the saved raw weights
(no extra residual memory), d W = x^T @ g in full precision.  The
backward GEMMs stay in the compute dtype: this is a *quantized
forward* matmul, the standard QAT contract.

`stochastic_rounding=True` rounds the int8 quantization stochastically
(floor(v + u), unbiased) when a `rng` is supplied — the engine threads
a per-step "quant" rng stream next to "dropout".  The same flag makes
the no-quantization bf16 fallback (`resolve_quantized_compute` ->
False with stochastic_rounding on) use an unbiased stochastically
rounded fp32->bf16 operand cast (`bf16_optimizer.stochastic_round_bf16`)
instead of truncation; without the flag that fallback is bit-for-bit
today's bf16 GEMM — backward compatible.

Parity is pinned by the `quantized_matmul` bench leg (loss/logit
bounds asserted in-leg) and tests/test_quantized_matmul.py.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# default quantization block along the contraction dim for the
# quantized-compute (training) family. 128 = one MXU/lane tile, the
# Pallas kernel's minimum legal int8 K-tile. (Serving keeps its own
# 64 default — finer blocks, XLA epilogue only.)
DEFAULT_QUANT_BLOCK = 128

_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))
_COMPILER_PARAMS = None if _CompilerParams is None else \
    _CompilerParams(vmem_limit_bytes=64 * 1024 * 1024)


def _on_tpu():
    return jax.default_backend() == "tpu"


def resolve_quantized_compute(mode):
    """`quantized_compute` config value -> bool. "auto" enables the
    int8 compute path on real TPU only (the backend-keyed auto
    convention of fused_ops/head_packing: CPU numerics stay
    bit-identical by default); "on" forces it anywhere (XLA fallback
    off-TPU, same quantization numerics); "off" disables."""
    if mode in ("off", False, 0, None):
        return False
    if mode in ("on", True, 1):
        return True
    if mode == "auto":
        return _on_tpu()
    raise ValueError(
        f"quantized_compute={mode!r}: expected 'auto', 'on' or 'off'")


# ----------------------------------------------------------------------
# the shared scale layout: numpy (load-time, serving) + jnp (traced,
# training) quantizers. ONE formula: scale = maxabs/127 per
# (K-block, column), zero-scale blocks clamp to 1.
# ----------------------------------------------------------------------
def quantize_kernel_int8_np(w, block):
    """[.., K, N] fp kernel -> (q int8 [.., K, N], scales fp32
    [.., nb, N]) with K zero-padded conceptually to nb*block (scales
    for the pad region fall out of max-abs over the real rows).
    Numpy, for quantize-once-at-load users (the serving engine)."""
    w = np.asarray(w, np.float32)
    k = w.shape[-2]
    nb = -(-k // block)
    pad = nb * block - k
    if pad:
        wp = np.concatenate(
            [w, np.zeros(w.shape[:-2] + (pad, w.shape[-1]), np.float32)],
            axis=-2)
    else:
        wp = w
    blocks = wp.reshape(wp.shape[:-2] + (nb, block, wp.shape[-1]))
    s = (np.abs(blocks).max(axis=-2) / 127.0).astype(np.float32)
    safe = np.where(s > 0, s, 1.0).astype(np.float32)
    q = np.clip(np.rint(blocks / safe[..., None, :]), -127, 127)
    q = q.astype(np.int8).reshape(wp.shape)[..., :k, :]
    return q, s


def _round(v, rng):
    """Round-to-nearest, or unbiased stochastic floor(v + u) when a
    rng is supplied."""
    if rng is None:
        return jnp.rint(v)
    u = jax.random.uniform(rng, v.shape, jnp.float32)
    return jnp.floor(v + u)


def quantize_kernel_int8(w, block, rng=None, values_dtype=jnp.int8):
    """Traced twin of `quantize_kernel_int8_np`: [.., K, N] ->
    (q [.., nb*block, N] in `values_dtype`, scales fp32 [.., nb, N]).
    K is REALLY padded here (the consumer contracts over nb*block);
    pass values_dtype=float32 on the XLA fallback to skip the int8
    round trip (values are exact small integers either way)."""
    w = w.astype(jnp.float32)
    k = w.shape[-2]
    nb = -(-k // block)
    pad = nb * block - k
    if pad:
        w = jnp.concatenate(
            [w, jnp.zeros(w.shape[:-2] + (pad, w.shape[-1]),
                          jnp.float32)], axis=-2)
    blocks = w.reshape(w.shape[:-2] + (nb, block, w.shape[-1]))
    s = jnp.max(jnp.abs(blocks), axis=-2) / 127.0
    safe = jnp.where(s > 0, s, 1.0)
    q = jnp.clip(_round(blocks / safe[..., None, :], rng), -127, 127)
    q = q.astype(values_dtype).reshape(w.shape)
    return q, safe.astype(jnp.float32)


def quantize_rows_int8(x, rng=None, values_dtype=jnp.int8):
    """Per-row (per-token) activation quantization: [.., K] ->
    (q [.., K] in `values_dtype`, scales fp32 [.., 1])."""
    x = x.astype(jnp.float32)
    s = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0
    safe = jnp.where(s > 0, s, 1.0)
    q = jnp.clip(_round(x / safe, rng), -127, 127)
    return q.astype(values_dtype), safe.astype(jnp.float32)


def dequantize_kernel(q, scales, block, k=None, dtype=jnp.float32):
    """(q [.., K', N], scales [.., nb, N]) -> dequantized [.., k, N]
    (k defaults to K' = whatever the quantizer produced)."""
    kp = q.shape[-2]
    nb = scales.shape[-2]
    pad = nb * block - kp
    if pad > 0:
        q = jnp.concatenate(
            [q, jnp.zeros(q.shape[:-2] + (pad, q.shape[-1]), q.dtype)],
            axis=-2)
    blocks = q.reshape(q.shape[:-2] + (nb, block, q.shape[-1]))
    deq = blocks.astype(jnp.float32) * scales[..., None, :]
    deq = deq.reshape(deq.shape[:-3] + (nb * block, deq.shape[-1]))
    return deq[..., :k if k is not None else kp, :].astype(dtype)


# ----------------------------------------------------------------------
# weight-only epilogue (the serving family; inference/quant.py
# re-exports this under its legacy name)
# ----------------------------------------------------------------------
def int8_matmul(x, q, scales, block, out_dtype):
    """The weight-only dequant-in-matmul epilogue: x [.., T, K] @ int8
    q [K, N] with per-(block, column) scales [nb, N] -> [.., T, N] in
    out_dtype. Contraction runs per block in out_dtype with the scale
    applied to each block's partial sum — the int8 weights are never
    materialised in full precision."""
    k = x.shape[-1]
    nb = scales.shape[-2]
    pad = nb * block - k
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros(x.shape[:-1] + (pad,), x.dtype)], axis=-1)
        q = jnp.concatenate(
            [q, jnp.zeros((pad, q.shape[-1]), q.dtype)], axis=0)
    xb = x.reshape(x.shape[:-1] + (nb, block)).astype(out_dtype)
    qb = q.reshape(nb, block, q.shape[-1]).astype(out_dtype)
    part = jnp.einsum("...bk,bkn->...bn", xb, qb)
    return (part * scales.astype(out_dtype)).sum(axis=-2)


# ----------------------------------------------------------------------
# quantized-compute GEMM: int8 x int8 with the dequant in the epilogue
# ----------------------------------------------------------------------
def _qmm_kernel(xq_ref, wq_ref, sx_ref, sw_ref, out_ref, acc_scr, *,
                nb, out_dtype):
    """One (bm, bn) output tile, K innermost: int8 tiles contract on
    the MXU into int32, each K-block's partial is scaled by its weight
    block-column scale into the fp32 accumulator, and the epilogue
    applies the per-row activation scale on the single output write."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    part = jax.lax.dot_general(
        xq_ref[...], wq_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    acc_scr[...] += part.astype(jnp.float32) * sw_ref[...]

    @pl.when(k == nb - 1)
    def _():
        out_ref[...] = (acc_scr[...] * sx_ref[...]).astype(out_dtype)


def _qmm_pallas(xq, wq, sx, sw, block, out_dtype, block_m, block_n,
                interpret):
    """[M, Kp] int8 @ [Kp, N] int8 via the Pallas epilogue kernel.
    Kp = nb*block (pre-padded by the quantizers); M/N pad here."""
    m, kp = xq.shape
    n = wq.shape[-1]
    nb = kp // block
    mp = -(-m // block_m) * block_m
    np_ = -(-n // block_n) * block_n
    if mp != m:
        xq = jnp.pad(xq, ((0, mp - m), (0, 0)))
        sx = jnp.pad(sx, ((0, mp - m), (0, 0)), constant_values=1.0)
    if np_ != n:
        wq = jnp.pad(wq, ((0, 0), (0, np_ - n)))
        sw = jnp.pad(sw, ((0, 0), (0, np_ - n)), constant_values=1.0)
    kwargs = dict(
        grid=(mp // block_m, np_ // block_n, nb),
        in_specs=[
            pl.BlockSpec((block_m, block), lambda i, j, k: (i, k)),
            pl.BlockSpec((block, block_n), lambda i, j, k: (k, j)),
            pl.BlockSpec((block_m, 1), lambda i, j, k: (i, 0)),
            pl.BlockSpec((1, block_n), lambda i, j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n),
                               lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        interpret=interpret)
    if _COMPILER_PARAMS is not None:
        kwargs["compiler_params"] = _COMPILER_PARAMS
    kernel = functools.partial(_qmm_kernel, nb=nb, out_dtype=out_dtype)
    try:
        out = pl.pallas_call(kernel, name="quantized_matmul",
                             **kwargs)(xq, wq, sx, sw)
    except TypeError:   # older pallas without the name kwarg
        out = pl.pallas_call(kernel, **kwargs)(xq, wq, sx, sw)
    return out[:m, :n]


def _resolve_impl(impl):
    """impl -> (use_pallas, interpret)."""
    if impl in ("auto", None):
        return (True, False) if _on_tpu() else (False, False)
    if impl == "pallas":
        return True, False
    if impl == "interpret":
        return True, True
    if impl == "xla":
        return False, False
    raise ValueError(
        f"impl={impl!r}: expected 'auto', 'pallas', 'xla' or "
        "'interpret'")


def _qmm_blocks(m, k, n, dtype, block_m, block_n):
    """Tile sizes: explicit args win, then the autotune table, then
    the hand-picked 256/256."""
    if block_m is not None and block_n is not None:
        return int(block_m), int(block_n)
    from deepspeed_tpu.ops import autotune
    tuned = autotune.qmm_blocks(m, k, n, dtype)
    if tuned is not None:
        return tuned
    return 256, 256


def quantized_matmul(x, wq, sw, *, block, out_dtype=None, x_rng=None,
                     impl="auto", block_m=None, block_n=None):
    """x [.., K] (any float dtype) @ PRE-quantized weights
    (wq [nb*block or K, N] int8-valued, sw [nb, N]) -> [.., N].

    Quantizes the activations per row on the fly (stochastically when
    x_rng is given) and runs the int8xint8 dequant-epilogue GEMM: the
    Pallas kernel on TPU (block_m/block_n from the autotune table
    unless passed), the exact-integer fp32 fallback elsewhere. This is
    the forward core `quantized_dense` differentiates through."""
    out_dtype = np.dtype(out_dtype) if out_dtype is not None \
        else x.dtype
    use_pallas, interpret = _resolve_impl(impl)
    k = x.shape[-1]
    n = wq.shape[-1]
    nb = sw.shape[-2]
    kp = nb * block
    lead = x.shape[:-1]
    m = int(np.prod(lead)) if lead else 1
    with jax.named_scope("quantized_matmul"):
        vdt = jnp.int8 if use_pallas else jnp.float32
        xq, sx = quantize_rows_int8(x.reshape(m, k), rng=x_rng,
                                    values_dtype=vdt)
        if kp != k:
            xq = jnp.pad(xq, ((0, 0), (0, kp - k)))
        if wq.shape[-2] != kp:
            wq = jnp.pad(wq, ((0, kp - wq.shape[-2]), (0, 0)))
        if use_pallas:
            bm, bn = _qmm_blocks(m, k, n, out_dtype, block_m, block_n)
            out = _qmm_pallas(xq.astype(jnp.int8),
                              wq.astype(jnp.int8), sx, sw, block,
                              out_dtype, bm, bn, interpret)
        else:
            # fallback: dequantized operands, ONE fp32 GEMM. Integer
            # values <= 127 and their block sums are exact in fp32, so
            # this reproduces the kernel's numerics to fp32 roundoff.
            wd = dequantize_kernel(wq, sw, block)
            out = ((xq.astype(jnp.float32) @ wd) * sx).astype(out_dtype)
        return out.reshape(lead + (n,))


def _zeros_ct(x):
    """Zero cotangent matching x's tangent type (float0 for ints/keys,
    zeros for inexact) — the stage3 `_zeros_ct` convention for inputs
    whose gradient is discarded by construction (the rng)."""
    from jax import dtypes
    dtype = np.result_type(getattr(x, "dtype", np.float32))
    if np.issubdtype(dtype, np.inexact):
        return jnp.zeros(np.shape(x), dtype)
    return np.zeros(np.shape(x), dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _qdense(x, w, rng, block, out_dtype, sr, impl):
    wq, sw = quantize_kernel_int8(
        w, block, rng=rng if sr else None,
        values_dtype=jnp.int8 if _resolve_impl(impl)[0]
        else jnp.float32)
    return quantized_matmul(
        x, wq, sw, block=block, out_dtype=out_dtype,
        x_rng=jax.random.fold_in(rng, 1) if sr else None, impl=impl)


def _qdense_fwd(x, w, rng, block, out_dtype, sr, impl):
    # residuals are the RAW operands (aliased, no extra memory); the
    # backward re-derives W_eff by re-quantizing deterministically
    return _qdense(x, w, rng, block, out_dtype, sr, impl), (x, w, rng)


def _qdense_bwd(block, out_dtype, sr, impl, res, g):
    x, w, rng = res
    # straight-through: forward y = x_q @ W_eff; backward treats both
    # quantizations as identity around the dequantized weights
    wq, sw = quantize_kernel_int8(w, block,
                                  rng=rng if sr else None,
                                  values_dtype=jnp.float32)
    w_eff = dequantize_kernel(wq, sw, block, k=w.shape[-2],
                              dtype=x.dtype)
    gc = g.astype(x.dtype)
    dx = jnp.einsum("...n,kn->...k", gc, w_eff)
    dw = jnp.einsum("...k,...n->kn", x.astype(jnp.float32),
                    g.astype(jnp.float32)).astype(w.dtype)
    return dx.astype(x.dtype), dw, _zeros_ct(rng)


_qdense.defvjp(_qdense_fwd, _qdense_bwd)


def quantized_dense(x, kernel, *, block=DEFAULT_QUANT_BLOCK,
                    out_dtype=None, stochastic_rounding=False,
                    rng=None, impl="auto"):
    """y = x @ kernel with the int8 quantized-compute forward and a
    straight-through backward — the training entry point (the third
    fused-ops epilogue family).

    kernel [K, N] is quantized per-(K-block, N-column) INSIDE the
    trace (fresh every step — the weights move); x quantizes per row.
    `block` must be a multiple of 128 on the Pallas path (int8 lane
    tiling); any positive block works on the XLA fallback.
    stochastic_rounding rounds both quantizations stochastically when
    `rng` is provided (the engine's per-step "quant" stream); without
    a rng it falls back to round-to-nearest."""
    if block <= 0:
        raise ValueError(f"quantized_compute block must be > 0, "
                         f"got {block}")
    use_pallas, _ = _resolve_impl(impl)
    if use_pallas and block % 128:
        raise ValueError(
            f"quantized_compute block must be a multiple of 128 on "
            f"the Pallas path (int8 lane tiling), got {block}; use "
            f"impl='xla' for finer blocks")
    out_dtype = np.dtype(out_dtype) if out_dtype is not None \
        else x.dtype
    sr = bool(stochastic_rounding) and rng is not None
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return _qdense(x, kernel, rng, int(block), out_dtype, sr, impl)


def bf16_fallback_matmul(x, kernel, *, out_dtype=None,
                         stochastic_rounding=False, rng=None):
    """The backward-compatible fallback when quantized compute
    resolves OFF: a plain compute-dtype GEMM, bit-for-bit today's
    path — unless stochastic_rounding is on AND a rng is supplied, in
    which case the fp32->bf16 operand casts round stochastically
    (unbiased) instead of truncating."""
    out_dtype = np.dtype(out_dtype) if out_dtype is not None \
        else x.dtype
    if stochastic_rounding and rng is not None and \
            out_dtype == np.dtype(jnp.bfloat16):
        from deepspeed_tpu.runtime.bf16_optimizer import \
            stochastic_round_bf16
        r1, r2 = jax.random.split(rng)
        x = stochastic_round_bf16(x.astype(jnp.float32), r1)
        kernel = stochastic_round_bf16(kernel.astype(jnp.float32), r2)
    y = jax.lax.dot_general(
        x.astype(out_dtype), kernel.astype(out_dtype),
        (((x.ndim - 1,), (0,)), ((), ())))
    return y
