"""Flash attention as a Pallas TPU kernel.

TPU-native replacement for the reference's fused CUDA attention chain
(`csrc/transformer/softmax_kernels.cu`, `strided_batch_gemm.h`,
`ds_transformer_cuda.cpp:1026-1044`): instead of materializing the
[B, H, T, T] score tensor in HBM, the kernel streams K/V blocks through
VMEM with an online-softmax running (m, l) pair, so HBM traffic is
O(T·d) and the MXU sees back-to-back [block_q, d]×[d, block_k] matmuls.

Layout: [B, T, H, D] in/out (the model's native layout); the kernel grid
is (B·H, T/block_q, T/block_k) with K innermost so the (m, l, acc)
scratch carries across K blocks.  Backward is the standard two-kernel
flash backward (dKV sweep + dQ sweep) off saved logsumexp rows — the
reference instead checkpoints 17 intermediate activations
(`ops/transformer/transformer.py:155-213`).

Head packing (d = 64).  The MXU contracts 128 elements per pass, so a
d=64 attention runs its QK^T at K=64 (half the systolic rows idle) and
its PV at N=64 (half the lanes idle) — measured ~5 TF on a 197 TF chip
(VERDICT r5).  With `head_packing` the kernel processes TWO heads per
grid step in a feature-packed layout [rows, T, 128] (adjacent B·H rows
pair up; an odd B·H count pads one zero row that is sliced off):

    Qp  = [q0 | q1]                          [bq, 128]   (dense)
    Kbd = [[k0 | 0], [0 | k1]]               [2·bk, 128] (block diagonal)
    S   = Qp · Kbdᵀ = [S0 | S1]              [bq, 2·bk]  K=128 contraction
    O   = P · Vbd   = [O0 | O1]              [bq, 128]   N=128 lanes

The zero blocks double the MAC count per useful flop, but every matmul
now runs at full MXU occupancy — a win whenever K=64 throughput is
below half of K=128 throughput (it is far below on v5e).  The zero
lanes contribute exact +0 to every fp32 partial sum, so packed and
unpacked results agree bit-for-bit under a deterministic backend.  The
backward's dV/dK contractions come out row-stacked ([2·bk, 128] with
the useful blocks on the diagonal) and are folded back with a lane
select.  `head_packing="auto"` packs on real TPU for d=64; the CPU
interpreter path, d ≠ 64, and `"off"` use the unpacked kernel.

Ring-attention partial merge.  `flash_attention_merge` fuses the ring
step's (out, lse) softmax-partial merge into the kernel epilogue: the
previous partial rides in as two extra refs and the merged result is
written directly, so the per-step partial never round-trips HBM through
an XLA elementwise merge chain (`ops/sequence/ring_attention.py`).

On non-TPU backends the same kernels run in Pallas interpreter mode so
CPU CI validates kernel logic bit-for-bit against the XLA reference path.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# The online softmax runs in log2 space: exp2 is the TPU VPU's native
# transcendental (jnp.exp lowers to exp2(x·log2e) anyway), so folding
# log2e into the QK^T scale removes one vmul per score element per
# pass — the softmax VPU chain is a first-order term at d=64, where
# the MXU work per score element is small. LSE is saved in log2 space;
# both backward kernels consume it there.
LOG2E = 1.4426950408889634
_LN2 = 0.6931471805599453
# Block sizes swept on v5e at the flagship shape (B8 T1024 H25 d64,
# round 4): 1024/1024 beats 512/512 by ~3.5% fwd+bwd and — decisively —
# makes T<=1024 a SINGLE tile, which routes the backward through the
# fused one-pass kernel below (no second s/p/dp recompute sweep). For
# longer T the per-call min(block, T) keeps tiles at 1024.
_DEFAULT_BLOCK = 1024
# Heads processed per grid step.  At short T the grid is overhead-bound
# (each step's matmuls are microseconds), so batching heads into one
# step cuts the iteration count G-fold; VMEM cost is G * block_q *
# block_k fp32 for the score tile (the pallas calls raise the Mosaic
# scoped-vmem ceiling to make the fatter tiles legal).
_DEFAULT_HEAD_GROUP = 8
_VMEM_LIMIT = 100 * 1024 * 1024
# CompilerParams was TPUCompilerParams before jax 0.6 (same fields)
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))
_COMPILER_PARAMS = _CompilerParams(vmem_limit_bytes=_VMEM_LIMIT)


def _on_tpu():
    return jax.default_backend() == "tpu"


def dense_attention(q, k, v, mask=None, causal=False, sm_scale=None,
                    dropout_rate=0.0, dropout_rng=None, deterministic=True):
    """Dense XLA attention over [B, T, H, D] — the reference path for the
    flash kernel and the fallback when dropout/masks rule it out.
    fp32 softmax; `mask` is additive, broadcastable to [B, H, Tq, Tk]."""
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    scores = scores * sm_scale
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        tri = jnp.tril(jnp.ones((t_q, t_k), dtype=bool))
        scores = jnp.where(tri[None, None, :, :], scores, jnp.float32(-1e30))
    if mask is not None:
        scores = scores + mask.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1)
    if not deterministic and dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _fit_block(block, t):
    """Largest power-of-two shrink of `block` (floor 128) that divides
    t, after clamping to t — so T=1536 gets 512-wide tiles instead of
    failing the 1024 default."""
    block = min(block, t)
    while block > 128 and t % block:
        block //= 2
    return block


def flash_attention_usable(q, no_dropout: bool,
                           block_q=None, block_k=None):
    """The kernel handles [B, T, H, D] with T divisible by the block size
    and D a lane-friendly multiple of 64; dropout stays on the XLA path."""
    if not no_dropout:
        return False
    if q.ndim != 4:
        return False
    t, d = q.shape[1], q.shape[3]
    block_q = _fit_block(block_q or _DEFAULT_BLOCK, t)
    block_k = _fit_block(block_k or _DEFAULT_BLOCK, t)
    # t % 128 guards the lane dimension: _fit_block clamps the block to
    # t for 128 <= t < 1024, so without it a T like 136 would "fit" its
    # own single tile — unaligned lanes Mosaic rejects or pads on real
    # TPU (CPU interpret mode hides it).
    return t % block_q == 0 and t % block_k == 0 and d % 64 == 0 and \
        t >= 128 and t % 128 == 0


def _resolve_head_packing(head_packing, d, interpret):
    """Head-packing mode -> bool.  "auto" packs d=64 heads pairwise on
    real TPU (K=128 contractions); the interpreter path stays unpacked
    so CPU CI timings/VMEM budgets reflect the per-head kernel unless a
    test forces "packed".  Odd B·H counts are handled by a one-row zero
    pad, NOT a fallback — the flagship's 11×25 = 275 rows still pack."""
    if head_packing in ("off", False, 0):
        return False
    if head_packing in ("packed", True, 1):
        if d != 64:
            raise ValueError(
                f"head_packing='packed' requires head_dim 64 (got {d}): "
                "packing pairs two 64-wide heads into one K=128 "
                "contraction")
        return True
    if head_packing in ("auto", None):
        return d == 64 and not interpret
    raise ValueError(
        f"head_packing={head_packing!r}: expected 'auto', 'packed' or "
        "'off'")


# ----------------------------------------------------------------------
# packed-layout helpers
# ----------------------------------------------------------------------
def _pack_pairs(x):
    """[rows, T, d] -> [ceil(rows/2), T, 2·d]: adjacent rows pair up
    feature-wise (row 2i in lanes [:d], row 2i+1 in lanes [d:]); an odd
    row count pads one zero row.  Also packs [rows, T, 1] lse/delta
    columns into [pairs, T, 2]."""
    rows, t, d = x.shape
    if rows % 2:
        x = jnp.concatenate([x, jnp.zeros((1, t, d), x.dtype)], axis=0)
    pairs = (rows + 1) // 2
    return x.reshape(pairs, 2, t, d).transpose(0, 2, 1, 3) \
        .reshape(pairs, t, 2 * d)


def _unpack_pairs(x, rows):
    """Inverse of `_pack_pairs`, slicing off the odd-count pad row."""
    pairs, t, dd = x.shape
    d = dd // 2
    x = x.reshape(pairs, t, 2, d).transpose(0, 2, 1, 3) \
        .reshape(2 * pairs, t, d)
    return x[:rows]


def _block_diag_pack(x, half):
    """[G, n, 2h] -> [G, 2n, 2h] block-diagonal stack: rows [:n] keep
    the first head's lanes ([x0 | 0]), rows [n:] the second's
    ([0 | x1]).  The zero blocks are what buy the K=128 contraction;
    they contribute exact +0 to every fp32 partial sum."""
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
    zero = jnp.zeros_like(x)
    top = jnp.where(lane < half, x, zero)
    bot = jnp.where(lane < half, zero, x)
    return jnp.concatenate([top, bot], axis=1)


def _block_diag_fold(x, half, n):
    """Fold a row-stacked [G, 2n, 2h] cross-product back to the packed
    [G, n, 2h] layout: the useful blocks sit on the block diagonal
    (top-left for head 0, bottom-right for head 1); the off-diagonal
    blocks are cross-head garbage the lane select drops."""
    top = x[:, :n]
    bot = x[:, n:]
    lane = jax.lax.broadcasted_iota(jnp.int32, top.shape, top.ndim - 1)
    return jnp.where(lane < half, top, bot)


def _halves(a, b, half):
    """Broadcast two per-head row stats [G, bq, 1] into the packed
    [G, bq, 2·half] lane layout (first half holds a, second b)."""
    shape = a.shape[:-1] + (half,)
    return jnp.concatenate([jnp.broadcast_to(a, shape),
                            jnp.broadcast_to(b, shape)], axis=-1)


def _two_cols(x, half):
    """Collapse a half-broadcast [G, bq, 2·half] stat to its two
    representative columns [G, bq, 2]."""
    return jnp.concatenate([x[:, :, :1], x[:, :, half:half + 1]], axis=-1)


def _mask_causal(s, causal, qi, ki, block_q, block_k):
    """Apply the causal mask to a score block.

    Unconditional by design: gating the mask behind a value-returning
    `lax.cond` on "does this block straddle the diagonal" was measured
    SLOWER in the forward kernel (interleaved A/B on v5e at the
    flagship shape: up to +26% fwd) — Mosaic serializes around the
    branched tile and loses more than the iota/compare/select chain
    costs. Blocks fully above the diagonal never reach here (the
    `visible` guard skips their matmuls entirely)."""
    if not causal:
        return s
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where((rows >= cols)[None], s, NEG_INF)


def _mask_causal_packed(s, causal, qi, ki, block_q, block_k):
    """Causal mask over a packed [G, bq, 2·bk] score tile: columns
    [:bk] and [bk:] carry the SAME key positions (one per head), so the
    key index is the column index modulo bk."""
    if not causal:
        return s
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 2 * block_k), 0)
    col = jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 2 * block_k), 1)
    key = ki * block_k + jnp.where(col >= block_k, col - block_k, col)
    return jnp.where((rows >= key)[None], s, NEG_INF)


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, *rest, sm_scale, causal,
                block_q, block_k, merge):
    if merge:
        (po_ref, plse_ref, o_ref, lse_ref, lse_n_ref,
         m_scr, l_scr, acc_scr) = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: a K block strictly above the diagonal contributes nothing —
    # skip its matmuls entirely (the grid still visits it).
    visible = True
    if causal:
        visible = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(visible)
    def _():
        q = q_ref[...]                            # [G, bq, d] native dtype
        k = k_ref[...]                            # [G, bk, d]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * (sm_scale * LOG2E)
        s = _mask_causal(s, causal, qi, ki, block_q, block_k)

        m_prev = m_scr[:, :, :1]                   # [G, bq, 1]
        l_prev = l_scr[:, :, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp2(s - m_new)                    # [G, bq, bk]
        alpha = jnp.exp2(m_prev - m_new)           # [G, bq, 1]
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

        v = v_ref[...]                             # [G, bk, d]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)    # [G, bq, d]
        acc_scr[...] = acc_scr[...] * alpha + pv
        m_scr[:, :, :1] = m_new
        l_scr[:, :, :1] = l_new

    @pl.when(ki == nk - 1)
    def _():
        m = m_scr[:, :, :1]
        l = l_scr[:, :, :1]
        # log2-space LSE (= natural lse · log2e); consumed only by the
        # backward kernels, which stay in the same space
        lse_n = m + jnp.log2(l)
        if merge:
            # in-kernel softmax-partial merge: fold the previous ring
            # partial into this pass's (m, l, acc) before the single
            # HBM write (ops/sequence/ring_attention.py)
            plse = plse_ref[...]                   # [G, bq, 1]
            mm = jnp.maximum(lse_n, plse)
            w_p = jnp.exp2(plse - mm)
            # w_n/ l == exp2(m - mm): acc is unnormalized, so its merge
            # weight folds the 1/l normalization in
            wsum = w_p + jnp.exp2(lse_n - mm)
            out = (po_ref[...] * w_p +
                   acc_scr[...] * jnp.exp2(m - mm)) / wsum
            o_ref[...] = out.astype(o_ref.dtype)
            lse_ref[...] = mm + jnp.log2(wsum)
            lse_n_ref[...] = lse_n
        else:
            o_ref[...] = (acc_scr[...] / l).astype(o_ref.dtype)
            lse_ref[...] = lse_n


def _fwd_kernel_packed(q_ref, k_ref, v_ref, *rest, sm_scale, causal,
                       block_q, block_k, merge):
    """Two heads per grid step in the feature-packed layout: the QK^T
    contraction runs at K=128 and PV at N=128 (see module docstring).
    m/l scratch is half-broadcast-stored ([G, bq, 128] with each head's
    stat replicated across its 64 lanes) so alpha/l apply to the packed
    acc with plain elementwise ops."""
    if merge:
        (po_ref, plse_ref, o_ref, lse_ref, lse_n_ref,
         m_scr, l_scr, acc_scr) = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    half = q_ref.shape[-1] // 2

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    visible = True
    if causal:
        visible = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(visible)
    def _():
        q = q_ref[...]                             # [G, bq, 128]
        k = k_ref[...]                             # [G, bk, 128]
        kbd = _block_diag_pack(k, half)            # [G, 2bk, 128]
        s = jax.lax.dot_general(
            q, kbd, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * (sm_scale * LOG2E)
        s = _mask_causal_packed(s, causal, qi, ki, block_q, block_k)

        s0 = s[:, :, :block_k]
        s1 = s[:, :, block_k:]
        m_prev = m_scr[...]                        # [G, bq, 128]
        l_prev = l_scr[...]
        m_cur = _halves(jnp.max(s0, axis=-1, keepdims=True),
                        jnp.max(s1, axis=-1, keepdims=True), half)
        m_new = jnp.maximum(m_prev, m_cur)
        p0 = jnp.exp2(s0 - m_new[:, :, :1])
        p1 = jnp.exp2(s1 - m_new[:, :, half:half + 1])
        alpha = jnp.exp2(m_prev - m_new)
        l_new = alpha * l_prev + _halves(
            jnp.sum(p0, axis=-1, keepdims=True),
            jnp.sum(p1, axis=-1, keepdims=True), half)

        v = v_ref[...]
        vbd = _block_diag_pack(v, half)            # [G, 2bk, 128]
        p = jnp.concatenate([p0, p1], axis=-1)     # [G, bq, 2bk]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), vbd, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)    # [G, bq, 128]
        acc_scr[...] = acc_scr[...] * alpha + pv
        m_scr[...] = m_new
        l_scr[...] = l_new

    @pl.when(ki == nk - 1)
    def _():
        m = m_scr[...]
        l = l_scr[...]
        lse_n = m + jnp.log2(l)                    # half-broadcast
        if merge:
            plse = plse_ref[...]                   # [G, bq, 2]
            plse_b = _halves(plse[:, :, :1], plse[:, :, 1:2], half)
            mm = jnp.maximum(lse_n, plse_b)
            w_p = jnp.exp2(plse_b - mm)
            wsum = w_p + jnp.exp2(lse_n - mm)
            out = (po_ref[...] * w_p +
                   acc_scr[...] * jnp.exp2(m - mm)) / wsum
            o_ref[...] = out.astype(o_ref.dtype)
            lse_ref[...] = _two_cols(mm + jnp.log2(wsum), half)
            lse_n_ref[...] = _two_cols(lse_n, half)
        else:
            o_ref[...] = (acc_scr[...] / l).astype(o_ref.dtype)
            lse_ref[...] = _two_cols(lse_n, half)


def _head_group(bh, block_q, block_k, d, tile_budget=8 * 1024 * 1024):
    """Largest head-group G (≤ default) dividing B·H, with the fp32 score
    tile capped to `tile_budget` bytes of VMEM (the backward kernels keep
    ~4 score-sized tiles live, so they pass a smaller budget)."""
    g = _DEFAULT_HEAD_GROUP
    cap = max(1, tile_budget // (block_q * block_k * 4))
    g = min(g, cap)
    while bh % g:
        g -= 1
    return max(g, 1)


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret, pack,
         prev=None):
    """Forward launcher.  Returns (out [bh, t, d], lse [bh, t, 1]); with
    `prev = (prev_out [B,T,H,D], prev_lse [B,H,T,1])` the kernel merges
    the prior softmax partial in its epilogue and additionally returns
    the CURRENT partial's lse_n [bh, t, 1] (the backward residual)."""
    b, t, h, d = q.shape
    bh = b * h
    merge = prev is not None

    # [B, T, H, D] -> [B*H, T, D]
    def to_bht(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, t, d)
    qt, kt, vt = to_bht(q), to_bht(k), to_bht(v)
    if merge:
        prev_out, prev_lse = prev
        pot = to_bht(prev_out.astype(jnp.float32))
        plse = prev_lse.astype(jnp.float32).reshape(bh, t, 1)

    if pack:
        qt, kt, vt = _pack_pairs(qt), _pack_pairs(kt), _pack_pairs(vt)
        if merge:
            pot, plse = _pack_pairs(pot), _pack_pairs(plse)
    rows = qt.shape[0]                    # bh, or padded pair count
    dl = qt.shape[-1]                     # d, or 2·d packed
    lanes = 2 if pack else 1              # lse columns per row

    # 8 MB score-tile budget. A 24 MB budget (g=5 at the flagship
    # shape) measures ~20% faster on the ISOLATED kernel chain but ~1%
    # slower inside the full train step (VMEM pressure against the
    # surrounding fusions) — keep the in-model winner.  The packed tile
    # is [bq, 2·bk], so the same budget halves G there.
    g = _head_group(rows, block_q, (2 if pack else 1) * block_k, dl)
    nq, nk = t // block_q, t // block_k
    grid = (rows // g, nq, nk)
    kernel_fn = _fwd_kernel_packed if pack else _fwd_kernel
    kernel = functools.partial(kernel_fn, sm_scale=sm_scale,
                               causal=causal, block_q=block_q,
                               block_k=block_k, merge=merge)

    def q_spec(width):
        return pl.BlockSpec((g, block_q, width),
                            lambda bhi, qi, ki: (bhi, qi, 0))

    kv_spec = pl.BlockSpec((g, block_k, dl),
                           lambda bhi, qi, ki: (bhi, ki, 0))
    in_specs = [q_spec(dl), kv_spec, kv_spec]
    operands = [qt, kt, vt]
    out_specs = [q_spec(dl), q_spec(lanes)]
    out_shape = [
        jax.ShapeDtypeStruct((rows, t, dl),
                             jnp.float32 if merge else q.dtype),
        jax.ShapeDtypeStruct((rows, t, lanes), jnp.float32),
    ]
    if merge:
        in_specs += [q_spec(dl), q_spec(lanes)]
        operands += [pot, plse]
        out_specs.append(q_spec(lanes))
        out_shape.append(
            jax.ShapeDtypeStruct((rows, t, lanes), jnp.float32))
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        compiler_params=_COMPILER_PARAMS,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((g, block_q, max(dl, 128)), jnp.float32),
            pltpu.VMEM((g, block_q, max(dl, 128)), jnp.float32),
            pltpu.VMEM((g, block_q, dl), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    if pack:
        outs = [_unpack_pairs(o, bh) for o in outs]
    return tuple(outs) if merge else (outs[0], outs[1])


# ----------------------------------------------------------------------
# backward
# ----------------------------------------------------------------------
def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale, causal,
                    block_q, block_k):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    visible = True
    if causal:
        visible = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(visible)
    def _():
        q = q_ref[...]                             # [G, bq, d] native dtype
        k = k_ref[...]                             # [G, bk, d]
        v = v_ref[...]
        do = do_ref[...]                           # [G, bq, d]
        lse = lse_ref[...]                         # [G, bq, 1]
        delta = delta_ref[...]                     # [G, bq, 1]

        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * (sm_scale * LOG2E)
        s = _mask_causal(s, causal, qi, ki, block_q, block_k)
        p = jnp.exp2(s - lse)                      # [G, bq, bk]

        # dV += Pᵀ dO
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        # dP = dO Vᵀ ; dS = P ⊙ (dP − δ) · scale
        dp = jax.lax.dot_general(
            do, v, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        # dK += dSᵀ Q
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr, *, sm_scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    visible = True
    if causal:
        visible = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(visible)
    def _():
        q = q_ref[...]                             # [G, bq, d]
        k = k_ref[...]                             # [G, bk, d]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...]
        delta = delta_ref[...]

        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * (sm_scale * LOG2E)
        s = _mask_causal(s, causal, qi, ki, block_q, block_k)
        p = jnp.exp2(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        # dQ += dS K
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[...] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, *, sm_scale, causal,
                      block_q, block_k):
    """Single-tile backward (T == block): s, p and dP exist once, so
    dQ, dK and dV all come out of ONE pass — the two-kernel flash
    backward recomputes s/p (and dP) in each sweep, paying ~2x the
    matmul+exp work at tiles the VMEM can hold whole."""
    q = q_ref[...]
    k = k_ref[...]
    v = v_ref[...]
    do = do_ref[...]
    lse = lse_ref[...]
    delta = delta_ref[...]

    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * (sm_scale * LOG2E)
    s = _mask_causal(s, causal, 0, 0, block_q, block_k)
    p = jnp.exp2(s - lse)
    dv_ref[...] = jax.lax.dot_general(
        p.astype(do.dtype), do, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dp = jax.lax.dot_general(
        do, v, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * sm_scale
    dk_ref[...] = jax.lax.dot_general(
        ds.astype(q.dtype), q, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)
    dq_ref[...] = jax.lax.dot_general(
        ds.astype(k.dtype), k, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)


def _packed_p_ds(q, k, v, do, lse, delta, half, sm_scale, causal, qi, ki,
                 block_q, block_k):
    """Shared packed-backward front half: recompute P and dS for a
    [G, bq, 2·bk] tile at K=128 contractions.  Returns (p, ds, kbd)."""
    kbd = _block_diag_pack(k, half)                # [G, 2bk, 128]
    s = jax.lax.dot_general(
        q, kbd, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * (sm_scale * LOG2E)
    s = _mask_causal_packed(s, causal, qi, ki, block_q, block_k)
    p0 = jnp.exp2(s[:, :, :block_k] - lse[:, :, :1])
    p1 = jnp.exp2(s[:, :, block_k:] - lse[:, :, 1:2])
    p = jnp.concatenate([p0, p1], axis=-1)         # [G, bq, 2bk]
    vbd = _block_diag_pack(v, half)
    dp = jax.lax.dot_general(
        do, vbd, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)        # [G, bq, 2bk]
    ds0 = p0 * (dp[:, :, :block_k] - delta[:, :, :1]) * sm_scale
    ds1 = p1 * (dp[:, :, block_k:] - delta[:, :, 1:2]) * sm_scale
    ds = jnp.concatenate([ds0, ds1], axis=-1)
    return p, ds, kbd


def _bwd_dkv_kernel_packed(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale,
                           causal, block_q, block_k):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    half = q_ref.shape[-1] // 2

    @pl.when(qi == 0)
    def _():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    visible = True
    if causal:
        visible = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(visible)
    def _():
        q = q_ref[...]
        do = do_ref[...]
        p, ds, _ = _packed_p_ds(q, k_ref[...], v_ref[...], do,
                                lse_ref[...], delta_ref[...], half,
                                sm_scale, causal, qi, ki, block_q,
                                block_k)
        # dV/dK come out row-stacked [G, 2bk, 128] with the useful
        # blocks on the block diagonal (K=bq, N=128 contractions)
        dv_stack = jax.lax.dot_general(
            p.astype(do.dtype), do, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        dv_scr[...] += _block_diag_fold(dv_stack, half, block_k)
        dk_stack = jax.lax.dot_general(
            ds.astype(q.dtype), q, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        dk_scr[...] += _block_diag_fold(dk_stack, half, block_k)

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_dq_kernel_packed(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dq_ref, dq_scr, *, sm_scale, causal, block_q,
                          block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    half = q_ref.shape[-1] // 2

    @pl.when(ki == 0)
    def _():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    visible = True
    if causal:
        visible = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(visible)
    def _():
        k = k_ref[...]
        _, ds, kbd = _packed_p_ds(q_ref[...], k, v_ref[...], do_ref[...],
                                  lse_ref[...], delta_ref[...], half,
                                  sm_scale, causal, qi, ki, block_q,
                                  block_k)
        # dQ += dS Kbd: [G, bq, 2bk] x [G, 2bk, 128] (K=2bk, N=128); the
        # block-diagonal zeros route each half's keys to its own lanes
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), kbd, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[...] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_fused_kernel_packed(q_ref, k_ref, v_ref, do_ref, lse_ref,
                             delta_ref, dq_ref, dk_ref, dv_ref, *,
                             sm_scale, causal, block_q, block_k):
    """Packed single-tile backward: one pass for dQ/dK/dV at K=128
    contractions (see `_bwd_fused_kernel`)."""
    half = q_ref.shape[-1] // 2
    q = q_ref[...]
    k = k_ref[...]
    do = do_ref[...]
    p, ds, kbd = _packed_p_ds(q, k, v_ref[...], do, lse_ref[...],
                              delta_ref[...], half, sm_scale, causal,
                              0, 0, block_q, block_k)
    dv_stack = jax.lax.dot_general(
        p.astype(do.dtype), do, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    dv_ref[...] = _block_diag_fold(dv_stack, half, block_k) \
        .astype(dv_ref.dtype)
    dk_stack = jax.lax.dot_general(
        ds.astype(q.dtype), q, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    dk_ref[...] = _block_diag_fold(dk_stack, half, block_k) \
        .astype(dk_ref.dtype)
    dq_ref[...] = jax.lax.dot_general(
        ds.astype(k.dtype), kbd, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, interpret, res, g,
         dlse=None, pack=False, delta=None):
    """dlse: optional [bh, t, 1] cotangent of the (log2-space) LSE
    output. ∂lse/∂s_scaled = p·log2e, so the lse path contributes
    ds += p·log2e·dlse — algebraically a shift of δ:
    ds = p·(dp − (δ − log2e·dlse))·scale. The kernels stay unchanged;
    only the δ row vector moves.

    delta: optional precomputed δ = rowsum(dO ⊙ O) [bh, t, 1] — the
    merged ring backward derives it from merge weights without ever
    materializing the per-step partial out (res[3] may then be None)."""
    q, k, v, out, lse = res
    b, t, h, d = q.shape
    bh = b * h

    def to_bht(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, t, d)

    def from_bht(x):
        return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    qt, kt, vt, dot_ = to_bht(q), to_bht(k), to_bht(v), to_bht(g)
    if delta is None:
        ot = to_bht(out)
        # δ = rowsum(dO ⊙ O) — computed by XLA (one fused
        # elementwise+reduce)
        delta = jnp.sum(dot_.astype(jnp.float32) * ot.astype(jnp.float32),
                        axis=-1, keepdims=True)    # [bh, t, 1]
    if dlse is not None:
        delta = delta - LOG2E * dlse.astype(jnp.float32)

    if pack:
        qt, kt, vt, dot_ = map(_pack_pairs, (qt, kt, vt, dot_))
        lse_in = _pack_pairs(lse)
        delta_in = _pack_pairs(delta)
    else:
        lse_in, delta_in = lse, delta
    rows = qt.shape[0]
    dl = qt.shape[-1]
    lanes = 2 if pack else 1
    score_k = (2 if pack else 1) * block_k

    def unpack(x):
        return from_bht(_unpack_pairs(x, bh) if pack else x)

    nq, nk = t // block_q, t // block_k

    if nq == 1 and nk == 1:
        # whole sequence in one tile: fused one-pass backward (~4
        # score-sized fp32 tiles live: s, p, dp, ds). Bigger budgets
        # win on the isolated kernel but lose inside the full step —
        # see the forward's budget note.
        gf = _head_group(rows, block_q, score_k, dl,
                         tile_budget=4 * 1024 * 1024)
        fused = functools.partial(
            _bwd_fused_kernel_packed if pack else _bwd_fused_kernel,
            sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k)
        specs = pl.BlockSpec((gf, t, dl), lambda i: (i, 0, 0))
        row_spec = pl.BlockSpec((gf, t, lanes), lambda i: (i, 0, 0))
        dq, dk, dv = pl.pallas_call(
            fused,
            grid=(rows // gf,),
            compiler_params=_COMPILER_PARAMS,
            in_specs=[specs, specs, specs, specs, row_spec, row_spec],
            out_specs=[specs, specs, specs],
            out_shape=[jax.ShapeDtypeStruct((rows, t, dl), q.dtype),
                       jax.ShapeDtypeStruct((rows, t, dl), k.dtype),
                       jax.ShapeDtypeStruct((rows, t, dl), v.dtype)],
            interpret=interpret,
        )(qt, kt, vt, dot_, lse_in, delta_in)
        return unpack(dq), unpack(dk), unpack(dv)

    gg = _head_group(rows, block_q, score_k, dl,
                     tile_budget=2 * 1024 * 1024)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel_packed if pack else _bwd_dkv_kernel,
        sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(rows // gg, nk, nq),
        compiler_params=_COMPILER_PARAMS,
        in_specs=[
            pl.BlockSpec((gg, block_q, dl),
                         lambda bhi, ki, qi: (bhi, qi, 0)),
            pl.BlockSpec((gg, block_k, dl),
                         lambda bhi, ki, qi: (bhi, ki, 0)),
            pl.BlockSpec((gg, block_k, dl),
                         lambda bhi, ki, qi: (bhi, ki, 0)),
            pl.BlockSpec((gg, block_q, dl),
                         lambda bhi, ki, qi: (bhi, qi, 0)),
            pl.BlockSpec((gg, block_q, lanes),
                         lambda bhi, ki, qi: (bhi, qi, 0)),
            pl.BlockSpec((gg, block_q, lanes),
                         lambda bhi, ki, qi: (bhi, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((gg, block_k, dl),
                         lambda bhi, ki, qi: (bhi, ki, 0)),
            pl.BlockSpec((gg, block_k, dl),
                         lambda bhi, ki, qi: (bhi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, t, dl), k.dtype),
            jax.ShapeDtypeStruct((rows, t, dl), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((gg, block_k, dl), jnp.float32),
            pltpu.VMEM((gg, block_k, dl), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot_, lse_in, delta_in)

    dq_kernel = functools.partial(
        _bwd_dq_kernel_packed if pack else _bwd_dq_kernel,
        sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(rows // gg, nq, nk),
        compiler_params=_COMPILER_PARAMS,
        in_specs=[
            pl.BlockSpec((gg, block_q, dl),
                         lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((gg, block_k, dl),
                         lambda bhi, qi, ki: (bhi, ki, 0)),
            pl.BlockSpec((gg, block_k, dl),
                         lambda bhi, qi, ki: (bhi, ki, 0)),
            pl.BlockSpec((gg, block_q, dl),
                         lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((gg, block_q, lanes),
                         lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((gg, block_q, lanes),
                         lambda bhi, qi, ki: (bhi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((gg, block_q, dl),
                               lambda bhi, qi, ki: (bhi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, t, dl), q.dtype),
        scratch_shapes=[pltpu.VMEM((gg, block_q, dl), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot_, lse_in, delta_in)

    return unpack(dq), unpack(dk), unpack(dv)


# ----------------------------------------------------------------------
# public API
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret, pack):
    out, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                  pack)
    b, t, h, d = q.shape
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
               pack):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k,
                    interpret, pack)
    b, t, h, d = q.shape
    out_bthd = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return out_bthd, (q, k, v, out_bthd, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, interpret, pack, res,
               g):
    return _bwd(sm_scale, causal, block_q, block_k, interpret, res, g,
                pack=pack)


_flash.defvjp(_flash_fwd, _flash_bwd)


# ----------------------------------------------------------------------
# (out, lse) form: differentiable partials for ring attention
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_lse(q, k, v, sm_scale, causal, block_q, block_k, interpret,
               pack):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k,
                    interpret, pack)
    b, t, h, d = q.shape
    return (out.reshape(b, h, t, d).transpose(0, 2, 1, 3),
            lse.reshape(b, h, t, 1))


def _flash_lse_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                   pack):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k,
                    interpret, pack)
    b, t, h, d = q.shape
    out_bthd = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return (out_bthd, lse.reshape(b, h, t, 1)), (q, k, v, out_bthd, lse)


def _flash_lse_bwd(sm_scale, causal, block_q, block_k, interpret, pack,
                   res, g):
    g_out, g_lse = g
    b = res[0].shape[0]
    h = res[0].shape[2]
    t = res[0].shape[1]
    return _bwd(sm_scale, causal, block_q, block_k, interpret, res, g_out,
                dlse=g_lse.reshape(b * h, t, 1), pack=pack)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_with_lse(q, k, v, causal=True, sm_scale=None,
                             block_q=None, block_k=None,
                             interpret=None, head_packing="auto"):
    """Flash attention returning (out [B,T,H,D], lse [B,H,T,1]).

    The LSE is in LOG2 space (m + log2(l) over log2e-scaled scores, the
    kernel's native convention). Two partials over disjoint key sets
    merge exactly as m = max(lse1, lse2); w_i = exp2(lse_i − m);
    out = (out1·w1 + out2·w2)/(w1+w2); lse = m + log2(w1+w2) — the
    ring-attention per-step merge (ops/sequence/ring_attention.py,
    which fuses that merge into the kernel epilogue via
    `flash_attention_merge`). Fully differentiable: the lse cotangent
    enters the backward kernels as a δ shift (see _bwd)."""
    args = _normalize_flash_args(q, k, v, causal, sm_scale, block_q,
                                 block_k, interpret, head_packing)
    return _flash_lse(q, k, v, *args)


# ----------------------------------------------------------------------
# in-kernel merge with a prior partial: the ring-attention step body
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_merge(q, k, v, prev_out, prev_lse, sm_scale, causal, block_q,
                 block_k, interpret, pack):
    out, lse, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k,
                       interpret, pack, prev=(prev_out, prev_lse))
    b, t, h, d = q.shape
    return (out.reshape(b, h, t, d).transpose(0, 2, 1, 3),
            lse.reshape(b, h, t, 1))


def _flash_merge_fwd(q, k, v, prev_out, prev_lse, sm_scale, causal,
                     block_q, block_k, interpret, pack):
    out, lse, lse_n = _fwd(q, k, v, sm_scale, causal, block_q, block_k,
                           interpret, pack, prev=(prev_out, prev_lse))
    b, t, h, d = q.shape
    out_bthd = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    lse_m = lse.reshape(b, h, t, 1)
    return (out_bthd, lse_m), (q, k, v, prev_out, prev_lse, out_bthd,
                               lse_m, lse_n)


def _flash_merge_bwd(sm_scale, causal, block_q, block_k, interpret, pack,
                     res, g):
    """VJP of merge(flash(q,k,v), prev).  With a_p = w_p/W =
    2^(lse_p − lse_m) and a_n = w_n/W = 2^(lse_n − lse_m) (a_p+a_n = 1):

        d o_p   = ḡ_o · a_p            d o_n = ḡ_o · a_n
        d lse_p = ln2·a_p·(R_p − R_m) + ḡ_l·a_p
        d lse_n = ln2·a_p·(R_m − R_p) + ḡ_l·a_n
        δ_n     = Σ_d(d o_n ⊙ o_n) = R_m − a_p·R_p

    where R_x = Σ_d(ḡ_o ⊙ o_x).  Every quantity uses only the SAVED
    o_p/o_m/lses — the current partial o_n is never reconstructed (a
    naive o_n = (o_m·W − w_p·o_p)/w_n divides by a possibly-underflowed
    w_n).  δ_n and d lse_n then drive the standard flash backward
    kernels directly (res out=None, delta= precomputed)."""
    q, k, v, prev_out, prev_lse, out_m, lse_m, lse_n = res
    g_out, g_lse = g
    b, t, h, d = q.shape
    bh = b * h

    def bhq1_to_bqh1(x):
        return x.transpose(0, 2, 1, 3)

    go = g_out.astype(jnp.float32)
    a_p = jnp.exp2(prev_lse.astype(jnp.float32) - lse_m)   # [B,H,T,1]
    a_n = jnp.exp2(lse_n.reshape(b, h, t, 1) - lse_m)

    def rowsum(x, y):            # [B,T,H,D] ⊙ [B,T,H,D] -> [B,H,T,1]
        return jnp.sum(x * y.astype(jnp.float32), axis=-1,
                       keepdims=True).transpose(0, 2, 1, 3)

    r_m = rowsum(go, out_m)
    r_p = rowsum(go, prev_out)
    d_prev_out = go * bhq1_to_bqh1(a_p)
    d_o_n = g_out * bhq1_to_bqh1(a_n).astype(g_out.dtype)
    d_prev_lse = _LN2 * a_p * (r_p - r_m) + g_lse * a_p
    d_lse_n = _LN2 * a_p * (r_m - r_p) + g_lse * a_n
    delta_n = r_m - a_p * r_p

    dq, dk, dv = _bwd(
        sm_scale, causal, block_q, block_k, interpret,
        (q, k, v, None, lse_n), d_o_n,
        dlse=d_lse_n.reshape(bh, t, 1), pack=pack,
        delta=delta_n.reshape(bh, t, 1))
    return dq, dk, dv, d_prev_out, d_prev_lse


_flash_merge.defvjp(_flash_merge_fwd, _flash_merge_bwd)


def flash_attention_merge(q, k, v, prev_out, prev_lse, causal=True,
                          sm_scale=None, block_q=None,
                          block_k=None, interpret=None,
                          head_packing="auto"):
    """Flash attention over one KV block, merged IN THE KERNEL EPILOGUE
    with a prior softmax partial over a disjoint key set.

    prev_out [B,T,H,D] (any float dtype; promoted to fp32) and prev_lse
    [B,H,T,1] (log2 space, NEG_INF rows = empty partial) are the running
    ring-attention carry; returns the merged (out fp32 [B,T,H,D],
    lse [B,H,T,1]).  Equivalent to `flash_attention_with_lse` followed
    by the two-partial merge formula, but the per-step partial never
    round-trips HBM through an XLA elementwise chain — the kernel folds
    the previous carry into its epilogue write
    (`ops/sequence/ring_attention.py` is the caller).  Differentiable
    in q, k, v, prev_out and prev_lse."""
    args = _normalize_flash_args(q, k, v, causal, sm_scale, block_q,
                                 block_k, interpret, head_packing)
    return _flash_merge(q, k, v, prev_out.astype(jnp.float32),
                        prev_lse, *args)


# ----------------------------------------------------------------------
# remat-friendly form: never re-run the forward kernel in backward
# ----------------------------------------------------------------------
# Under `jax.checkpoint`, a custom_vjp op is atomic: the backward pass
# re-runs its FORWARD to regenerate residuals, so rematted transformer
# blocks pay the (expensive) flash forward kernel twice.
# The split below routes the residuals AROUND the remat boundary:
#
#     out, lse = _flash_outlse(q, k, v)      # fwd kernel, NOT differentiable
#     out = checkpoint_name(out, "attn_out") # 2 B/elem per layer
#     lse = checkpoint_name(lse, "attn_lse") # 4 B/token per layer
#     out = _flash_apply(q, k, v, out, lse)  # identity fwd; custom bwd
#
# With a `save_only_these_names:attn_out,attn_lse` policy the named
# values are saved, `_flash_outlse` is dead in the recompute (its only
# outputs are saved) and never re-runs, while `_flash_apply`'s VJP runs
# the dq/dkv kernels directly from the saved residuals — q, k, v are
# recomputed by the (cheap) qkv-matmul chain remat. Without such a
# policy the behavior degrades gracefully to plain full remat.
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_apply(q, k, v, out, lse, sm_scale, causal, block_q, block_k,
                 interpret, pack):
    return out


def _flash_apply_fwd(q, k, v, out, lse, sm_scale, causal, block_q,
                     block_k, interpret, pack):
    return out, (q, k, v, out, lse)


def _flash_apply_bwd(sm_scale, causal, block_q, block_k, interpret, pack,
                     res, g):
    dq, dk, dv = _bwd(sm_scale, causal, block_q, block_k, interpret,
                      res, g, pack=pack)
    # out/lse enter via the non-differentiable forward kernel (gradient
    # flows exclusively through q, k, v — mathematically out = f(q,k,v))
    return dq, dk, dv, jnp.zeros_like(res[3]), jnp.zeros_like(res[4])


_flash_apply.defvjp(_flash_apply_fwd, _flash_apply_bwd)


def _normalize_flash_args(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret, head_packing="auto"):
    """Shared argument validation/defaulting for all flash entry
    points — they must never diverge (the rematerializable form
    guarantees identical numerics)."""
    assert q.shape == k.shape == v.shape, (q.shape, k.shape, v.shape)
    t = q.shape[1]
    if block_q is None and block_k is None:
        # caller did not pick tiles (None is the sentinel — an
        # EXPLICIT 1024/1024 stays 1024/1024): consult the autotune
        # table (a pure host-side dict lookup at trace time; returns
        # only divisors of t, validated on load), else the
        # hand-picked default.
        from deepspeed_tpu.ops import autotune
        if interpret is None:
            _interp_probe = not _on_tpu()
        else:
            _interp_probe = bool(interpret)
        _pack_probe = _resolve_head_packing(head_packing, q.shape[-1],
                                            _interp_probe)
        tuned = autotune.flash_blocks(t, q.shape[-1], bool(causal),
                                      _pack_probe, q.dtype)
        if tuned is not None:
            block_q, block_k = tuned
    block_q = _DEFAULT_BLOCK if block_q is None else block_q
    block_k = _DEFAULT_BLOCK if block_k is None else block_k
    block_q = _fit_block(block_q, t)
    block_k = _fit_block(block_k, t)
    assert t % block_q == 0 and t % block_k == 0, (
        f"seq_len {t} must divide by block sizes ({block_q}, {block_k}); "
        "pad the sequence or pass smaller block_q/block_k")
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    if interpret is None:
        interpret = not _on_tpu()
    pack = _resolve_head_packing(head_packing, q.shape[-1],
                                 bool(interpret))
    return (float(sm_scale), bool(causal), int(block_q), int(block_k),
            bool(interpret), pack)


def flash_attention_rematerializable(q, k, v, causal=True, sm_scale=None,
                                     block_q=None,
                                     block_k=None,
                                     interpret=None, head_packing="auto"):
    """flash_attention whose (out, lse) carry checkpoint_name
    annotations ("attn_out"/"attn_lse") so a names-saving remat policy
    skips the forward-kernel re-run in backward. Numerics identical to
    `flash_attention`."""
    from jax.ad_checkpoint import checkpoint_name
    b, t, h, d = q.shape
    args = _normalize_flash_args(q, k, v, causal, sm_scale, block_q,
                                 block_k, interpret, head_packing)

    out, lse = _fwd(jax.lax.stop_gradient(q), jax.lax.stop_gradient(k),
                    jax.lax.stop_gradient(v), *args)
    out = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    out = checkpoint_name(out, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return _flash_apply(q, k, v, out, lse, *args)


def flash_attention(q, k, v, causal=True, sm_scale=None,
                    block_q=None, block_k=None,
                    interpret=None, head_packing="auto"):
    """Flash attention over [B, T, H, D] tensors; returns [B, T, H, D].

    interpret=None auto-selects Pallas interpreter mode off-TPU so the
    same kernel code is exercised by CPU tests.  head_packing
    ("auto"|"packed"|"off") selects the two-heads-per-step K=128 kernel
    for d=64 (auto: on real TPU only; packed/off force it on/off; see
    module docstring).
    """
    return _flash(q, k, v, *_normalize_flash_args(
        q, k, v, causal, sm_scale, block_q, block_k, interpret,
        head_packing))
