"""Flash attention as a Pallas TPU kernel.

TPU-native replacement for the reference's fused CUDA attention chain
(`csrc/transformer/softmax_kernels.cu`, `strided_batch_gemm.h`,
`ds_transformer_cuda.cpp:1026-1044`): instead of materializing the
[B, H, T, T] score tensor in HBM, the kernel streams K/V blocks through
VMEM with an online-softmax running (m, l) pair, so HBM traffic is
O(T·d) and the MXU sees back-to-back [block_q, d]×[d, block_k] matmuls.

Layout: [B, T, H, D] in/out (the model's native layout); the kernel grid
is (B·H, T/block_q, T/block_k) with K innermost so the (m, l, acc)
scratch carries across K blocks.  Backward is the standard two-kernel
flash backward (dKV sweep + dQ sweep) off saved logsumexp rows — the
reference instead checkpoints 17 intermediate activations
(`ops/transformer/transformer.py:155-213`).

On non-TPU backends the same kernels run in Pallas interpreter mode so
CPU CI validates kernel logic bit-for-bit against the XLA reference path.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# The online softmax runs in log2 space: exp2 is the TPU VPU's native
# transcendental (jnp.exp lowers to exp2(x·log2e) anyway), so folding
# log2e into the QK^T scale removes one vmul per score element per
# pass — the softmax VPU chain is a first-order term at d=64, where
# the MXU work per score element is small. LSE is saved in log2 space;
# both backward kernels consume it there.
LOG2E = 1.4426950408889634
# Block sizes swept on v5e at the flagship shape (B8 T1024 H25 d64,
# round 4): 1024/1024 beats 512/512 by ~3.5% fwd+bwd and — decisively —
# makes T<=1024 a SINGLE tile, which routes the backward through the
# fused one-pass kernel below (no second s/p/dp recompute sweep). For
# longer T the per-call min(block, T) keeps tiles at 1024.
_DEFAULT_BLOCK = 1024
# Heads processed per grid step.  At short T the grid is overhead-bound
# (each step's matmuls are microseconds), so batching heads into one
# step cuts the iteration count G-fold; VMEM cost is G * block_q *
# block_k fp32 for the score tile (the pallas calls raise the Mosaic
# scoped-vmem ceiling to make the fatter tiles legal).
_DEFAULT_HEAD_GROUP = 8
_VMEM_LIMIT = 100 * 1024 * 1024
# CompilerParams was TPUCompilerParams before jax 0.6 (same fields)
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))
_COMPILER_PARAMS = _CompilerParams(vmem_limit_bytes=_VMEM_LIMIT)


def _on_tpu():
    return jax.default_backend() == "tpu"


def dense_attention(q, k, v, mask=None, causal=False, sm_scale=None,
                    dropout_rate=0.0, dropout_rng=None, deterministic=True):
    """Dense XLA attention over [B, T, H, D] — the reference path for the
    flash kernel and the fallback when dropout/masks rule it out.
    fp32 softmax; `mask` is additive, broadcastable to [B, H, Tq, Tk]."""
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    scores = scores * sm_scale
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        tri = jnp.tril(jnp.ones((t_q, t_k), dtype=bool))
        scores = jnp.where(tri[None, None, :, :], scores, jnp.float32(-1e30))
    if mask is not None:
        scores = scores + mask.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1)
    if not deterministic and dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _fit_block(block, t):
    """Largest power-of-two shrink of `block` (floor 128) that divides
    t, after clamping to t — so T=1536 gets 512-wide tiles instead of
    failing the 1024 default."""
    block = min(block, t)
    while block > 128 and t % block:
        block //= 2
    return block


def flash_attention_usable(q, no_dropout: bool,
                           block_q=_DEFAULT_BLOCK, block_k=_DEFAULT_BLOCK):
    """The kernel handles [B, T, H, D] with T divisible by the block size
    and D a lane-friendly multiple of 64; dropout stays on the XLA path."""
    if not no_dropout:
        return False
    if q.ndim != 4:
        return False
    t, d = q.shape[1], q.shape[3]
    block_q = _fit_block(block_q, t)
    block_k = _fit_block(block_k, t)
    # t % 128 guards the lane dimension: _fit_block clamps the block to
    # t for 128 <= t < 1024, so without it a T like 136 would "fit" its
    # own single tile — unaligned lanes Mosaic rejects or pads on real
    # TPU (CPU interpret mode hides it).
    return t % block_q == 0 and t % block_k == 0 and d % 64 == 0 and \
        t >= 128 and t % 128 == 0


def _mask_causal(s, causal, qi, ki, block_q, block_k):
    """Apply the causal mask to a score block.

    Unconditional by design: gating the mask behind a value-returning
    `lax.cond` on "does this block straddle the diagonal" was measured
    SLOWER in the forward kernel (interleaved A/B on v5e at the
    flagship shape: up to +26% fwd) — Mosaic serializes around the
    branched tile and loses more than the iota/compare/select chain
    costs. Blocks fully above the diagonal never reach here (the
    `visible` guard skips their matmuls entirely)."""
    if not causal:
        return s
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where((rows >= cols)[None], s, NEG_INF)


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, sm_scale, causal,
                block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: a K block strictly above the diagonal contributes nothing —
    # skip its matmuls entirely (the grid still visits it).
    visible = True
    if causal:
        visible = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(visible)
    def _():
        q = q_ref[...]                            # [G, bq, d] native dtype
        k = k_ref[...]                            # [G, bk, d]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * (sm_scale * LOG2E)
        s = _mask_causal(s, causal, qi, ki, block_q, block_k)

        m_prev = m_scr[:, :, :1]                   # [G, bq, 1]
        l_prev = l_scr[:, :, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp2(s - m_new)                    # [G, bq, bk]
        alpha = jnp.exp2(m_prev - m_new)           # [G, bq, 1]
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

        v = v_ref[...]                             # [G, bk, d]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)    # [G, bq, d]
        acc_scr[...] = acc_scr[...] * alpha + pv
        m_scr[:, :, :1] = m_new
        l_scr[:, :, :1] = l_new

    @pl.when(ki == nk - 1)
    def _():
        l = l_scr[:, :, :1]
        o_ref[...] = (acc_scr[...] / l).astype(o_ref.dtype)
        # log2-space LSE (= natural lse · log2e); consumed only by the
        # backward kernels, which stay in the same space
        lse_ref[...] = m_scr[:, :, :1] + jnp.log2(l)


def _head_group(bh, block_q, block_k, d, tile_budget=8 * 1024 * 1024):
    """Largest head-group G (≤ default) dividing B·H, with the fp32 score
    tile capped to `tile_budget` bytes of VMEM (the backward kernels keep
    ~4 score-sized tiles live, so they pass a smaller budget)."""
    g = _DEFAULT_HEAD_GROUP
    cap = max(1, tile_budget // (block_q * block_k * 4))
    g = min(g, cap)
    while bh % g:
        g -= 1
    return max(g, 1)


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    b, t, h, d = q.shape
    bh = b * h
    # [B, T, H, D] -> [B*H, T, D]
    def to_bht(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, t, d)
    qt, kt, vt = to_bht(q), to_bht(k), to_bht(v)

    # 8 MB score-tile budget. A 24 MB budget (g=5 at the flagship
    # shape) measures ~20% faster on the ISOLATED kernel chain but ~1%
    # slower inside the full train step (VMEM pressure against the
    # surrounding fusions) — keep the in-model winner.
    g = _head_group(bh, block_q, block_k, d)
    nq, nk = t // block_q, t // block_k
    grid = (bh // g, nq, nk)
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, block_q=block_q,
                               block_k=block_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        compiler_params=_COMPILER_PARAMS,
        in_specs=[
            pl.BlockSpec((g, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((g, block_k, d), lambda bhi, qi, ki: (bhi, ki, 0)),
            pl.BlockSpec((g, block_k, d), lambda bhi, qi, ki: (bhi, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((g, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((g, block_q, 1), lambda bhi, qi, ki: (bhi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, block_q, 128), jnp.float32),
            pltpu.VMEM((g, block_q, 128), jnp.float32),
            pltpu.VMEM((g, block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out, lse


# ----------------------------------------------------------------------
# backward
# ----------------------------------------------------------------------
def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale, causal,
                    block_q, block_k):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    visible = True
    if causal:
        visible = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(visible)
    def _():
        q = q_ref[...]                             # [G, bq, d] native dtype
        k = k_ref[...]                             # [G, bk, d]
        v = v_ref[...]
        do = do_ref[...]                           # [G, bq, d]
        lse = lse_ref[...]                         # [G, bq, 1]
        delta = delta_ref[...]                     # [G, bq, 1]

        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * (sm_scale * LOG2E)
        s = _mask_causal(s, causal, qi, ki, block_q, block_k)
        p = jnp.exp2(s - lse)                      # [G, bq, bk]

        # dV += Pᵀ dO
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        # dP = dO Vᵀ ; dS = P ⊙ (dP − δ) · scale
        dp = jax.lax.dot_general(
            do, v, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        # dK += dSᵀ Q
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr, *, sm_scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    visible = True
    if causal:
        visible = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(visible)
    def _():
        q = q_ref[...]                             # [G, bq, d]
        k = k_ref[...]                             # [G, bk, d]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...]
        delta = delta_ref[...]

        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * (sm_scale * LOG2E)
        s = _mask_causal(s, causal, qi, ki, block_q, block_k)
        p = jnp.exp2(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        # dQ += dS K
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[...] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, *, sm_scale, causal,
                      block_q, block_k):
    """Single-tile backward (T == block): s, p and dP exist once, so
    dQ, dK and dV all come out of ONE pass — the two-kernel flash
    backward recomputes s/p (and dP) in each sweep, paying ~2x the
    matmul+exp work at tiles the VMEM can hold whole."""
    q = q_ref[...]
    k = k_ref[...]
    v = v_ref[...]
    do = do_ref[...]
    lse = lse_ref[...]
    delta = delta_ref[...]

    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * (sm_scale * LOG2E)
    s = _mask_causal(s, causal, 0, 0, block_q, block_k)
    p = jnp.exp2(s - lse)
    dv_ref[...] = jax.lax.dot_general(
        p.astype(do.dtype), do, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dp = jax.lax.dot_general(
        do, v, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * sm_scale
    dk_ref[...] = jax.lax.dot_general(
        ds.astype(q.dtype), q, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)
    dq_ref[...] = jax.lax.dot_general(
        ds.astype(k.dtype), k, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, interpret, res, g,
         dlse=None):
    """dlse: optional [bh, t, 1] cotangent of the (log2-space) LSE
    output. ∂lse/∂s_scaled = p·log2e, so the lse path contributes
    ds += p·log2e·dlse — algebraically a shift of δ:
    ds = p·(dp − (δ − log2e·dlse))·scale. The kernels stay unchanged;
    only the δ row vector moves."""
    q, k, v, out, lse = res
    b, t, h, d = q.shape
    bh = b * h

    def to_bht(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, t, d)

    def from_bht(x):
        return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    qt, kt, vt, dot_ = to_bht(q), to_bht(k), to_bht(v), to_bht(g)
    ot = to_bht(out)
    # δ = rowsum(dO ⊙ O) — computed by XLA (one fused elementwise+reduce)
    delta = jnp.sum(dot_.astype(jnp.float32) * ot.astype(jnp.float32),
                    axis=-1, keepdims=True)        # [bh, t, 1]
    if dlse is not None:
        delta = delta - LOG2E * dlse.astype(jnp.float32)

    nq, nk = t // block_q, t // block_k

    if nq == 1 and nk == 1:
        # whole sequence in one tile: fused one-pass backward (~4
        # score-sized fp32 tiles live: s, p, dp, ds). Bigger budgets
        # win on the isolated kernel but lose inside the full step —
        # see the forward's budget note.
        gf = _head_group(bh, block_q, block_k, d,
                         tile_budget=4 * 1024 * 1024)
        fused = functools.partial(
            _bwd_fused_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k)
        specs = pl.BlockSpec((gf, t, d), lambda i: (i, 0, 0))
        row_spec = pl.BlockSpec((gf, t, 1), lambda i: (i, 0, 0))
        dq, dk, dv = pl.pallas_call(
            fused,
            grid=(bh // gf,),
            compiler_params=_COMPILER_PARAMS,
            in_specs=[specs, specs, specs, specs, row_spec, row_spec],
            out_specs=[specs, specs, specs],
            out_shape=[jax.ShapeDtypeStruct((bh, t, d), q.dtype),
                       jax.ShapeDtypeStruct((bh, t, d), k.dtype),
                       jax.ShapeDtypeStruct((bh, t, d), v.dtype)],
            interpret=interpret,
        )(qt, kt, vt, dot_, lse, delta)
        return from_bht(dq), from_bht(dk), from_bht(dv)

    g = _head_group(bh, block_q, block_k, d, tile_budget=2 * 1024 * 1024)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh // g, nk, nq),
        compiler_params=_COMPILER_PARAMS,
        in_specs=[
            pl.BlockSpec((g, block_q, d), lambda bhi, ki, qi: (bhi, qi, 0)),
            pl.BlockSpec((g, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
            pl.BlockSpec((g, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
            pl.BlockSpec((g, block_q, d), lambda bhi, ki, qi: (bhi, qi, 0)),
            pl.BlockSpec((g, block_q, 1), lambda bhi, ki, qi: (bhi, qi, 0)),
            pl.BlockSpec((g, block_q, 1), lambda bhi, ki, qi: (bhi, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((g, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
            pl.BlockSpec((g, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, block_k, d), jnp.float32),
            pltpu.VMEM((g, block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot_, lse, delta)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh // g, nq, nk),
        compiler_params=_COMPILER_PARAMS,
        in_specs=[
            pl.BlockSpec((g, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((g, block_k, d), lambda bhi, qi, ki: (bhi, ki, 0)),
            pl.BlockSpec((g, block_k, d), lambda bhi, qi, ki: (bhi, ki, 0)),
            pl.BlockSpec((g, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((g, block_q, 1), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((g, block_q, 1), lambda bhi, qi, ki: (bhi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((g, block_q, d),
                               lambda bhi, qi, ki: (bhi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((g, block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot_, lse, delta)

    return from_bht(dq), from_bht(dk), from_bht(dv)


# ----------------------------------------------------------------------
# public API
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    b, t, h, d = q.shape
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    b, t, h, d = q.shape
    out_bthd = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return out_bthd, (q, k, v, out_bthd, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    return _bwd(sm_scale, causal, block_q, block_k, interpret, res, g)


_flash.defvjp(_flash_fwd, _flash_bwd)


# ----------------------------------------------------------------------
# (out, lse) form: differentiable partials for ring attention
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    b, t, h, d = q.shape
    return (out.reshape(b, h, t, d).transpose(0, 2, 1, 3),
            lse.reshape(b, h, t, 1))


def _flash_lse_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    b, t, h, d = q.shape
    out_bthd = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return (out_bthd, lse.reshape(b, h, t, 1)), (q, k, v, out_bthd, lse)


def _flash_lse_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    g_out, g_lse = g
    b = res[0].shape[0]
    h = res[0].shape[2]
    t = res[0].shape[1]
    return _bwd(sm_scale, causal, block_q, block_k, interpret, res, g_out,
                dlse=g_lse.reshape(b * h, t, 1))


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_with_lse(q, k, v, causal=True, sm_scale=None,
                             block_q=_DEFAULT_BLOCK, block_k=_DEFAULT_BLOCK,
                             interpret=None):
    """Flash attention returning (out [B,T,H,D], lse [B,H,T,1]).

    The LSE is in LOG2 space (m + log2(l) over log2e-scaled scores, the
    kernel's native convention). Two partials over disjoint key sets
    merge exactly as m = max(lse1, lse2); w_i = exp2(lse_i − m);
    out = (out1·w1 + out2·w2)/(w1+w2); lse = m + log2(w1+w2) — the
    ring-attention per-step merge (ops/sequence/ring_attention.py).
    Fully differentiable: the lse cotangent enters the backward kernels
    as a δ shift (see _bwd)."""
    args = _normalize_flash_args(q, k, v, causal, sm_scale, block_q,
                                 block_k, interpret)
    return _flash_lse(q, k, v, *args)


# ----------------------------------------------------------------------
# remat-friendly form: never re-run the forward kernel in backward
# ----------------------------------------------------------------------
# Under `jax.checkpoint`, a custom_vjp op is atomic: the backward pass
# re-runs its FORWARD to regenerate residuals, so rematted transformer
# blocks pay the (expensive, d=64-starved) flash forward kernel twice.
# The split below routes the residuals AROUND the remat boundary:
#
#     out, lse = _flash_outlse(q, k, v)      # fwd kernel, NOT differentiable
#     out = checkpoint_name(out, "attn_out") # 2 B/elem per layer
#     lse = checkpoint_name(lse, "attn_lse") # 4 B/token per layer
#     out = _flash_apply(q, k, v, out, lse)  # identity fwd; custom bwd
#
# With a `save_only_these_names:attn_out,attn_lse` policy the named
# values are saved, `_flash_outlse` is dead in the recompute (its only
# outputs are saved) and never re-runs, while `_flash_apply`'s VJP runs
# the dq/dkv kernels directly from the saved residuals — q, k, v are
# recomputed by the (cheap) qkv-matmul chain remat. Without such a
# policy the behavior degrades gracefully to plain full remat.
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_apply(q, k, v, out, lse, sm_scale, causal, block_q, block_k,
                 interpret):
    return out


def _flash_apply_fwd(q, k, v, out, lse, sm_scale, causal, block_q,
                     block_k, interpret):
    return out, (q, k, v, out, lse)


def _flash_apply_bwd(sm_scale, causal, block_q, block_k, interpret,
                     res, g):
    dq, dk, dv = _bwd(sm_scale, causal, block_q, block_k, interpret,
                      res, g)
    # out/lse enter via the non-differentiable forward kernel (gradient
    # flows exclusively through q, k, v — mathematically out = f(q,k,v))
    return dq, dk, dv, jnp.zeros_like(res[3]), jnp.zeros_like(res[4])


_flash_apply.defvjp(_flash_apply_fwd, _flash_apply_bwd)


def _normalize_flash_args(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret):
    """Shared argument validation/defaulting for both flash entry
    points — they must never diverge (the rematerializable form
    guarantees identical numerics)."""
    assert q.shape == k.shape == v.shape, (q.shape, k.shape, v.shape)
    t = q.shape[1]
    block_q = _fit_block(block_q, t)
    block_k = _fit_block(block_k, t)
    assert t % block_q == 0 and t % block_k == 0, (
        f"seq_len {t} must divide by block sizes ({block_q}, {block_k}); "
        "pad the sequence or pass smaller block_q/block_k")
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    if interpret is None:
        interpret = not _on_tpu()
    return (float(sm_scale), bool(causal), int(block_q), int(block_k),
            bool(interpret))


def flash_attention_rematerializable(q, k, v, causal=True, sm_scale=None,
                                     block_q=_DEFAULT_BLOCK,
                                     block_k=_DEFAULT_BLOCK,
                                     interpret=None):
    """flash_attention whose (out, lse) carry checkpoint_name
    annotations ("attn_out"/"attn_lse") so a names-saving remat policy
    skips the forward-kernel re-run in backward. Numerics identical to
    `flash_attention`."""
    from jax.ad_checkpoint import checkpoint_name
    b, t, h, d = q.shape
    args = _normalize_flash_args(q, k, v, causal, sm_scale, block_q,
                                 block_k, interpret)

    out, lse = _fwd(jax.lax.stop_gradient(q), jax.lax.stop_gradient(k),
                    jax.lax.stop_gradient(v), *args)
    out = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    out = checkpoint_name(out, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return _flash_apply(q, k, v, out, lse, *args)


def flash_attention(q, k, v, causal=True, sm_scale=None,
                    block_q=_DEFAULT_BLOCK, block_k=_DEFAULT_BLOCK,
                    interpret=None):
    """Flash attention over [B, T, H, D] tensors; returns [B, T, H, D].

    interpret=None auto-selects Pallas interpreter mode off-TPU so the
    same kernel code is exercised by CPU tests.
    """
    return _flash(q, k, v, *_normalize_flash_args(
        q, k, v, causal, sm_scale, block_q, block_k, interpret))
