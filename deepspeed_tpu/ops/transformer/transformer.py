"""DeepSpeedTransformerLayer — the fused BERT-style transformer block.

TPU-native equivalent of the reference's fused CUDA layer
(`deepspeed/ops/transformer/transformer.py:470,39,155` driving
`csrc/transformer/ds_transformer_cuda.cpp`): one flax module whose whole
forward lowers to a single XLA fusion region — QKV projection as one
[H, 3H] matmul, flash-attention Pallas kernel, bias+residual+LayerNorm
fused by XLA, exact-GELU MLP.  The reference's memory-vs-speed flags map
to rematerialisation policies instead of hand-managed workspaces:

  normalize_invertible   → don't save LN inputs; recompute in backward
                           (ref `transformer.py:107-113`)
  attn_dropout_checkpoint→ recompute attention context in backward
                           (ref `transformer.py:121-129`)
  gelu_checkpoint        → recompute the intermediate GELU activation
                           (ref `transformer.py:114-120`)

All three become a single `jax.checkpoint` over the block with a
save-nothing-but-inputs policy when any flag is set — XLA re-derives the
cheapest recompute schedule, which is what the CUDA flags hand-pick.

`stochastic_mode` (ref `op_builder/stochastic_transformer.py`) trades
determinism for ~2% speed on GPU; XLA is deterministic by construction,
so the flag is accepted and ignored.
"""

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class SplitDense(nn.Module):
    """nn.Dense-compatible parameters (same "kernel"/"bias" names,
    shapes and initializers — checkpoints interchange freely) that
    returns `(x @ kernel, bias)` instead of adding the bias, so the
    bias rides a fused epilogue kernel (ops/transformer/fused_ops.py)
    together with the residual/LayerNorm or GeLU that follows."""
    features: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    kernel_init: Any = nn.initializers.lecun_normal()
    bias_init: Any = nn.initializers.zeros

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", self.kernel_init,
                            (x.shape[-1], self.features), self.param_dtype)
        bias = self.param("bias", self.bias_init, (self.features,),
                          self.param_dtype)
        x = x.astype(self.dtype)
        y = jax.lax.dot_general(x, kernel.astype(self.dtype),
                                (((x.ndim - 1,), (0,)), ((), ())))
        return y, bias


class QuantizedDense(nn.Module):
    """nn.Dense/SplitDense-compatible "kernel"/"bias" parameters whose
    forward matmul runs the int8 quantized-compute family
    (ops/transformer/quantized_matmul.py): weights re-quantize
    per-(K-block, N-column) inside every trace, activations quantize
    per row, the MXU contracts int8xint8 and the dequant rides the
    GEMM epilogue; the backward is straight-through in the compute
    dtype.  The parameter tree is IDENTICAL to nn.Dense/SplitDense —
    checkpoints interchange freely and quantized compute can be
    toggled on an existing run.

    split=True returns `(x @ kernel, bias)` (the SplitDense contract,
    so the bias keeps riding a fused epilogue); split=False adds the
    bias like nn.Dense.  Stochastic rounding engages when the caller
    provides a "quant" rng stream (the engine threads one per step);
    without it rounding is to-nearest.

    sr_fallback=True is the backward-compatible bf16 fallback of the
    family (quantized compute configured with stochastic_rounding but
    RESOLVED off on this backend): no int8 quantization — a plain
    compute-dtype GEMM whose fp32->bf16 operand casts round
    stochastically off the same "quant" stream
    (`bf16_fallback_matmul`); without the rng it is bit-for-bit
    nn.Dense/SplitDense."""
    features: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    kernel_init: Any = nn.initializers.lecun_normal()
    bias_init: Any = nn.initializers.zeros
    quant_block: int = 128
    stochastic_rounding: bool = False
    split: bool = False
    quant_impl: str = "auto"
    sr_fallback: bool = False

    @nn.compact
    def __call__(self, x):
        from deepspeed_tpu.ops.transformer.quantized_matmul import (
            bf16_fallback_matmul, quantized_dense)
        kernel = self.param("kernel", self.kernel_init,
                            (x.shape[-1], self.features),
                            self.param_dtype)
        bias = self.param("bias", self.bias_init, (self.features,),
                          self.param_dtype)
        rng = None
        if self.stochastic_rounding and self.has_rng("quant"):
            rng = self.make_rng("quant")
        if self.sr_fallback:
            y = bf16_fallback_matmul(
                x.astype(self.dtype), kernel, out_dtype=self.dtype,
                stochastic_rounding=self.stochastic_rounding, rng=rng)
        else:
            y = quantized_dense(
                x.astype(self.dtype), kernel, block=self.quant_block,
                out_dtype=self.dtype,
                stochastic_rounding=self.stochastic_rounding,
                rng=rng, impl=self.quant_impl)
        if self.split:
            return y, bias
        return y + bias.astype(self.dtype)


class LNParams(nn.Module):
    """LayerNorm-compatible "scale"/"bias" parameters without applying
    the norm — the fused bias+residual+LayerNorm kernel applies it."""
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, features):
        scale = self.param("scale", nn.initializers.ones, (features,),
                           self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (features,),
                          self.param_dtype)
        return scale, bias


def plain_layernorm(x, scale, bias, eps):
    """flax nn.LayerNorm(dtype=fp32) numerics off raw scale/bias params
    (fast-variance formula, variance clamped >= 0 — fp32 roundoff on
    near-constant rows can drive E[x^2]-E[x]^2 negative past eps and
    rsqrt of that is NaN), for the LN applications the fused chain
    does not cover (e.g. the pre-LN block's leading norm).  Same
    formula as fused_ops._ln_stats."""
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.maximum(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) - mu * mu, 0.0)
    return (x32 - mu) * jax.lax.rsqrt(var + eps) * \
        scale.astype(jnp.float32) + bias.astype(jnp.float32)


class DeepSpeedTransformerConfig:
    """Config parity with ref `ops/transformer/transformer.py:39-154`."""

    def __init__(self,
                 batch_size=-1,
                 max_seq_length=-1,
                 hidden_size=-1,
                 intermediate_size=-1,
                 heads=-1,
                 attn_dropout_ratio=-1,
                 hidden_dropout_ratio=-1,
                 num_hidden_layers=-1,
                 initializer_range=-1,
                 local_rank=-1,
                 seed=-1,
                 fp16=False,
                 pre_layer_norm=True,
                 normalize_invertible=False,
                 gelu_checkpoint=False,
                 adjust_init_range=True,
                 attn_dropout_checkpoint=False,
                 stochastic_mode=False,
                 huggingface=False,
                 training=True,
                 bf16=False,
                 layer_norm_eps=1e-12,
                 head_packing="auto",
                 fused_ops="auto",
                 quantized_compute="off",
                 quant_block=128,
                 quant_stochastic_rounding=False):
        self.batch_size = batch_size
        self.max_seq_length = max_seq_length
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size if intermediate_size > 0 \
            else 4 * hidden_size
        self.heads = heads
        self.attn_dropout_ratio = max(attn_dropout_ratio, 0)
        self.hidden_dropout_ratio = max(hidden_dropout_ratio, 0)
        self.num_hidden_layers = num_hidden_layers
        self.initializer_range = initializer_range if initializer_range > 0 \
            else 0.02
        self.local_rank = local_rank
        self.seed = seed
        self.fp16 = fp16
        self.pre_layer_norm = pre_layer_norm
        self.normalize_invertible = normalize_invertible
        self.gelu_checkpoint = gelu_checkpoint
        self.adjust_init_range = adjust_init_range
        self.attn_dropout_checkpoint = attn_dropout_checkpoint
        self.stochastic_mode = stochastic_mode
        self.huggingface = huggingface
        self.training = training
        # TPU-native extension: bf16 compute (the reference is fp16/fp32
        # only; on TPU bf16 is the fast dtype).
        self.bf16 = bf16
        self.layer_norm_eps = layer_norm_eps
        # d=64 head packing in the flash kernel ("auto"|"packed"|"off"):
        # "auto" pairs two heads per grid step on real TPU so the
        # score/output matmuls contract over K=128 instead of running
        # the MXU half-starved at K=64 (flash_attention.py docstring).
        self.head_packing = head_packing
        # Fused non-attention epilogues ("auto"|"on"|"off"): the
        # bias+residual+LayerNorm and bias+GeLU chains run as single
        # Pallas launches with a one-pass custom backward
        # (ops/transformer/fused_ops.py). "auto" fuses on real TPU when
        # hidden dropout is inactive; "on" forces the fused path (XLA
        # fallback off-TPU — same custom VJP, same remat names); the
        # parameter tree is identical either way.
        self.fused_ops = fused_ops
        # int8 quantized-compute projections ("off"|"on"|"auto"): the
        # third epilogue family — forward matmuls contract int8xint8
        # with per-(K-block, column) weight scales and per-row
        # activation scales dequantized in the GEMM epilogue
        # (ops/transformer/quantized_matmul.py), straight-through
        # backward in the compute dtype. "auto" enables on real TPU;
        # the parameter tree is identical either way.
        self.quantized_compute = quantized_compute
        self.quant_block = quant_block
        self.quant_stochastic_rounding = quant_stochastic_rounding

    @classmethod
    def from_dict(cls, json_object):
        import inspect
        known = set(inspect.signature(cls.__init__).parameters) - {"self"}
        config = cls(**{k: v for k, v in json_object.items() if k in known})
        for key, value in json_object.items():
            if key not in known:
                setattr(config, key, value)
        return config

    @property
    def any_checkpointing(self):
        return (self.normalize_invertible or self.gelu_checkpoint or
                self.attn_dropout_checkpoint)


class _TransformerLayerCore(nn.Module):
    """The block body (separate module so remat can wrap it whole)."""
    config: DeepSpeedTransformerConfig
    dtype: Any

    @nn.compact
    def __call__(self, hidden_states, attention_mask, deterministic: bool):
        cfg = self.config
        h = cfg.hidden_size
        nh = cfg.heads
        hd = h // nh
        b, t, _ = hidden_states.shape
        compute_dtype = self.dtype

        init = nn.initializers.normal(cfg.initializer_range)
        # Output-projection init scaled down with depth when
        # adjust_init_range (ref `transformer.py:477-489` "output std dev").
        out_scale = cfg.initializer_range
        if cfg.adjust_init_range and cfg.num_hidden_layers > 0:
            out_scale = cfg.initializer_range / np.sqrt(
                2.0 * cfg.num_hidden_layers)
        out_init = nn.initializers.normal(out_scale)

        from deepspeed_tpu.ops.transformer.quantized_matmul import \
            resolve_quantized_compute
        use_quant = resolve_quantized_compute(cfg.quantized_compute)
        # configured-but-resolved-off + stochastic_rounding: the
        # documented bf16 fallback (plain GEMM, SR operand casts)
        use_sr_fallback = (
            not use_quant and
            cfg.quantized_compute not in ("off", False, 0, None) and
            cfg.quant_stochastic_rounding)

        def dense(features, name, kernel_init=init):
            if use_quant or use_sr_fallback:
                return QuantizedDense(
                    features, dtype=compute_dtype,
                    param_dtype=jnp.float32, kernel_init=kernel_init,
                    quant_block=cfg.quant_block,
                    stochastic_rounding=cfg.quant_stochastic_rounding,
                    sr_fallback=use_sr_fallback, name=name)
            return nn.Dense(features, dtype=compute_dtype,
                            param_dtype=jnp.float32,
                            kernel_init=kernel_init, name=name)

        from deepspeed_tpu.ops.transformer.fused_ops import (
            fused_bias_gelu, fused_bias_residual_layernorm,
            resolve_fused_ops)
        # hidden dropout sits between the bias add and the residual, so
        # the fused chain requires it inactive ("auto" checks exactly
        # this; attention dropout is inside the attention op and does
        # not constrain the epilogues)
        use_fused = resolve_fused_ops(
            cfg.fused_ops,
            deterministic or cfg.hidden_dropout_ratio == 0.0)

        if use_fused:
            ln_attn_p = LNParams(name="attn_layer_norm")(h)
            ln_out_p = LNParams(name="layer_norm")(h)

            def split_dense(features, name, kernel_init=init):
                if use_quant or use_sr_fallback:
                    return QuantizedDense(
                        features, dtype=compute_dtype,
                        param_dtype=jnp.float32,
                        kernel_init=kernel_init,
                        quant_block=cfg.quant_block,
                        stochastic_rounding=cfg
                        .quant_stochastic_rounding,
                        split=True, sr_fallback=use_sr_fallback,
                        name=name)
                return SplitDense(features, dtype=compute_dtype,
                                  param_dtype=jnp.float32,
                                  kernel_init=kernel_init, name=name)
        else:
            ln_attn = nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                                   dtype=jnp.float32,
                                   param_dtype=jnp.float32,
                                   name="attn_layer_norm")
            ln_out = nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                                  dtype=jnp.float32,
                                  param_dtype=jnp.float32,
                                  name="layer_norm")

        # ---- attention ----
        x = hidden_states
        if cfg.pre_layer_norm:
            attn_input = (plain_layernorm(x, *ln_attn_p,
                                          eps=cfg.layer_norm_eps)
                          if use_fused else ln_attn(x)) \
                .astype(compute_dtype)
        else:
            attn_input = x.astype(compute_dtype)
        qkv = dense(3 * h, "attn_qkvw")(attn_input)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, nh, hd)
        k = k.reshape(b, t, nh, hd)
        v = v.reshape(b, t, nh, hd)

        ctx = self._attention(q, k, v, attention_mask, deterministic)
        ctx = ctx.reshape(b, t, h)
        if use_fused:
            attn_y, attn_b = split_dense(h, "attn_ow",
                                         kernel_init=out_init)(ctx)
            if cfg.pre_layer_norm:
                # one launch: attn_ow bias + residual + the MLP's
                # pre-norm; `x` carries on un-normalized
                mlp_input, x = fused_bias_residual_layernorm(
                    attn_y, attn_b, x, *ln_out_p,
                    eps=cfg.layer_norm_eps, out_dtype=compute_dtype,
                    sum_dtype=jnp.result_type(x.dtype, compute_dtype))
            else:
                # post-LN: the normalized sum IS the carry
                # (return_sum=False: single-output primal — no zeros
                # cotangent rides the backward kernel)
                x = fused_bias_residual_layernorm(
                    attn_y, attn_b, x, *ln_attn_p,
                    eps=cfg.layer_norm_eps, out_dtype=jnp.float32,
                    return_sum=False)
                mlp_input = x.astype(compute_dtype)
        else:
            attn_out = dense(h, "attn_ow", kernel_init=out_init)(ctx)
            attn_out = nn.Dropout(cfg.hidden_dropout_ratio)(
                attn_out, deterministic=deterministic)
            x = x + attn_out
            if not cfg.pre_layer_norm:
                x = ln_attn(x)
            mlp_input = (ln_out(x) if cfg.pre_layer_norm else x) \
                .astype(compute_dtype)

        # ---- MLP ----
        if use_fused:
            inter_y, inter_b = split_dense(cfg.intermediate_size,
                                           "inter_w")(mlp_input)
            inter = fused_bias_gelu(inter_y, inter_b, approximate=False,
                                    out_dtype=compute_dtype)
            if cfg.pre_layer_norm:
                mlp_out = dense(h, "output_w",
                                kernel_init=out_init)(inter)
                return x + mlp_out
            mlp_y, mlp_b = split_dense(h, "output_w",
                                       kernel_init=out_init)(inter)
            return fused_bias_residual_layernorm(
                mlp_y, mlp_b, x, *ln_out_p, eps=cfg.layer_norm_eps,
                out_dtype=jnp.float32, return_sum=False)
        inter = dense(cfg.intermediate_size, "inter_w")(mlp_input)
        inter = nn.gelu(inter, approximate=False)
        mlp_out = dense(h, "output_w", kernel_init=out_init)(inter)
        mlp_out = nn.Dropout(cfg.hidden_dropout_ratio)(
            mlp_out, deterministic=deterministic)
        x = x + mlp_out
        if not cfg.pre_layer_norm:
            x = ln_out(x)
        return x

    def _attention(self, q, k, v, attention_mask, deterministic):
        cfg = self.config
        no_drop = deterministic or cfg.attn_dropout_ratio == 0.0
        if attention_mask is None and no_drop:
            from deepspeed_tpu.ops.transformer.flash_attention import (
                flash_attention, flash_attention_usable)
            if flash_attention_usable(q, True):
                return flash_attention(q, k, v, causal=False,
                                       head_packing=cfg.head_packing)
        # XLA path: additive mask ([B, 1, 1, T] or [B, 1, T, T]), fp32
        # softmax — the shape contract of the reference's fused softmax
        # kernel (`csrc/transformer/softmax_kernels.cu`).
        from deepspeed_tpu.ops.transformer.flash_attention import (
            dense_attention)
        drop_rng = None
        if not deterministic and cfg.attn_dropout_ratio > 0.0:
            drop_rng = self.make_rng("dropout")
        return dense_attention(q, k, v, mask=attention_mask,
                               dropout_rate=cfg.attn_dropout_ratio,
                               dropout_rng=drop_rng,
                               deterministic=deterministic)


class DeepSpeedTransformerLayer(nn.Module):
    """Drop-in layer: `layer(hidden_states, attention_mask)` →
    hidden_states (ref `transformer.py:470-614`)."""
    config: DeepSpeedTransformerConfig

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None,
                 deterministic: Optional[bool] = None):
        cfg = self.config
        if deterministic is None:
            deterministic = not cfg.training
        dtype = (jnp.float16 if cfg.fp16 else
                 jnp.bfloat16 if cfg.bf16 else jnp.float32)
        core = _TransformerLayerCore
        if cfg.any_checkpointing:
            # Save only the block inputs; recompute LN/GELU/attention
            # context in the backward pass (the memory the reference's
            # normalize_invertible / gelu_checkpoint /
            # attn_dropout_checkpoint flags reclaim).  With fused ops
            # active, remat is PER-FUSION instead: the
            # save_fused_epilogues policy keeps the fused kernels'
            # named outputs, so the backward recompute skips the
            # attention forward and every fused chain (tuned from the
            # roofline's bytes/flops verdicts —
            # runtime/activation_checkpointing/checkpointing.py).
            from deepspeed_tpu.ops.transformer.fused_ops import \
                resolve_fused_ops
            policy = None
            if resolve_fused_ops(cfg.fused_ops,
                                 deterministic or
                                 cfg.hidden_dropout_ratio == 0.0):
                from deepspeed_tpu.runtime.activation_checkpointing \
                    .checkpointing import resolve_checkpoint_policy
                policy = resolve_checkpoint_policy("save_fused_epilogues")
            core = nn.remat(core, prevent_cse=False, static_argnums=(3,),
                            policy=policy)
        return core(cfg, dtype, name="core")(
            hidden_states, attention_mask, deterministic)
