from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerLayer, DeepSpeedTransformerConfig)
from deepspeed_tpu.ops.transformer.flash_attention import (
    flash_attention, flash_attention_usable)
from deepspeed_tpu.ops.transformer.fused_ops import (
    fused_bias_gelu, fused_bias_residual_layernorm, resolve_fused_ops)
from deepspeed_tpu.ops.transformer.quantized_matmul import (
    quantized_dense, quantized_matmul, resolve_quantized_compute)

__all__ = ["DeepSpeedTransformerLayer", "DeepSpeedTransformerConfig",
           "flash_attention", "flash_attention_usable",
           "fused_bias_gelu", "fused_bias_residual_layernorm",
           "resolve_fused_ops", "quantized_dense", "quantized_matmul",
           "resolve_quantized_compute"]
