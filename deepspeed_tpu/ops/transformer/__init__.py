from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerLayer, DeepSpeedTransformerConfig)
from deepspeed_tpu.ops.transformer.flash_attention import (
    flash_attention, flash_attention_usable)

__all__ = ["DeepSpeedTransformerLayer", "DeepSpeedTransformerConfig",
           "flash_attention", "flash_attention_usable"]
