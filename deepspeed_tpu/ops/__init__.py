from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerLayer,
                                           DeepSpeedTransformerConfig)

__all__ = ["DeepSpeedTransformerLayer", "DeepSpeedTransformerConfig"]
