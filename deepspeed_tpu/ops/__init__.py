from deepspeed_tpu.ops import adam
from deepspeed_tpu.ops import lamb
from deepspeed_tpu.ops import sequence
from deepspeed_tpu.ops import sparse_attention
from deepspeed_tpu.ops import transformer

from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerLayer,
                                           DeepSpeedTransformerConfig)
from deepspeed_tpu.ops.module_inject import replace_module

__all__ = ["DeepSpeedTransformerLayer", "DeepSpeedTransformerConfig",
           "replace_module", "adam", "lamb", "sequence",
           "sparse_attention", "transformer"]
