from deepspeed_tpu.utils.logging import logger, log_dist
# parity: the reference exports RepeatingLoader here
# (ref utils/__init__.py:3)
from deepspeed_tpu.runtime.dataloader import RepeatingLoader
from deepspeed_tpu.utils.timer import (SynchronizedWallClockTimer,
                                       ThroughputTimer)
from deepspeed_tpu.utils.distributed import init_distributed
