"""Distributed initialization.

TPU-native analogue of `deepspeed/utils/distributed.py:12` — the NCCL
rendezvous becomes `jax.distributed.initialize` (coordinator + process
index/count). On a TPU pod the three values auto-resolve from the TPU
environment, so plain `init_distributed()` works with no env plumbing; the
env-var path (MASTER_ADDR/PORT, RANK, WORLD_SIZE) is honored for parity
with the reference's launcher contract.
"""

import os

from deepspeed_tpu.utils.logging import logger


def init_distributed(dist_backend="xla",
                     auto_mpi_discovery=True,
                     distributed_port=29500,
                     verbose=True,
                     timeout=None,
                     init_method=None):
    """Initialize multi-host JAX. Safe to call when single-host (no-op).

    Must run before any other JAX call (jax.distributed.initialize
    requirement) — so this reads only the environment until the decision
    to initialize is made.
    """
    coordinator = os.environ.get("MASTER_ADDR")
    num_processes = os.environ.get("WORLD_SIZE")
    process_id = os.environ.get("RANK")

    if auto_mpi_discovery and coordinator is None and \
            in_mpi_environment():
        mpi_discovery(distributed_port=distributed_port, verbose=verbose)
        coordinator = os.environ.get("MASTER_ADDR")
        num_processes = os.environ.get("WORLD_SIZE")
        process_id = os.environ.get("RANK")

    kwargs = {}
    if coordinator is not None:
        port = os.environ.get("MASTER_PORT", str(distributed_port))
        kwargs["coordinator_address"] = f"{coordinator}:{port}"
    if num_processes is not None:
        kwargs["num_processes"] = int(num_processes)
    if process_id is not None:
        kwargs["process_id"] = int(process_id)

    import jax
    if not kwargs and int(num_processes or 1) <= 1 and \
            "TPU_WORKER_HOSTNAMES" not in os.environ:
        return  # explicit single-process run; leave JAX untouched
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        if "already" in str(e).lower():
            logger.warning("jax.distributed already initialized; skipping")
        elif not kwargs:
            return  # auto-resolution found nothing; single-process run
        else:
            raise
    if verbose:
        logger.info(
            f"Initialized distributed: process {jax.process_index()}/"
            f"{jax.process_count()}, {jax.device_count()} global devices")


def in_mpi_environment():
    return "OMPI_COMM_WORLD_RANK" in os.environ or \
        "PMI_RANK" in os.environ


def mpi_discovery(distributed_port=29500, verbose=True):
    """Derive MASTER_ADDR/RANK/WORLD_SIZE from an MPI launch (ref
    `distributed.py:54-95`), via env vars (OpenMPI/PMI) without requiring
    mpi4py."""
    rank = os.environ.get("OMPI_COMM_WORLD_RANK",
                          os.environ.get("PMI_RANK", "0"))
    world_size = os.environ.get("OMPI_COMM_WORLD_SIZE",
                                os.environ.get("PMI_SIZE", "1"))
    master_addr = os.environ.get("MASTER_ADDR")
    if master_addr is None:
        try:
            from mpi4py import MPI
            comm = MPI.COMM_WORLD
            import socket
            master_addr = comm.bcast(socket.gethostbyname(socket.gethostname())
                                     if comm.Get_rank() == 0 else None, root=0)
        except ImportError:
            master_addr = "127.0.0.1"
    os.environ["MASTER_ADDR"] = master_addr
    os.environ["MASTER_PORT"] = str(distributed_port)
    os.environ["RANK"] = rank
    os.environ["WORLD_SIZE"] = world_size
    os.environ.setdefault("LOCAL_RANK",
                          os.environ.get("OMPI_COMM_WORLD_LOCAL_RANK", "0"))
    if verbose:
        logger.info(
            f"MPI discovery: rank={rank} world_size={world_size} "
            f"master_addr={master_addr} master_port={distributed_port}")
