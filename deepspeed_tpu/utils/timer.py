"""Wall-clock + throughput timers.

TPU-native analogue of `deepspeed/utils/timer.py:19,97`. Where the reference
fences with `torch.cuda.synchronize()`, we fence with
`jax.block_until_ready` on a sentinel / `jax.effects_barrier()` — XLA
dispatch is async exactly like CUDA streams.
"""

import time

from deepspeed_tpu.utils.logging import log_dist


def device_memory_stats():
    """Aggregate allocator stats over ALL local devices — sum of
    bytes-in-use (what this process holds), max of peak-bytes-in-use
    (the binding per-chip high-water mark; summing peaks would
    overstate a single chip's pressure). device_count=0 means the
    backend exposes no memory_stats (e.g. some CPU runtimes); the
    monitor's memory gauge publishes the same numbers. `host_rss_
    bytes` (from /proc/self/statm, stdlib-only) rides along so the
    gauge and the memory ledger's reconciliation stay meaningful
    off-TPU, where the host RSS IS the run's memory signal."""
    in_use, peak, count = 0, 0, 0
    try:
        import jax
        for dev in jax.local_devices():
            stats = dev.memory_stats() or {}
            if not stats:
                continue
            in_use += int(stats.get("bytes_in_use", 0))
            peak = max(peak, int(stats.get("peak_bytes_in_use", 0)))
            count += 1
    except Exception:  # ds-lint: allow[BROADEXC] allocator stats are optional (absent off-TPU / older jaxlib); gauges degrade to zero
        pass
    out = {"in_use_bytes": in_use, "peak_bytes": peak,
           "device_count": count}
    from deepspeed_tpu.monitor.memory import host_rss_bytes
    rss = host_rss_bytes()
    if rss is not None:
        out["host_rss_bytes"] = rss
    return out


def _device_sync():
    try:
        import jax
        # Blocks until all outstanding device computations are complete.
        jax.effects_barrier()
    except Exception:  # ds-lint: allow[BROADEXC] best-effort barrier: timers degrade to dispatch timing when jax is absent/uninitialized
        pass


class SynchronizedWallClockTimer:
    """Named timers with device-fence on start/stop."""

    class Timer:
        def __init__(self, name):
            self.name_ = name
            self.elapsed_ = 0.0
            self.started_ = False
            self.start_time = time.time()

        def start(self):
            assert not self.started_, f"timer {self.name_} has already been started"
            _device_sync()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, reset=False):
            assert self.started_, "timer is not started"
            _device_sync()
            if reset:
                self.elapsed_ = time.time() - self.start_time
            else:
                self.elapsed_ += time.time() - self.start_time
            self.started_ = False

        def reset(self):
            self.elapsed_ = 0.0
            self.started_ = False

        def elapsed(self, reset=True):
            started_ = self.started_
            if self.started_:
                self.stop()
            elapsed_ = self.elapsed_
            if reset:
                self.reset()
            if started_:
                self.start()
            return elapsed_

        def mean(self, reset=True):
            return self.elapsed(reset=reset)

    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    def has_timer(self, name):
        return name in self.timers

    @staticmethod
    def memory_usage():
        stats = device_memory_stats()
        if not stats["device_count"]:
            return "DeviceMem=unavailable"
        gib = 1024 ** 3
        return (f"DeviceMemInUse={round(stats['in_use_bytes'] / gib, 2)}"
                f" GB | DevicePeak="
                f"{round(stats['peak_bytes'] / gib, 2)} GB "
                f"(over {stats['device_count']} local devices)")

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += " | {}: {:.2f}".format(name, elapsed_time)
        log_dist(string, ranks=ranks or [0])


class ThroughputTimer:
    """Samples/sec with warmup-step exclusion (ref `timer.py:97-173`)."""

    def __init__(self,
                 batch_size,
                 num_workers=1,
                 start_step=2,
                 steps_per_output=50,
                 monitor_memory=False,
                 logging_fn=None):
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.batch_size = batch_size or 1
        self.num_workers = num_workers
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn
        if self.logging is None:
            from deepspeed_tpu.utils.logging import logger
            self.logging = logger.info
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True

    def stop(self, report_speed=True, count=1):
        """`count` = microbatches consumed since start() (a fused
        grad-accum step consumes several at once).

        Device fences happen ONLY at measurement-window boundaries (end
        of warmup, and each steps_per_output report) — a per-step
        `effects_barrier` would serialize host and device every step,
        which on a remote-dispatch TPU runtime costs more than the step
        itself. Between fences the device queue stays full; the
        window's wall time divided by its step count is exact."""
        if not self.started:
            return
        self.started = False
        self.micro_step_count += count
        self.global_step_count += count
        if self.start_time == 0:
            if self.global_step_count >= self.start_step:
                # warmup done: fence once and open the window
                _device_sync()
                self.start_time = time.time()
                self._steps_at_window_start = self.global_step_count
            return
        if report_speed and \
                self.global_step_count % self.steps_per_output < count:
            _device_sync()
            self.end_time = time.time()
            window_elapsed = self.end_time - self.start_time
            # cumulative pair: total_elapsed_time / _measured_steps only
            # grow at fences, so avg_samples_per_sec is correct when
            # called mid-window or at end of training (ref ThroughputTimer
            # accumulated total_elapsed_time the same way)
            self.total_elapsed_time += window_elapsed
            self._measured_steps = getattr(self, "_measured_steps", 0) + \
                (self.global_step_count - self._steps_at_window_start)
            self.logging(
                "{}/{}, SamplesPerSec={}".format(
                    self.epoch_count, self.micro_step_count,
                    self.avg_samples_per_sec()))
            # restart the window so a host-side pause (checkpoint save,
            # eval loop) dilutes at most ONE report, not all of them
            self.start_time = self.end_time
            self._steps_at_window_start = self.global_step_count

    def avg_samples_per_sec(self):
        """Cumulative samples/sec over all completed measurement windows
        (post-warmup). Safe to call mid-window — unfenced in-flight steps
        are simply not counted yet; before the first fenced window it
        returns 0.0 (not -inf: callers feed this into logs/ratios).

        Units: `_measured_steps` counts MICROBATCHES (`stop(count=...)`),
        and one microbatch consumes `batch_size` (micro-batch per
        worker) × `num_workers` samples globally — so gas>1 fused steps
        (count=gas) and dp>1 both cancel out to
        train_batch_size × optimizer-steps / elapsed."""
        measured = getattr(self, "_measured_steps", 0)
        if measured > 0 and self.total_elapsed_time > 0:
            samples_per_step = self.batch_size * self.num_workers
            avg_time_per_step = self.total_elapsed_time / measured
            return samples_per_step / avg_time_per_step
        return 0.0
