"""Framework logger + rank-filtered logging.

TPU-native analogue of the reference's `deepspeed/utils/logging.py:7,40`:
one shared `logger`, and `log_dist(message, ranks)` which only emits on the
listed process indices (JAX multi-controller: `jax.process_index()`).
"""

import logging
import sys
import functools


class LoggerFactory:
    @staticmethod
    def create_logger(name=None, level=logging.INFO):
        if name is None:
            raise ValueError("name for logger cannot be None")
        formatter = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s")
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            ch = logging.StreamHandler(stream=sys.stdout)
            ch.setLevel(level)
            ch.setFormatter(formatter)
            logger_.addHandler(ch)
        return logger_


logger = LoggerFactory.create_logger(name="DeepSpeedTPU", level=logging.INFO)


@functools.lru_cache(maxsize=None)
def _process_index():
    try:
        import jax
        return jax.process_index()
    except Exception:  # ds-lint: allow[BROADEXC] logging must work before (or without) jax/distributed init; rank defaults to 0
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log `message` only if the current process index is in `ranks`.

    ranks=None or [-1] means: log on every process.
    """
    my_rank = _process_index()
    should_log = ranks is None or (-1 in ranks) or (my_rank in ranks)
    if should_log:
        logger.log(level, f"[Rank {my_rank}] {message}")
