"""BERT family — pretraining model built on DeepSpeedTransformerLayer.

Counterpart of the reference's BERT story: the vendored test models
(`tests/unit/modeling.py` ~2600 LoC) and the BingBertSquad / bert
pretraining benchmarks (`docs/_tutorials/bert-pretraining.md`) all run
BERT through the fused `DeepSpeedTransformerLayer`. Here the encoder IS a
stack of those layers (scanned, so params stack [L, ...] and the compile
is O(1) in depth), with MLM+NSP heads for pretraining parity.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerLayer,
                                           DeepSpeedTransformerConfig)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pre_layer_norm: bool = False      # classic BERT is post-LN
    fp16: bool = False
    bf16: bool = True                 # TPU-native default
    normalize_invertible: bool = False
    gelu_checkpoint: bool = False
    attn_dropout_checkpoint: bool = False
    # d=64 head packing in the flash kernel ("auto"|"packed"|"off");
    # forwarded to DeepSpeedTransformerConfig.head_packing. bert-large
    # is d=64 (1024/16), so "auto" packs two heads per grid step into
    # K=128 contractions on real TPU.
    attention_head_packing: str = "auto"
    # Fused non-attention epilogues ("auto"|"on"|"off"), forwarded to
    # DeepSpeedTransformerConfig.fused_ops: bias+residual+LayerNorm and
    # bias+exact-erf-GeLU as single Pallas launches
    # (ops/transformer/fused_ops.py). "auto" fuses on real TPU when
    # hidden dropout is inactive; the parameter tree is unchanged.
    fused_ops: str = "auto"
    # Run the MLM head (transform + vocab decoder) matmuls in the
    # compute dtype instead of fp32. The [hidden, vocab] decoder
    # projection is ~10% of the model's flops; in fp32 it runs at a
    # fraction of the MXU's bf16 rate and was the top per-fusion time
    # sink of the seq-128 pretraining step (bench.py
    # bert_mlm_head_dtype leg). LayerNorm stats stay fp32 and the loss
    # upcasts logits to fp32, so only the matmul precision changes —
    # the same contract as every encoder-layer matmul. "auto" enables
    # it on real TPU only (CPU XLA emulates bf16 dots ~11% SLOWER than
    # fp32, measured in the bench leg); True/False force. Resolved at
    # trace time off jax.default_backend() — same AOT caveat as the
    # flash kernel's interpret auto-select.
    mlm_head_in_compute_dtype: Any = "auto"


BERT_SIZES = {
    # CI/harness size: big enough to have real trajectories, small
    # enough for the virtual CPU mesh (tests/model/)
    "bert-tiny": dict(hidden_size=128, num_hidden_layers=2,
                      num_attention_heads=4, intermediate_size=512,
                      vocab_size=512),
    "bert-base": dict(hidden_size=768, num_hidden_layers=12,
                      num_attention_heads=12, intermediate_size=3072),
    "bert-large": dict(hidden_size=1024, num_hidden_layers=24,
                       num_attention_heads=16, intermediate_size=4096),
}


def bert_config(name="bert-base", **overrides) -> BertConfig:
    base = dict(BERT_SIZES[name])
    base.update(overrides)
    return BertConfig(**base)


def _ds_layer_config(cfg: BertConfig) -> DeepSpeedTransformerConfig:
    return DeepSpeedTransformerConfig(
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        heads=cfg.num_attention_heads,
        attn_dropout_ratio=cfg.attention_probs_dropout_prob,
        hidden_dropout_ratio=cfg.hidden_dropout_prob,
        num_hidden_layers=cfg.num_hidden_layers,
        initializer_range=cfg.initializer_range,
        pre_layer_norm=cfg.pre_layer_norm,
        fp16=cfg.fp16,
        bf16=cfg.bf16,
        normalize_invertible=cfg.normalize_invertible,
        gelu_checkpoint=cfg.gelu_checkpoint,
        attn_dropout_checkpoint=cfg.attn_dropout_checkpoint,
        layer_norm_eps=cfg.layer_norm_eps,
        head_packing=cfg.attention_head_packing,
        fused_ops=cfg.fused_ops,
        training=True)


def additive_attention_mask(attention_mask):
    """[B, T] 1/0 -> additive [B, 1, 1, T] (None passes through).
    The ONE definition of BERT's mask arithmetic — shared by the
    module path and the ZeRO-3 scheduled path so they cannot drift."""
    if attention_mask is None:
        return None
    mask = (1.0 - attention_mask.astype(jnp.float32)) * -1e9
    return mask[:, None, None, :]


def mlm_head_dtype(cfg: BertConfig):
    """Resolve mlm_head_in_compute_dtype ("auto" = real TPU only) to
    the dtype the head matmuls run in — shared by both apply paths."""
    head_compute = cfg.mlm_head_in_compute_dtype
    if head_compute == "auto":
        head_compute = jax.default_backend() == "tpu"
    if not head_compute:
        return jnp.float32
    return (jnp.float16 if cfg.fp16 else
            jnp.bfloat16 if cfg.bf16 else jnp.float32)


class BertEmbeddings(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None,
                 deterministic: bool = True):
        cfg = self.config
        b, t = input_ids.shape
        init = nn.initializers.normal(cfg.initializer_range)
        word = self.param("word_embeddings", init,
                          (cfg.vocab_size, cfg.hidden_size))
        pos = self.param("position_embeddings", init,
                         (cfg.max_position_embeddings, cfg.hidden_size))
        tok = self.param("token_type_embeddings", init,
                         (cfg.type_vocab_size, cfg.hidden_size))
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        h = word[input_ids] + pos[:t][None] + tok[token_type_ids]
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="LayerNorm")(h)
        return nn.Dropout(cfg.hidden_dropout_prob)(
            h, deterministic=deterministic)


class BertEncoder(nn.Module):
    """num_hidden_layers DeepSpeedTransformerLayers, scanned."""
    config: BertConfig

    @nn.compact
    def __call__(self, hidden, attention_mask, deterministic: bool = True):
        cfg = self.config
        ds_cfg = _ds_layer_config(cfg)

        class Cell(nn.Module):
            @nn.compact
            def __call__(self, h, mask, det):
                out = DeepSpeedTransformerLayer(ds_cfg)(h, mask, det)
                # scan carry must be dtype-stable: the fused layer's
                # residual/LN path is fp32 while the carry may be bf16
                return out.astype(h.dtype), None

        Scanned = nn.scan(
            Cell,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=(nn.broadcast, nn.broadcast),
            length=cfg.num_hidden_layers,
            metadata_params={nn.meta.PARTITION_NAME: "layers"},
        )
        hidden, _ = Scanned(name="layer")(hidden, attention_mask,
                                          deterministic)
        return hidden


class BertModel(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        cfg = self.config
        h = BertEmbeddings(cfg, name="embeddings")(
            input_ids, token_type_ids, deterministic)
        additive_mask = additive_attention_mask(attention_mask)
        h = BertEncoder(cfg, name="encoder")(h, additive_mask,
                                             deterministic)
        # pooler: tanh(dense(CLS))
        pooled = nn.tanh(nn.Dense(cfg.hidden_size, name="pooler")(
            h[:, 0].astype(jnp.float32)))
        return h, pooled


class BertForPreTraining(nn.Module):
    """MLM + NSP heads (the BingBert pretraining objective)."""
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        cfg = self.config
        sequence_output, pooled = BertModel(cfg, name="bert")(
            input_ids, attention_mask, token_type_ids, deterministic)
        # MLM head: transform + LN + decoder tied to nothing (separate
        # projection keeps the head simple; tying is a config choice).
        # The head matmuls run in the compute dtype (see
        # mlm_head_in_compute_dtype): the [hidden, vocab] decoder is
        # ~10% of the step's flops and in fp32 it was the top
        # per-fusion time sink. LN stats stay fp32; the loss upcasts
        # logits to fp32.
        head_dtype = mlm_head_dtype(cfg)
        x = nn.Dense(cfg.hidden_size, dtype=head_dtype, name="transform")(
            sequence_output.astype(head_dtype))
        x = nn.gelu(x, approximate=False)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="transform_ln")(x)
        mlm_logits = nn.Dense(cfg.vocab_size, dtype=head_dtype,
                              name="decoder")(x.astype(head_dtype))
        nsp_logits = nn.Dense(2, name="seq_relationship")(pooled)
        return mlm_logits, nsp_logits


def _cross_entropy(logits, labels, ignore_index=-100):
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe[..., None],
                               axis=-1).squeeze(-1)
    nll = (logz - gold) * valid
    return nll.sum() / jnp.maximum(valid.sum(), 1)


class BertForPreTrainingLM:
    """Engine-facing wrapper: batch keys input_ids, attention_mask,
    token_type_ids, masked_lm_labels ([B,T], -100 = unmasked), and
    next_sentence_label ([B])."""

    def __init__(self, config: BertConfig):
        self.config = config
        self.module = BertForPreTraining(config)
        # ZeRO-3 gather/release scheduler (runtime/zero/stage3.py),
        # bound by the engine when the effective zero stage is 3
        self._zero3 = None

    def bind_zero3_scheduler(self, sched):
        """Engine hook: weave (or unweave, sched=None) the explicit
        stage-3 gather scheduler through the loss path. The parameter
        tree is IDENTICAL either way — checkpoints interchange."""
        self._zero3 = sched

    def init(self, rng, example_batch):
        ids = example_batch["input_ids"]
        variables = self.module.init(
            {"params": rng, "dropout": rng}, ids, deterministic=True)
        return variables["params"]

    _zero3_dropout_warned = False

    def _zero3_active(self, deterministic):
        """Scheduled-path gate: dropout-active traces stay on the
        module path — the scheduled stack folds its own per-layer rng
        stream, which would change dropout masks vs the module path
        (the fused_ops "auto = dropout-inactive" convention)."""
        if self._zero3 is None:
            return False
        cfg = self.config
        if deterministic or (cfg.hidden_dropout_prob == 0.0 and
                             cfg.attention_probs_dropout_prob == 0.0):
            return True
        if not BertForPreTrainingLM._zero3_dropout_warned:
            BertForPreTrainingLM._zero3_dropout_warned = True
            from deepspeed_tpu.utils.logging import logger
            logger.warning(
                "ZeRO-3 gather scheduler: dropout is active, so this "
                "trace uses the module path (implicit GSPMD gathers); "
                "set the dropout probs to 0.0 for the scheduled "
                "gather/release path in training")
        return False

    def loss_fn(self, params, batch, rngs=None, deterministic=False, **_):
        if self._zero3_active(deterministic):
            mlm_logits, nsp_logits = self._zero3_forward(
                params, batch, rngs, deterministic)
        else:
            mlm_logits, nsp_logits = self.module.apply(
                {"params": params}, batch["input_ids"],
                batch.get("attention_mask"), batch.get("token_type_ids"),
                deterministic, rngs=rngs or {})
        loss = _cross_entropy(mlm_logits, batch["masked_lm_labels"])
        if "next_sentence_label" in batch:
            loss = loss + _cross_entropy(nsp_logits,
                                         batch["next_sentence_label"])
        return loss

    def _zero3_forward(self, params, batch, rngs, deterministic):
        """Scheduled stage-3 forward: the encoder's stacked [L, ...]
        DeepSpeedTransformerLayer params run under the gather/prefetch/
        release schedule (attention mask threads through as a
        non-differentiable broadcast input); embeddings/pooler/heads
        gather once for the step. Same math as the module path."""
        cfg = self.config
        sched = self._zero3
        rngs = rngs or {}
        ids = batch["input_ids"]
        attention_mask = batch.get("attention_mask")
        token_type_ids = batch.get("token_type_ids")
        bert_p = params["bert"]
        # dropout-inactive by the _zero3_active gate
        h = BertEmbeddings(cfg).apply(
            {"params": sched.gather(bert_p["embeddings"],
                                    name="bert.embeddings")},
            ids, token_type_ids, deterministic, rngs=rngs)
        additive_mask = additive_attention_mask(attention_mask)

        (_, stacked), = bert_p["encoder"]["layer"].items()
        ds_cfg = _ds_layer_config(cfg)
        layer = DeepSpeedTransformerLayer(ds_cfg)

        def body(lp, x, rng_k, *extra):
            mask = extra[0] if extra else None
            out = layer.apply({"params": lp}, x, mask, deterministic)
            # dtype-stable carry, like the nn.scan cell: the fused
            # layer's residual/LN path is fp32 while the carry may not be
            return out.astype(x.dtype)

        base_rng = rngs.get("dropout", jax.random.PRNGKey(0))
        extra = () if additive_mask is None else (additive_mask,)
        h = sched.apply_layers(body, stacked, h, base_rng, extra=extra,
                               name="bert.encoder")

        pooled = nn.tanh(nn.Dense(cfg.hidden_size).apply(
            {"params": sched.gather(bert_p["pooler"],
                                    name="bert.pooler")},
            h[:, 0].astype(jnp.float32)))

        head_dtype = mlm_head_dtype(cfg)
        x = nn.Dense(cfg.hidden_size, dtype=head_dtype).apply(
            {"params": sched.gather(params["transform"],
                                    name="transform")},
            h.astype(head_dtype))
        x = nn.gelu(x, approximate=False)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                         dtype=jnp.float32).apply(
            {"params": sched.gather(params["transform_ln"],
                                    name="transform_ln")}, x)
        mlm_logits = nn.Dense(cfg.vocab_size, dtype=head_dtype).apply(
            {"params": sched.gather(params["decoder"], name="decoder")},
            x.astype(head_dtype))
        nsp_logits = nn.Dense(2).apply(
            {"params": sched.gather(params["seq_relationship"],
                                    name="seq_relationship")}, pooled)
        return mlm_logits, nsp_logits


def tiny_bert_config(**overrides):
    base = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                num_attention_heads=4, intermediate_size=128,
                max_position_embeddings=128, hidden_dropout_prob=0.0,
                attention_probs_dropout_prob=0.0, bf16=False)
    base.update(overrides)
    return BertConfig(**base)
