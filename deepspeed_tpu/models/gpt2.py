"""GPT-2 family — the flagship model for the TPU-native runtime.

The reference frames GPT-2 through Megatron integration
(`tests/model/Megatron_GPT2`); here the model is first-class and built for
XLA: one transformer block scanned over the layer dimension
(`nn.scan` → stacked [L, ...] params, single trace, pipeline-ready),
optional `nn.remat` activation checkpointing, bf16 compute with fp32
numerics where it matters (LayerNorm stats, softmax, loss), and
einsum-phrased attention that XLA tiles directly onto the MXU.

Tensor-parallel placement is expressed as PartitionSpec rules over the
param tree (`tp_param_specs`) — Megatron column/row parallel linear layers
(which the reference outsources to an external `mpu`,
`deepspeed/__init__.py:79-80`) become sharding annotations: qkv/fc kernels
column-sharded over `model`, proj kernels row-sharded, with XLA inserting
the psum that Megatron codes by hand.
"""

import dataclasses
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from deepspeed_tpu.ops import overlap as _overlap
from deepspeed_tpu.runtime.mesh import EXPERT_AXIS, MODEL_AXIS


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.1
    layer_norm_epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16       # compute dtype
    param_dtype: Any = jnp.float32  # storage dtype of trainable params
    remat: bool = True              # activation-checkpoint each block
    # Selective rematerialisation: name of a jax.checkpoint_policies
    # policy (e.g. "dots_with_no_batch_dims_saveable" keeps weight-matmul
    # outputs and recomputes only the cheap elementwise chain) or None
    # for full-block remat.
    remat_policy: Optional[str] = None
    attention_impl: str = "auto"    # auto | pallas | xla
    # d=64 head packing in the flash kernel: "auto" pairs two heads per
    # grid step on real TPU so every score/output matmul contracts over
    # K=128 (the MXU's native width; unpacked d=64 runs half-starved),
    # "packed"/"off" force it. Odd B*H counts pad one zero row.
    attention_head_packing: str = "auto"
    # Fused non-attention epilogues ("auto"|"on"|"off"): the block's
    # c_proj-bias + residual + ln_2 chain and the c_fc-bias + GeLU run
    # as single Pallas launches with a one-pass custom backward
    # (ops/transformer/fused_ops.py). "auto" fuses on real TPU when
    # dropout is inactive (backend-keyed like attention_head_packing);
    # "on" forces the path anywhere (XLA fallback off-TPU — same custom
    # VJP, same checkpoint names). The parameter tree is identical
    # either way. Pairs with remat_policy="save_fused_epilogues" for
    # per-fusion rematerialisation.
    fused_ops: str = "auto"
    # int8 quantized-compute projections ("off"|"on"|"auto"): the
    # block's four projection matmuls (c_attn, c_proj, c_fc,
    # mlp_c_proj) contract int8xint8 on the MXU with per-(K-block,
    # N-column) weight scales + per-row activation scales dequantized
    # in the GEMM epilogue (ops/transformer/quantized_matmul.py);
    # weights re-quantize inside every trace, the backward is
    # straight-through in the compute dtype. "auto" = real TPU only
    # (the fused_ops convention — CPU numerics stay bit-identical by
    # default); "off" is bit-for-bit the unquantized path. The
    # parameter tree is identical either way. Engine-wired via the
    # `quantized_compute` config block (configure_quantized_compute).
    quantized_compute: str = "off"
    quant_block: int = 128
    # round the int8 quantization stochastically when the engine
    # provides a per-step "quant" rng stream (unbiased; defaults to
    # round-to-nearest without one)
    quant_stochastic_rounding: bool = False
    # Sequence/context parallelism for long sequences: shard T over a
    # mesh axis and run ring (ppermute KV rotation) or ulysses
    # (all-to-all head swap) attention. Set sp_mesh to the engine mesh
    # and sp_axis to the axis carrying the sequence. By convention this
    # is the model axis, which the engine ALSO uses for Megatron-style
    # tensor parallelism (tp_param_specs): params stay TP-sharded while
    # activations enter attention seq-sharded — the usual TP+SP
    # composition, at the cost of a reshard on entry/exit per layer.
    sequence_parallel: Optional[str] = None   # None | "ring" | "ulysses"
    sp_mesh: Any = None
    sp_axis: str = "model"
    # Mixture-of-Experts (deepspeed_tpu/moe/): a MoEConfig makes every
    # `every_n_layers`-th block replace its dense MLP with the gated
    # top-k expert-parallel MoE MLP (router + capacity-factor
    # all-to-all dispatch + grouped-GEMM experts). STRUCTURAL — the
    # parameter tree changes for MoE layers (dense layers keep the
    # exact dense tree, so their weights load from dense
    # checkpoints); None is bit-for-bit the dense model. The engine's
    # `moe` config block wires the runtime knobs via `configure_moe`.
    moe: Any = None
    initializer_range: float = 0.02

    @property
    def head_dim(self):
        assert self.n_embd % self.n_head == 0
        return self.n_embd // self.n_head

    @property
    def moe_cells(self):
        """Scan length of the MoE super-cell stack: each cell holds
        (every_n_layers - 1) dense blocks + one MoE block."""
        assert self.moe is not None
        every = self.moe.every_n_layers
        if self.n_layer % every:
            raise ValueError(
                f"moe.every_n_layers={every} must divide n_layer="
                f"{self.n_layer}")
        return self.n_layer // every


# Named model sizes (GPT-2 paper + GPT-3-style scale points used by the
# reference's Megatron benchmarks).
GPT2_SIZES = {
    # CI/harness size (tests/model/): real trajectories on a CPU mesh
    "gpt2-tiny": dict(n_layer=2, n_embd=64, n_head=4, vocab_size=512,
                      n_positions=128),
    "gpt2-125m": dict(n_layer=12, n_embd=768, n_head=12),
    "gpt2-350m": dict(n_layer=24, n_embd=1024, n_head=16),
    "gpt2-760m": dict(n_layer=24, n_embd=1536, n_head=16),
    "gpt2-1.5b": dict(n_layer=48, n_embd=1600, n_head=25),
    "gpt2-2.7b": dict(n_layer=32, n_embd=2560, n_head=32),
    "gpt2-6.7b": dict(n_layer=32, n_embd=4096, n_head=32),
    "gpt2-13b": dict(n_layer=40, n_embd=5120, n_head=40),
}


def gpt2_config(name="gpt2-125m", **overrides) -> GPT2Config:
    base = dict(GPT2_SIZES[name])
    base.update(overrides)
    return GPT2Config(**base)


def resolve_remat_policy(name):
    """Remat-policy string -> jax policy. Registered custom policies
    (incl. the built-in "save_fused_epilogues" per-fusion policy)
    resolve first, then `"save_only_these_names:a,b"` over
    `checkpoint_name` annotations (the model marks its attention output
    as "attn_out"), then `jax.checkpoint_policies` attributes."""
    from deepspeed_tpu.runtime.activation_checkpointing.checkpointing \
        import resolve_checkpoint_policy
    return resolve_checkpoint_policy(name)


def _dense(features, config, name, init_scale=1.0):
    return nn.Dense(
        features,
        dtype=config.dtype,
        param_dtype=config.param_dtype,
        kernel_init=nn.initializers.normal(config.initializer_range * init_scale),
        bias_init=nn.initializers.zeros,
        name=name)


def causal_attention_xla(q, k, v, dropout_rng=None, dropout_rate=0.0,
                         deterministic=True):
    """Plain XLA causal attention (shared dense_attention under the hood)."""
    from deepspeed_tpu.ops.transformer.flash_attention import dense_attention
    return dense_attention(q, k, v, causal=True, dropout_rate=dropout_rate,
                           dropout_rng=dropout_rng,
                           deterministic=deterministic)


def _attention(config, q, k, v, dropout_rng, deterministic):
    if config.sequence_parallel:
        # shard_map over the sequence axis composes inside the engine's
        # GSPMD step: activations reshard to [B, T/sp, H, D] on entry
        from deepspeed_tpu.ops.sequence import (ring_attention,
                                                ulysses_attention)
        assert config.sp_mesh is not None, \
            "sequence_parallel requires sp_mesh (pass the engine mesh)"
        assert deterministic or config.dropout == 0.0, \
            "attention dropout is not supported under sequence parallelism"
        impls = {"ring": ring_attention, "ulysses": ulysses_attention}
        if config.sequence_parallel not in impls:
            raise ValueError(
                f"sequence_parallel={config.sequence_parallel!r}; "
                f"valid values: {sorted(impls)} or None")
        fn = impls[config.sequence_parallel]
        return fn(q, k, v, mesh=config.sp_mesh,
                  axis_name=config.sp_axis, causal=True,
                  head_packing=config.attention_head_packing)
    if config.attention_impl in ("pallas", "auto"):
        try:
            from deepspeed_tpu.ops.transformer.flash_attention import (
                flash_attention_usable, flash_attention,
                flash_attention_rematerializable)
            if flash_attention_usable(q, deterministic or config.dropout == 0.0):
                if config.remat:
                    # (out, lse) carry checkpoint_names: with a
                    # save_only_these_names:attn_out,attn_lse policy the
                    # backward never re-runs the flash fwd kernel
                    return flash_attention_rematerializable(
                        q, k, v, causal=True,
                        head_packing=config.attention_head_packing)
                return flash_attention(
                    q, k, v, causal=True,
                    head_packing=config.attention_head_packing)
        except ImportError:
            pass
        if config.attention_impl == "pallas":
            raise RuntimeError("pallas attention requested but unusable "
                               "for these shapes/settings")
    out = causal_attention_xla(q, k, v, dropout_rng, config.dropout,
                               deterministic)
    # keep the named residual on the XLA path too, so
    # save_only_these_names:attn_out policies behave uniformly (no lse
    # here — XLA attention has no separate softmax stats to save)
    from jax.ad_checkpoint import checkpoint_name
    return checkpoint_name(out, "attn_out")


def _quant_dense(features, cfg, name, init_scale=1.0, split=False,
                 sr_fallback=False):
    """QuantizedDense with nn.Dense/SplitDense-identical parameters —
    the quantized-compute twin of `_dense` (checkpoints interchange).
    sr_fallback=True is the family's backward-compatible bf16
    fallback: no quantization, stochastically rounded operand casts."""
    from deepspeed_tpu.ops.transformer.transformer import QuantizedDense
    return QuantizedDense(
        features, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
        kernel_init=nn.initializers.normal(
            cfg.initializer_range * init_scale),
        bias_init=nn.initializers.zeros,
        quant_block=cfg.quant_block,
        stochastic_rounding=cfg.quant_stochastic_rounding,
        split=split, sr_fallback=sr_fallback, name=name)


class GPT2Block(nn.Module):
    """Pre-LN transformer block (attention + MLP).

    Boundary-fusion contract (tentpole of ISSUE 13(c) — the
    kernel-labeled `top_fusion_sinks` table ranks the unfused
    mlp_c_proj-bias + residual-add + next-layer ln_1 chain as the top
    remaining non-matmul sink of the fused flagship step): when the
    caller passes `boundary=(prev_mlp_y, prev_mlp_b)` the TRUE hidden
    state is `hidden + prev_mlp_y + prev_mlp_b`, and this block folds
    that add into its leading LayerNorm as one fused
    bias+residual+LN launch. With `return_boundary=True` the block
    returns `(residual_stream, (mlp_y, mlp_b))` instead of completing
    its own trailing add — the next block (or the model's final
    fused ln_f) consumes it. The scan cell threads this carry; plain
    callers (pipe stages, eval helpers) keep the hidden-in/hidden-out
    interface with both args defaulted off."""
    config: GPT2Config

    @nn.compact
    def __call__(self, hidden, deterministic: bool = True,
                 boundary=None, return_boundary: bool = False):
        cfg = self.config
        b, t, c = hidden.shape

        from deepspeed_tpu.ops.transformer.fused_ops import (
            fused_bias_gelu, fused_bias_residual_layernorm,
            resolve_fused_ops)
        from deepspeed_tpu.ops.transformer.quantized_matmul import \
            resolve_quantized_compute
        # dropout sits between each projection's bias and the residual,
        # so the fused epilogues require it inactive
        use_fused = resolve_fused_ops(
            cfg.fused_ops, deterministic or cfg.dropout == 0.0)
        use_quant = resolve_quantized_compute(cfg.quantized_compute)
        if (boundary is not None or return_boundary) and not use_fused:
            raise ValueError(
                "GPT2Block boundary fusion requires the fused-ops path "
                "(resolve_fused_ops must be active for this trace)")

        def proj(features, name, init_scale=1.0, split=False):
            if use_quant:
                return _quant_dense(features, cfg, name,
                                    init_scale=init_scale, split=split)
            if cfg.quantized_compute not in ("off", False, 0, None) \
                    and cfg.quant_stochastic_rounding:
                # quantized compute configured but resolved OFF on
                # this backend, with stochastic_rounding: the
                # documented bf16 fallback — plain GEMM with
                # stochastically rounded operand casts
                return _quant_dense(features, cfg, name,
                                    init_scale=init_scale,
                                    split=split, sr_fallback=True)
            if split:
                from deepspeed_tpu.ops.transformer.transformer import \
                    SplitDense
                return SplitDense(
                    features, dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype,
                    kernel_init=nn.initializers.normal(
                        cfg.initializer_range * init_scale),
                    name=name)
            return _dense(features, cfg, name, init_scale=init_scale)

        if use_fused:
            from deepspeed_tpu.ops.transformer.transformer import (
                LNParams, plain_layernorm)
            ln1_p = LNParams(param_dtype=cfg.param_dtype,
                             name="ln_1")(cfg.n_embd)
            ln2_p = LNParams(param_dtype=cfg.param_dtype,
                             name="ln_2")(cfg.n_embd)
        else:
            ln1 = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                               dtype=jnp.float32,
                               param_dtype=cfg.param_dtype, name="ln_1")
            ln2 = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                               dtype=jnp.float32,
                               param_dtype=cfg.param_dtype, name="ln_2")

        # --- attention ---
        if use_fused and boundary is not None:
            # one launch: previous block's mlp_c_proj bias + residual
            # + this block's ln_1 (the boundary chain); `hidden`
            # becomes the true residual stream
            prev_y, prev_b = boundary
            x, hidden = fused_bias_residual_layernorm(
                prev_y, prev_b, hidden, *ln1_p,
                eps=cfg.layer_norm_epsilon, out_dtype=cfg.dtype,
                sum_dtype=jnp.result_type(hidden.dtype, cfg.dtype))
        elif use_fused:
            x = plain_layernorm(hidden, *ln1_p,
                                eps=cfg.layer_norm_epsilon) \
                .astype(cfg.dtype)
        else:
            x = ln1(hidden).astype(cfg.dtype)
        qkv = proj(3 * cfg.n_embd, "c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, cfg.n_head, cfg.head_dim)
        k = k.reshape(b, t, cfg.n_head, cfg.head_dim)
        v = v.reshape(b, t, cfg.n_head, cfg.head_dim)
        drop_rng = None
        if not deterministic and cfg.dropout > 0.0:
            drop_rng = self.make_rng("dropout")
        # Under remat, the pallas path names its (out, lse) residuals
        # "attn_out"/"attn_lse" (flash_attention_rematerializable): a
        # "save_only_these_names:attn_out,attn_lse" policy then saves
        # ~27 MB/layer at 1.5B and the backward pass never re-runs the
        # flash forward kernel — the sweet spot between full remat
        # (+1 fwd of recompute) and dots_saveable (~235 MB/layer, OOM).
        attn = _attention(cfg, q, k, v, drop_rng, deterministic)
        attn = attn.reshape(b, t, cfg.n_embd)
        if use_fused:
            attn_y, attn_b = proj(
                cfg.n_embd, "c_proj",
                init_scale=1.0 / np.sqrt(2 * cfg.n_layer),
                split=True)(attn)
            # one launch: c_proj bias + residual + ln_2; `hidden`
            # carries on un-normalized (pre-LN)
            y, hidden = fused_bias_residual_layernorm(
                attn_y, attn_b, hidden, *ln2_p,
                eps=cfg.layer_norm_epsilon, out_dtype=cfg.dtype,
                sum_dtype=jnp.result_type(hidden.dtype, cfg.dtype))
            fc_y, fc_b = proj(4 * cfg.n_embd, "c_fc", split=True)(y)
            # GPT-2 uses the tanh GeLU approximation
            y = fused_bias_gelu(fc_y, fc_b, approximate=True,
                                out_dtype=cfg.dtype)
            if return_boundary:
                # the trailing bias+residual add is NOT completed
                # here: the next block's fused ln_1 (or the model's
                # fused ln_f) consumes it as its boundary input
                mlp_y, mlp_b = proj(
                    cfg.n_embd, "mlp_c_proj",
                    init_scale=1.0 / np.sqrt(2 * cfg.n_layer),
                    split=True)(y)
                return hidden, (mlp_y, mlp_b)
            y = proj(cfg.n_embd, "mlp_c_proj",
                     init_scale=1.0 / np.sqrt(2 * cfg.n_layer))(y)
            return hidden + y
        # proj init scaled down by depth (GPT-2 residual-scaling trick)
        attn = proj(cfg.n_embd, "c_proj",
                    init_scale=1.0 / np.sqrt(2 * cfg.n_layer))(attn)
        attn = nn.Dropout(cfg.dropout)(attn, deterministic=deterministic)
        hidden = hidden + attn

        # --- MLP ---
        y = ln2(hidden).astype(cfg.dtype)
        y = proj(4 * cfg.n_embd, "c_fc")(y)
        y = nn.gelu(y, approximate=True)
        y = proj(cfg.n_embd, "mlp_c_proj",
                 init_scale=1.0 / np.sqrt(2 * cfg.n_layer))(y)
        y = nn.Dropout(cfg.dropout)(y, deterministic=deterministic)
        return hidden + y


class MoEGPT2Block(nn.Module):
    """Pre-LN block whose MLP is the mixture-of-experts MoEMLP
    (deepspeed_tpu/moe/layer.py): attention half IDENTICAL to
    GPT2Block (same submodule names — ln_1/c_attn/c_proj/ln_2, so a
    dense checkpoint's attention weights load into an MoE model's MoE
    layers too), then router + dispatch + grouped-GEMM experts +
    combine instead of c_fc/mlp_c_proj. Returns (hidden, stats) —
    the [E+2] router stats vector the scan carry accumulates."""
    config: GPT2Config

    @nn.compact
    def __call__(self, hidden, deterministic: bool = True):
        cfg = self.config
        b, t, c = hidden.shape
        from deepspeed_tpu.moe.layer import MoEMLP
        from deepspeed_tpu.ops.transformer.quantized_matmul import \
            resolve_quantized_compute
        use_quant = resolve_quantized_compute(cfg.quantized_compute)

        def proj(features, name, init_scale=1.0):
            if use_quant:
                return _quant_dense(features, cfg, name,
                                    init_scale=init_scale)
            return _dense(features, cfg, name, init_scale=init_scale)

        ln1 = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                           dtype=jnp.float32,
                           param_dtype=cfg.param_dtype, name="ln_1")
        ln2 = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                           dtype=jnp.float32,
                           param_dtype=cfg.param_dtype, name="ln_2")

        x = ln1(hidden).astype(cfg.dtype)
        qkv = proj(3 * cfg.n_embd, "c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, cfg.n_head, cfg.head_dim)
        k = k.reshape(b, t, cfg.n_head, cfg.head_dim)
        v = v.reshape(b, t, cfg.n_head, cfg.head_dim)
        drop_rng = None
        if not deterministic and cfg.dropout > 0.0:
            drop_rng = self.make_rng("dropout")
        attn = _attention(cfg, q, k, v, drop_rng, deterministic)
        attn = attn.reshape(b, t, cfg.n_embd)
        attn = proj(cfg.n_embd, "c_proj",
                    init_scale=1.0 / np.sqrt(2 * cfg.n_layer))(attn)
        attn = nn.Dropout(cfg.dropout)(attn, deterministic=deterministic)
        hidden = hidden + attn

        y = ln2(hidden).astype(cfg.dtype)
        y, stats = MoEMLP(
            moe=cfg.moe, d_model=cfg.n_embd, d_ff=4 * cfg.n_embd,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(cfg.initializer_range),
            out_kernel_init=nn.initializers.normal(
                cfg.initializer_range / np.sqrt(2 * cfg.n_layer)),
            name="moe_mlp")(y, deterministic)
        y = nn.Dropout(cfg.dropout)(y, deterministic=deterministic)
        return hidden + y, stats


class _MoECellScan(nn.Module):
    """Scan cell of the MoE model: (every_n_layers - 1) dense
    GPT2Blocks — parameter-tree-identical to the dense model's
    blocks — followed by one MoEGPT2Block. Carry =
    (hidden, stats_sum): router stats accumulate across cells on
    device and surface once per step through the model loss, never
    per-layer host traffic. Also the cell the ZeRO-3 scheduled path
    applies per stacked slice (_zero3_loss), so the two traces run
    the same op sequence."""
    config: GPT2Config

    @nn.compact
    def __call__(self, carry, deterministic):
        cfg = self.config
        hidden, stats = carry
        block_cls = GPT2Block
        moe_cls = MoEGPT2Block
        if cfg.remat:
            block_cls = nn.remat(GPT2Block, prevent_cse=False,
                                 static_argnums=(2, 4),
                                 policy=resolve_remat_policy(
                                     cfg.remat_policy))
            moe_cls = nn.remat(MoEGPT2Block, prevent_cse=False,
                               static_argnums=(2,),
                               policy=resolve_remat_policy(
                                   cfg.remat_policy))
        for _ in range(cfg.moe.every_n_layers - 1):
            hidden = block_cls(cfg)(hidden, deterministic, None, False)
        hidden, s = moe_cls(cfg)(hidden, deterministic)
        return (hidden, stats + s), None


def embed_tokens(cfg: GPT2Config, wte, wpe, input_ids):
    """Token + position embedding in the compute dtype — the ONE
    definition of GPT-2's embedding arithmetic, shared by the module
    path and the ZeRO-3 scheduled path so they cannot drift."""
    t = input_ids.shape[1]
    return wte[input_ids].astype(cfg.dtype) + \
        wpe[:t][None, :, :].astype(cfg.dtype)


def stacked_block_params(params):
    """The nn.scan cell's stacked [n_layer, ...] param subtree — the
    single auto-named child under "h" (GPT2Block_0, or
    CheckpointGPT2Block_0 under remat; same leaves either way). The
    ONE place that naming knowledge lives: the ZeRO-3 scheduled loss
    and the inference engine's layer scan both reconstruct the block
    stack through this."""
    (_, stacked), = params["h"].items()
    return stacked


class GPT2LMHeadModel(nn.Module):
    """GPT-2 with tied-embedding LM head; returns logits."""
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids, deterministic: bool = True,
                 layer_keep_prob: Optional[jnp.ndarray] = None,
                 return_hidden: bool = False):
        cfg = self.config
        b, t = input_ids.shape

        wte = self.param("wte",
                         nn.initializers.normal(cfg.initializer_range),
                         (cfg.vocab_size, cfg.n_embd), cfg.param_dtype)
        wpe = self.param("wpe",
                         nn.initializers.normal(cfg.initializer_range),
                         (cfg.n_positions, cfg.n_embd), cfg.param_dtype)

        hidden = embed_tokens(cfg, wte, wpe, input_ids)
        hidden = nn.Dropout(cfg.dropout)(hidden, deterministic=deterministic)

        if cfg.moe is not None:
            # MoE path: scan super-cells of (every_n - 1 dense blocks
            # + 1 MoE block); the carry threads (hidden, router-stats
            # sum) so per-layer stats reach the loss/monitor with zero
            # extra host traffic. Boundary fusion and PLD keep to the
            # dense path (the MoE combine boundary is not a fusable
            # bias+residual chain).
            if layer_keep_prob is not None:
                raise ValueError(
                    "progressive_layer_drop is not supported with "
                    "mixture-of-experts (no per-cell keep-prob gate)")
            cells = cfg.moe_cells
            ScannedCells = nn.scan(
                _MoECellScan,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True,
                            "quant": True},
                in_axes=(nn.broadcast,),
                length=cells,
                metadata_params={nn.meta.PARTITION_NAME: "layers"},
            )
            stats0 = jnp.zeros((cfg.moe.num_experts + 2,), jnp.float32)
            (hidden, stats), _ = ScannedCells(cfg, name="h")(
                (hidden, stats0), deterministic)
            # per-MoE-layer mean: aux weighting and the fence event
            # stay depth-independent
            moe_stats = stats / jnp.float32(cells)
            hidden = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                                  dtype=jnp.float32,
                                  param_dtype=cfg.param_dtype,
                                  name="ln_f")(hidden)
            if return_hidden:
                return (hidden.astype(cfg.dtype), wte), moe_stats
            logits = jnp.einsum("btc,vc->btv",
                                hidden.astype(cfg.dtype),
                                wte.astype(cfg.dtype))
            return logits, moe_stats

        # Scan one block over a stacked [n_layer, ...] param tree: single
        # trace, O(1) compile in depth, and the layer dim is what pipeline
        # parallelism later splits across stages.
        ScannedBlocks = nn.scan(
            _BlockScanCell,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True, "quant": True},
            in_axes=(nn.broadcast, nn.broadcast),
            length=cfg.n_layer,
            metadata_params={nn.meta.PARTITION_NAME: "layers"},
        )
        # Progressive layer drop: stochastic depth with keep-prob theta fed
        # per step (ref `progressive_layer_drop.py:5`), applied as a
        # bernoulli gate on each block's residual inside the scan.
        keep = layer_keep_prob if layer_keep_prob is not None else None
        from deepspeed_tpu.ops.transformer.fused_ops import (
            fused_bias_residual_layernorm, resolve_fused_ops)
        # Boundary fusion (ISSUE 13(c)): under the fused path each
        # layer boundary's mlp_c_proj-bias + residual-add + next ln_1
        # runs as ONE fused launch — the scan carries
        # (residual_stream, (mlp_y, mlp_b)) instead of the completed
        # hidden state, and the final boundary folds into a fused
        # ln_f the same way. PLD gates on completed block outputs, so
        # it keeps the plain carry.
        use_boundary = keep is None and resolve_fused_ops(
            cfg.fused_ops, deterministic or cfg.dropout == 0.0)
        if use_boundary:
            from deepspeed_tpu.ops.transformer.transformer import \
                LNParams
            # the zero bias seeds the first boundary; its dtype must
            # match the bias params AS APPLIED (the engine hands the
            # compute-dtype cast of the tree to bf16 traces), which
            # wte's runtime dtype tracks exactly
            carry0 = (hidden,
                      (jnp.zeros(hidden.shape, cfg.dtype),
                       jnp.zeros((cfg.n_embd,), wte.dtype)))
            (resid, (mlp_y, mlp_b)), _ = ScannedBlocks(
                cfg, name="h")(carry0, deterministic, keep)
            lnf_p = LNParams(param_dtype=cfg.param_dtype,
                             name="ln_f")(cfg.n_embd)
            hidden = fused_bias_residual_layernorm(
                mlp_y, mlp_b, resid, *lnf_p,
                eps=cfg.layer_norm_epsilon, out_dtype=jnp.float32,
                return_sum=False)
        else:
            hidden, _ = ScannedBlocks(cfg, name="h")(hidden,
                                                     deterministic,
                                                     keep)
            hidden = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                                  dtype=jnp.float32,
                                  param_dtype=cfg.param_dtype,
                                  name="ln_f")(hidden)
        if return_hidden:
            # fused-head path: the caller computes loss chunkwise against
            # wte without materialising [B, T, vocab] logits
            return hidden.astype(cfg.dtype), wte
        logits = jnp.einsum("btc,vc->btv", hidden.astype(cfg.dtype),
                            wte.astype(cfg.dtype))
        return logits


class _BlockScanCell(nn.Module):
    """Scan cell: threads the carry through one (optionally rematted,
    optionally stochastic-depth-gated) block; returns (carry, None).

    Two carry shapes: a plain hidden array (the historical interface;
    PLD and the unfused path), or the boundary-fused tuple
    (residual_stream, (mlp_y, mlp_b)) — the block then folds the
    previous boundary into its fused ln_1 and leaves its own boundary
    open for the next cell (see GPT2Block's boundary contract)."""
    config: GPT2Config

    @nn.compact
    def __call__(self, carry, deterministic, keep_prob):
        cfg = self.config
        boundary_mode = isinstance(carry, tuple)
        block_cls = GPT2Block
        if cfg.remat:
            # static argnums index flax-remat call args with the
            # module at 0: deterministic=2, return_boundary=4
            block_cls = nn.remat(GPT2Block, prevent_cse=False,
                                 static_argnums=(2, 4),
                                 policy=resolve_remat_policy(
                                     cfg.remat_policy))
        if boundary_mode:
            hidden, prev = carry
            return block_cls(cfg)(hidden, deterministic, prev,
                                  True), None
        hidden = carry
        out = block_cls(cfg)(hidden, deterministic, None, False)
        if keep_prob is not None:
            if deterministic:
                out = hidden + keep_prob * (out - hidden)
            else:
                gate = jax.random.bernoulli(self.make_rng("dropout"),
                                            keep_prob)
                out = jnp.where(gate, out, hidden)
        return out, None


def chunked_tied_head_loss(hidden, wte, labels, ignore_index=-100,
                           chunk_tokens=1024):
    """Tied-embedding LM head + token CE without ever materialising the
    full [B, T, vocab] logits (at 50k vocab that is gigabytes in fp32 and
    was the single biggest activation in the train step).

    Scans over token chunks: each step computes a [chunk, vocab] logits
    tile on the MXU with fp32 accumulation, reduces it to (nll_sum,
    valid_count), and is `jax.checkpoint`-ed so the backward pass
    recomputes the tile instead of saving it.
    """
    b, t, c = hidden.shape
    n = b * t
    h = hidden.reshape(n, c)
    lab = labels.reshape(n)
    pad = (-n) % chunk_tokens
    if pad:
        h = jnp.concatenate([h, jnp.zeros((pad, c), h.dtype)])
        lab = jnp.concatenate(
            [lab, jnp.full((pad,), ignore_index, lab.dtype)])
    h = h.reshape(-1, chunk_tokens, c)
    lab = lab.reshape(-1, chunk_tokens)
    wte_c = wte.astype(hidden.dtype)

    @jax.checkpoint
    def body(carry, xs):
        hc, lc = xs
        logits = jnp.einsum("tc,vc->tv", hc, wte_c,
                            preferred_element_type=jnp.float32)
        valid = lc != ignore_index
        safe = jnp.where(valid, lc, 0)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, safe[:, None], axis=1)[:, 0]
        nll = jnp.where(valid, logz - gold, 0.0)
        return (carry[0] + nll.sum(), carry[1] + valid.sum()), None

    (total, count), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.int32(0)), (h, lab))
    return total / jnp.maximum(count, 1)


def _zero3_leaf_depend(sched, tree, hidden):
    """`depend=` for a ZeRO-3 standalone-leaf gather under the
    `zero3_leaf` overlap site (ops/overlap.py): tying the gather to
    the post-embed activation sinks its all-gather under the first
    scan layers instead of serializing at the program top. None when
    the site is off — the PR-9 up-front gather, bit-exact either way
    (the fence is a schedule constraint, not math)."""
    nbytes = sum(
        int(np.prod(np.shape(leaf))) * np.dtype(leaf.dtype).itemsize
        for leaf in jax.tree_util.tree_leaves(tree))
    on = _overlap.schedule(_overlap.SITE_ZERO3_LEAF,
                           payload_bytes=nbytes,
                           mesh=sched.mesh)["overlap"]
    return hidden if on else None


def cross_entropy_loss(logits, labels, ignore_index=-100):
    """Token-level CE in fp32; mean over non-ignored positions."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe_labels[..., None],
                               axis=-1).squeeze(-1)
    nll = (logz - gold) * valid
    return nll.sum() / jnp.maximum(valid.sum(), 1)


class GPT2ForCausalLM:
    """Engine-facing wrapper: `loss_fn(params, batch, rngs)` protocol.

    batch = dict(input_ids=[B,T] int32, labels=[B,T] int32).  Labels are
    next-token targets (already shifted) or raw ids (shift internally when
    labels is None).
    """

    def __init__(self, config: GPT2Config):
        self.config = config
        self.module = GPT2LMHeadModel(config)
        # ZeRO-3 gather/release scheduler (runtime/zero/stage3.py),
        # bound by the engine when the effective zero stage is 3
        self._zero3 = None

    def bind_zero3_scheduler(self, sched):
        """Engine hook: weave (or unweave, sched=None) the explicit
        stage-3 gather scheduler through the loss path. The parameter
        tree is IDENTICAL either way — checkpoints interchange."""
        self._zero3 = sched

    def moe_info(self):
        """Engine-facing MoE summary (None = dense model): the keys
        the engine needs for the `moe` block verification, the router
        labels of the per-fence `router` event, and the moe_dispatch
        ledger multiplier."""
        moe = self.config.moe
        if moe is None:
            return None
        return dict(num_experts=moe.num_experts, top_k=moe.top_k,
                    capacity_factor=moe.capacity_factor,
                    aux_loss_weight=moe.aux_loss_weight,
                    every_n_layers=moe.every_n_layers,
                    jitter_eps=moe.jitter_eps,
                    width=self.config.n_embd,
                    moe_layers=self.config.moe_cells)

    def configure_moe(self, mesh=None, num_experts=None,
                      every_n_layers=None, top_k=None,
                      capacity_factor=None, aux_loss_weight=None,
                      jitter_eps=None, fused_dispatch=None):
        """Engine hook for the `moe` config block. Structural keys
        (num_experts, every_n_layers) are VERIFIED against the built
        model — they shape the parameter tree, so a mismatch is a
        config error, not a rebuild. Router knobs (top_k,
        capacity_factor, aux_loss_weight, jitter_eps, fused_dispatch)
        and the engine mesh are applied: they are trace-time behavior,
        the parameter tree is identical before and after."""
        moe = self.config.moe
        if moe is None:
            raise ValueError(
                "moe config block is enabled but the model was built "
                "without MoE structure; construct it with "
                "GPT2Config(moe=MoEConfig(...)) so the parameter tree "
                "carries the expert leaves")
        for key, want in (("num_experts", num_experts),
                          ("every_n_layers", every_n_layers)):
            have = getattr(moe, key)
            if want is not None and int(want) != have:
                raise ValueError(
                    f"moe.{key}={want} does not match the model's "
                    f"built structure ({have}); structural keys "
                    "cannot be reconfigured after init")
        updates = {}
        if mesh is not None:
            updates["mesh"] = mesh
        if top_k is not None:
            updates["top_k"] = int(top_k)
        if capacity_factor is not None:
            updates["capacity_factor"] = float(capacity_factor)
        if aux_loss_weight is not None:
            updates["aux_loss_weight"] = float(aux_loss_weight)
        if jitter_eps is not None:
            updates["jitter_eps"] = float(jitter_eps)
        if fused_dispatch is not None:
            updates["fused_dispatch"] = fused_dispatch
        moe = dataclasses.replace(moe, **updates).validate()
        self.config = dataclasses.replace(self.config, moe=moe)
        self.module = GPT2LMHeadModel(self.config)

    def configure_quantized_compute(self, mode, block=None,
                                    stochastic_rounding=None):
        """Engine hook for the `quantized_compute` config block:
        rebuild the module with the int8 quantized-compute projection
        family switched to `mode` ("off"|"on"|"auto"). The parameter
        tree is IDENTICAL either way — existing checkpoints load
        unchanged and the toggle can flip mid-run between traces."""
        from deepspeed_tpu.ops.transformer.quantized_matmul import \
            resolve_quantized_compute
        resolve_quantized_compute(mode)   # ValueError on bad mode
        updates = {"quantized_compute": mode}
        if block is not None:
            updates["quant_block"] = int(block)
        if stochastic_rounding is not None:
            updates["quant_stochastic_rounding"] = \
                bool(stochastic_rounding)
        self.config = dataclasses.replace(self.config, **updates)
        self.module = GPT2LMHeadModel(self.config)

    def init(self, rng, example_batch):
        input_ids = example_batch["input_ids"]
        variables = self.module.init({"params": rng, "dropout": rng},
                                     input_ids, True)
        return variables["params"]

    @staticmethod
    def _shifted_labels(batch):
        input_ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate(
                [input_ids[:, 1:],
                 jnp.full_like(input_ids[:, :1], -100)], axis=1)
        return input_ids, labels

    _zero3_dropout_warned = False
    _zero3_jitter_warned = False

    def _zero3_active(self, deterministic):
        """Scheduled-path gate: rng-consuming traces stay on the
        module path — the scheduled stack folds its own per-layer rng
        stream, which would silently change dropout masks (and MoE
        router-jitter draws) vs the module path, false-alarming an
        ABCorrectnessChecker A/B. The fused_ops/head_packing
        "auto = dropout-inactive" convention, applied to the gather
        schedule; moe.jitter_eps is the same kind of training-only
        rng consumer, so it gates identically."""
        if self._zero3 is None:
            return False
        jitter_active = (not deterministic and
                         self.config.moe is not None and
                         self.config.moe.jitter_eps > 0.0)
        if jitter_active:
            if not GPT2ForCausalLM._zero3_jitter_warned:
                GPT2ForCausalLM._zero3_jitter_warned = True
                from deepspeed_tpu.utils.logging import logger
                logger.warning(
                    "ZeRO-3 gather scheduler: moe.jitter_eps is "
                    "active, so this trace uses the module path "
                    "(implicit GSPMD gathers) to keep router-jitter "
                    "draws identical to the unscheduled engine; set "
                    "moe.jitter_eps=0.0 to get the scheduled "
                    "gather/release path for training")
            return False
        if deterministic or self.config.dropout == 0.0:
            return True
        if not GPT2ForCausalLM._zero3_dropout_warned:
            GPT2ForCausalLM._zero3_dropout_warned = True
            from deepspeed_tpu.utils.logging import logger
            logger.warning(
                "ZeRO-3 gather scheduler: dropout is active, so this "
                "trace uses the module path (implicit GSPMD gathers) "
                "to keep dropout streams identical to the unscheduled "
                "engine; set dropout=0.0 to get the scheduled "
                "gather/release path for training")
        return False

    def loss_fn(self, params, batch, rngs=None, deterministic=False,
                layer_keep_prob=None, return_router_stats=False):
        if self._zero3_active(deterministic):
            return self._zero3_loss(params, batch, rngs, deterministic,
                                    layer_keep_prob,
                                    return_router_stats)
        input_ids, labels = self._shifted_labels(batch)
        kwargs = {}
        if layer_keep_prob is not None:
            kwargs["layer_keep_prob"] = layer_keep_prob
        out = self.module.apply({"params": params}, input_ids,
                                deterministic,
                                rngs=rngs or {},
                                return_hidden=True, **kwargs)
        if self.config.moe is not None:
            (hidden, wte), stats = out
            return self._moe_loss(hidden, wte, labels, stats,
                                  return_router_stats)
        if return_router_stats:
            raise ValueError(
                "return_router_stats requires a model built with "
                "GPT2Config(moe=...)")
        hidden, wte = out
        return chunked_tied_head_loss(hidden, wte, labels)

    def _moe_loss(self, hidden, wte, labels, stats,
                  return_router_stats):
        """CE + weighted aux load-balancing loss; `stats` is the
        per-MoE-layer mean [E+2] vector (aux at STAT_AUX), so the
        weight is depth-independent."""
        from deepspeed_tpu.moe.router import STAT_AUX
        loss = chunked_tied_head_loss(hidden, wte, labels)
        loss = loss + jnp.float32(
            self.config.moe.aux_loss_weight) * stats[STAT_AUX]
        if return_router_stats:
            return loss, stats
        return loss

    def _moe_zero3_specs(self, stacked):
        """Per-leaf base PartitionSpecs of the stacked MoE cell tree
        for the ZeRO-3 scheduler: expert leaves keep their expert dim
        on the `expert` axis through gather/reduce-scatter (the
        gathered copy stays expert-sharded — gathering over data
        only); everything else gathers to full. None when the mesh
        carries no expert axis (nothing to preserve)."""
        from deepspeed_tpu.runtime.mesh import (EXPERT_AXIS,
                                                expert_axis_size)
        mesh = self.config.moe.mesh
        if mesh is None or expert_axis_size(mesh) <= 1:
            return None
        flat, treedef = jax.tree_util.tree_flatten_with_path(stacked)
        specs = []
        for path, leaf in flat:
            name = jax.tree_util.keystr(path)
            spec = [None] * np.ndim(leaf)
            # stacked expert leaves: [cells, E, ...] — dim 1 is the
            # expert dim
            if "experts" in name and np.ndim(leaf) >= 3:
                spec[1] = EXPERT_AXIS
            specs.append(PartitionSpec(*spec))
        return jax.tree_util.tree_unflatten(treedef, specs)

    def _zero3_moe_loss(self, params, batch, rngs, deterministic,
                        return_router_stats):
        """The scheduled stage-3 forward of the MoE model: the whole
        super-cell subtree (dense blocks + MoE block — router,
        experts and all) is the stacked unit `apply_layers` drives, so
        expert leaves gather/reduce-scatter per layer window exactly
        like dense leaves, except their expert dim STAYS on the
        expert axis (param_specs below). The carry mirrors the module
        path's (hidden, stats) pair; op sequence identical."""
        cfg = self.config
        sched = self._zero3
        input_ids, labels = self._shifted_labels(batch)
        wte = sched.gather(params["wte"], name="wte")
        wpe = sched.gather(params["wpe"], name="wpe")
        hidden = embed_tokens(cfg, wte, wpe, input_ids)

        stacked = params["h"]
        cell = _MoECellScan(cfg)
        base_rng = (rngs or {}).get("dropout", jax.random.PRNGKey(0))
        lnf_params = sched.gather(
            params["ln_f"], name="ln_f",
            depend=_zero3_leaf_depend(sched, params["ln_f"], hidden))

        def body(lp, carry, rng_k):
            out, _ = cell.apply({"params": lp}, carry, deterministic)
            return out

        stats0 = jnp.zeros((cfg.moe.num_experts + 2,), jnp.float32)
        hidden, stats = sched.apply_layers(
            body, stacked, (hidden, stats0), base_rng, name="h",
            param_specs=self._moe_zero3_specs(stacked))
        stats = stats / jnp.float32(cfg.moe_cells)
        ln_f = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                            dtype=jnp.float32,
                            param_dtype=cfg.param_dtype)
        hidden = ln_f.apply({"params": lnf_params}, hidden)
        return self._moe_loss(hidden.astype(cfg.dtype), wte, labels,
                              stats, return_router_stats)

    def _zero3_loss(self, params, batch, rngs, deterministic,
                    layer_keep_prob, return_router_stats=False):
        """The scheduled stage-3 forward: same math as the module path
        (bit-exact at gather_dtype=None), but every parameter use goes
        through the scheduler — embeddings/ln_f gathered once for the
        step, the block stack driven by `apply_layers` so layer k+1's
        all-gather issues while layer k computes and each gathered
        buffer dies after its fwd/bwd use (full-block remat; the
        backward re-gathers in reverse order and reduce-scatters each
        layer's grad into its owning data-axis shard)."""
        if layer_keep_prob is not None:
            raise ValueError(
                "progressive_layer_drop is not supported on the ZeRO-3 "
                "scheduled path (the engine disables the scheduler "
                "when PLD is configured)")
        if self.config.moe is not None:
            return self._zero3_moe_loss(params, batch, rngs,
                                        deterministic,
                                        return_router_stats)
        if return_router_stats:
            raise ValueError(
                "return_router_stats requires a model built with "
                "GPT2Config(moe=...)")
        cfg = self.config
        sched = self._zero3
        input_ids, labels = self._shifted_labels(batch)
        # dropout-inactive by the _zero3_active gate: every dropout
        # layer is a no-op here, so no rng stream can diverge from the
        # module path
        wte = sched.gather(params["wte"], name="wte")
        wpe = sched.gather(params["wpe"], name="wpe")
        hidden = embed_tokens(cfg, wte, wpe, input_ids)

        stacked = stacked_block_params(params)
        block = GPT2Block(cfg)
        base_rng = (rngs or {}).get("dropout", jax.random.PRNGKey(0))
        from deepspeed_tpu.ops.transformer.fused_ops import (
            fused_bias_residual_layernorm, resolve_fused_ops)
        # mirror the module path's boundary fusion (dropout is
        # inactive here by the _zero3_active gate) so scheduled and
        # unscheduled traces run the same op sequence
        use_boundary = resolve_fused_ops(cfg.fused_ops, True)
        lnf_params = sched.gather(
            params["ln_f"], name="ln_f",
            depend=_zero3_leaf_depend(sched, params["ln_f"], hidden))

        if use_boundary:
            def body(lp, carry, rng_k):
                h, prev = carry
                return block.apply({"params": lp}, h, deterministic,
                                   prev, True)

            carry0 = (hidden,
                      (jnp.zeros(hidden.shape, cfg.dtype),
                       jnp.zeros((cfg.n_embd,), wte.dtype)))
            resid, (mlp_y, mlp_b) = sched.apply_layers(
                body, stacked, carry0, base_rng, name="h")
            hidden = fused_bias_residual_layernorm(
                mlp_y, mlp_b, resid, lnf_params["scale"],
                lnf_params["bias"], eps=cfg.layer_norm_epsilon,
                out_dtype=jnp.float32, return_sum=False)
        else:
            def body(lp, h, rng_k):
                return block.apply({"params": lp}, h, deterministic)

            hidden = sched.apply_layers(body, stacked, hidden,
                                        base_rng, name="h")
            ln_f = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                                dtype=jnp.float32,
                                param_dtype=cfg.param_dtype)
            hidden = ln_f.apply({"params": lnf_params}, hidden)
        return chunked_tied_head_loss(hidden.astype(cfg.dtype), wte,
                                      labels)

    def apply(self, params, input_ids, deterministic=True):
        out = self.module.apply({"params": params}, input_ids,
                                deterministic)
        if self.config.moe is not None:
            out, _stats = out   # logits only; stats ride loss_fn
        return out

    def sparse_grad_paths(self):
        """Param-path substrings whose grads are row-sparse, consumed by
        the engine's CSR gradient path (ref `engine.py:1190-1246`).
        Empty for GPT-2: the tied LM head makes the wte gradient DENSE
        (every vocab row receives softmax-normalizer gradient), so CSR
        compression would truncate it.  Models with pure-gather
        embeddings (untied heads) should return their embedding paths."""
        return ()

    # -- tensor parallel placement ---------------------------------------
    def tp_param_specs(self, params):
        """PartitionSpec tree: Megatron-style column/row sharding over the
        `model` mesh axis. Scanned blocks carry a leading layer dim."""
        from flax.traverse_util import flatten_dict, unflatten_dict
        from deepspeed_tpu.runtime.mesh import expert_axis_size
        flat = flatten_dict(params)
        moe = self.config.moe
        expert_sharded = (moe is not None and moe.mesh is not None and
                          expert_axis_size(moe.mesh) > 1)
        specs = {}
        for path, leaf in flat.items():
            name = "/".join(str(p) for p in path)
            nd = np.ndim(leaf)
            spec = [None] * nd
            if expert_sharded and "experts" in name and nd >= 3:
                # stacked expert leaves [cells, E, ...]: the expert
                # dim shards over the `expert` mesh axis; ZeRO's
                # data-axis sharding composes on a remaining free dim
                spec[1] = EXPERT_AXIS
                specs[path] = PartitionSpec(*spec)
                continue
            if name == "wte" or name == "wpe":
                # vocab/position dim sharded over model axis
                spec[0] = MODEL_AXIS
            elif "c_attn" in name and name.endswith("kernel"):
                spec[-1] = MODEL_AXIS          # column parallel
            elif "c_attn" in name and name.endswith("bias"):
                spec[-1] = MODEL_AXIS
            elif "c_fc" in name and name.endswith("kernel"):
                spec[-1] = MODEL_AXIS          # column parallel
            elif "c_fc" in name and name.endswith("bias"):
                spec[-1] = MODEL_AXIS
            elif "c_proj" in name and name.endswith("kernel"):
                spec[-2] = MODEL_AXIS          # row parallel
            specs[path] = PartitionSpec(*spec)
        return unflatten_dict(specs)


def tiny_gpt2_config(**overrides):
    """Small config for tests/CI (CPU-mesh friendly sizes)."""
    base = dict(vocab_size=256, n_positions=128, n_embd=64, n_layer=2,
                n_head=4, dropout=0.0, dtype=jnp.float32, remat=False)
    base.update(overrides)
    return GPT2Config(**base)
