"""PipelinedGPT2 — GPT-2 partitioned into homogeneous pipeline stages.

The SPMD execution substrate for pipeline parallelism (SURVEY §7 "hard
parts"): instead of per-stage programs + p2p (ref `runtime/pipe/engine.py`
+ `p2p.py`), stage parameters are STACKED on a leading [S, ...] axis
sharded over the `pipe` mesh axis, the stage body is `vmap`ed over that
axis (GSPMD partitions it, so every stage computes concurrently), and the
activation rotation stage i → i+1 is a `jnp.roll` on the pipe-sharded
buffer, which XLA lowers to a collective-permute over ICI. A
`lax.scan` over M + S - 1 ticks realizes the GPipe fill/steady/drain
timeline; reverse-mode autodiff through the scan + roll generates the
backward pipeline automatically (the transpose of a collective-permute is
the reverse permute), replacing the reference's hand-interpreted
BackwardPass/SendGrad/RecvGrad instruction stream (`schedule.py:182-289`).

Weight tying (ref TiedLayerSpec, `module.py:71-82`): the embedding is used
by the first-stage embed and the last-stage LM head; both live in the
replicated (non-pipe-sharded) param group, so the tied-grad allreduce the
reference runs by hand (`module.py:405-409`) is just gradient addition.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from deepspeed_tpu.runtime.mesh import DATA_AXIS, MODEL_AXIS, PIPE_AXIS
from deepspeed_tpu.models.gpt2 import (GPT2Config, GPT2Block,
                                       cross_entropy_loss)


class _StageBlocks(nn.Module):
    """The per-stage body: layers_per_stage sequential GPT2Blocks,
    scanned so params stack as [layers_per_stage, ...]."""
    config: GPT2Config
    layers_per_stage: int

    @nn.compact
    def __call__(self, hidden, deterministic: bool = True):
        cfg = self.config

        class Cell(nn.Module):
            config: GPT2Config

            @nn.compact
            def __call__(self, h, det):
                block_cls = GPT2Block
                if cfg.remat:
                    block_cls = nn.remat(block_cls, prevent_cse=False,
                                         static_argnums=(2,))
                return block_cls(self.config)(h, det), None

        Scanned = nn.scan(
            Cell,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=(nn.broadcast,),
            length=self.layers_per_stage,
            metadata_params={nn.meta.PARTITION_NAME: "layers"},
        )
        hidden, _ = Scanned(cfg, name="blocks")(hidden, deterministic)
        return hidden


class PipelinedGPT2:
    """GPT-2 with parameters grouped for pipelined SPMD execution.

    Param tree: {"embed": {wte, wpe}, "stages": [S, ...]-stacked stage
    params, "head": {ln_f}}. The engine-facing protocol is
    `loss_fn(params, batch, rngs, deterministic)` — identical to
    GPT2ForCausalLM, so the same DeepSpeedEngine step machinery runs it;
    the pipeline lives *inside* the loss function.
    """

    def __init__(self, config: GPT2Config, num_stages: int,
                 num_micro_batches: int):
        assert config.n_layer % num_stages == 0, \
            f"n_layer {config.n_layer} must divide stages {num_stages}"
        self.config = config
        self.num_stages = num_stages
        self.num_micro_batches = num_micro_batches
        self.layers_per_stage = config.n_layer // num_stages
        self.stage_module = _StageBlocks(config, self.layers_per_stage)

    # -- param init ------------------------------------------------------
    def init(self, rng, example_batch):
        cfg = self.config
        ids = example_batch["input_ids"]
        mb = ids.shape[0] // self.num_micro_batches
        t = ids.shape[1]
        rng_e, rng_s, rng_h = jax.random.split(rng, 3)

        embed = {
            "wte": jax.random.normal(rng_e, (cfg.vocab_size, cfg.n_embd),
                                     jnp.float32) * cfg.initializer_range,
            "wpe": jax.random.normal(rng_h, (cfg.n_positions, cfg.n_embd),
                                     jnp.float32) * cfg.initializer_range,
        }
        x = jnp.zeros((mb, t, cfg.n_embd), cfg.dtype)

        def init_stage(key):
            return self.stage_module.init(
                {"params": key, "dropout": key}, x, True)["params"]

        stage_keys = jax.random.split(rng_s, self.num_stages)
        stages = jax.vmap(init_stage)(stage_keys)     # [S, ...] stacked

        head = {
            "ln_f": {"scale": jnp.ones((cfg.n_embd,), jnp.float32),
                     "bias": jnp.zeros((cfg.n_embd,), jnp.float32)},
        }
        return {"embed": embed, "stages": stages, "head": head}

    # -- pipeline pieces -------------------------------------------------
    def _embed(self, embed_params, ids, rng, deterministic):
        cfg = self.config
        t = ids.shape[1]
        h = embed_params["wte"][ids].astype(cfg.dtype) + \
            embed_params["wpe"][:t][None].astype(cfg.dtype)
        if not deterministic and cfg.dropout > 0.0:
            keep = jax.random.bernoulli(rng, 1.0 - cfg.dropout, h.shape)
            h = jnp.where(keep, h / (1.0 - cfg.dropout), 0.0)
        return h

    def _stage_apply(self, stage_params, x, rng, deterministic):
        rngs = {"dropout": rng} if not deterministic else {}
        return self.stage_module.apply({"params": stage_params}, x,
                                       deterministic, rngs=rngs)

    def _head_loss(self, head_params, embed_params, hidden, labels):
        cfg = self.config
        scale = head_params["ln_f"]["scale"]
        bias = head_params["ln_f"]["bias"]
        h32 = hidden.astype(jnp.float32)
        mu = h32.mean(-1, keepdims=True)
        var = ((h32 - mu) ** 2).mean(-1, keepdims=True)
        h32 = (h32 - mu) * jax.lax.rsqrt(var + cfg.layer_norm_epsilon)
        h32 = h32 * scale + bias
        logits = jnp.einsum("btc,vc->btv", h32.astype(cfg.dtype),
                            embed_params["wte"].astype(cfg.dtype))
        return cross_entropy_loss(logits, labels)

    # -- the pipelined loss ---------------------------------------------
    def loss_fn(self, params, batch, rngs=None, deterministic=False,
                mesh=None, **_):
        cfg = self.config
        S = self.num_stages
        M = self.num_micro_batches
        rng = (rngs or {}).get("dropout", jax.random.PRNGKey(0))

        ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate(
                [ids[:, 1:], jnp.full_like(ids[:, :1], -100)], axis=1)
        bsz, t = ids.shape
        assert bsz % M == 0, f"batch {bsz} must divide microbatches {M}"
        mb = bsz // M
        micro_ids = ids.reshape(M, mb, t)
        micro_labels = labels.reshape(M, mb, t)

        def pipe_spec(*rest):
            if mesh is None:
                return None
            return jax.sharding.NamedSharding(
                mesh, PartitionSpec(PIPE_AXIS, *rest))

        x_buf = jnp.zeros((S, mb, t, cfg.n_embd), cfg.dtype)
        if mesh is not None:
            x_buf = jax.lax.with_sharding_constraint(
                x_buf, pipe_spec(DATA_AXIS))

        vstage = jax.vmap(
            lambda p, x, r: self._stage_apply(p, x, r, deterministic))

        def tick(carry, tick_idx):
            x_prev, = carry
            feed_idx = jnp.clip(tick_idx, 0, M - 1)
            tokens = jax.lax.dynamic_index_in_dim(
                micro_ids, feed_idx, 0, keepdims=False)
            x0 = self._embed(params["embed"], tokens,
                             jax.random.fold_in(rng, tick_idx),
                             deterministic)
            x_in = x_prev.at[0].set(x0)
            if mesh is not None:
                x_in = jax.lax.with_sharding_constraint(
                    x_in, pipe_spec(DATA_AXIS))
            stage_rngs = jax.vmap(
                lambda i: jax.random.fold_in(
                    jax.random.fold_in(rng, tick_idx), i + 1000)
            )(jnp.arange(S))
            y = vstage(params["stages"], x_in, stage_rngs)   # [S, mb, t, H]
            if mesh is not None:
                y = jax.lax.with_sharding_constraint(y, pipe_spec(DATA_AXIS))
            out_last = y[-1]
            # rotate: stage i's output becomes stage i+1's next input;
            # slot 0 is overwritten by the next tick's embed feed.
            x_next = jnp.roll(y, 1, axis=0)
            if mesh is not None:
                x_next = jax.lax.with_sharding_constraint(
                    x_next, pipe_spec(DATA_AXIS))
            return (x_next,), out_last

        num_ticks = M + S - 1
        (_,), outs = jax.lax.scan(tick, (x_buf,), jnp.arange(num_ticks))
        # outs: [num_ticks, mb, t, H]; microbatch m exits at tick m + S - 1
        final = outs[S - 1:]                         # [M, mb, t, H]
        hidden = final.reshape(M * mb, t, cfg.n_embd)
        flat_labels = micro_labels.reshape(M * mb, t)
        return self._head_loss(params["head"], params["embed"],
                               hidden, flat_labels)

    # -- sharding specs --------------------------------------------------
    def pipeline_param_specs(self, params):
        """Base PartitionSpecs: stage-stacked leaves get pipe on dim 0
        (+ Megatron TP over `model` on the same rules as GPT2);
        embed/head replicated over pipe."""
        def stage_leaf_spec(path, leaf):
            name = "/".join(str(getattr(p, "key", p)) for p in path)
            nd = np.ndim(leaf)
            spec = [PIPE_AXIS] + [None] * (nd - 1)
            if nd >= 2:
                if "c_attn" in name or "c_fc" in name:
                    spec[-1] = MODEL_AXIS          # column parallel
                elif "c_proj" in name and name.endswith("kernel"):
                    spec[-2] = MODEL_AXIS          # row parallel
            return PartitionSpec(*spec)

        stages = jax.tree_util.tree_map_with_path(
            stage_leaf_spec, params["stages"])

        def repl(leaf):
            return PartitionSpec(*([None] * np.ndim(leaf)))

        return {
            "embed": jax.tree_util.tree_map(repl, params["embed"]),
            "stages": stages,
            "head": jax.tree_util.tree_map(repl, params["head"]),
        }

    # engine hook (same name as GPT2ForCausalLM's TP spec hook)
    def tp_param_specs(self, params):
        return self.pipeline_param_specs(params)
