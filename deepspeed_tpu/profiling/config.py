"""FLOPS-profiler config block (parity with `deepspeed/profiling/config.py`).

On TPU the profile itself comes from XLA HLO cost analysis
(`jitted.lower(...).compile().cost_analysis()`) instead of monkey-patched
torch.nn.functional — see `deepspeed_tpu/profiling/flops_profiler.py`.
"""

from deepspeed_tpu.runtime.config_utils import get_scalar_param

FLOPS_PROFILER = "flops_profiler"

FLOPS_PROFILER_ENABLED = "enabled"
FLOPS_PROFILER_ENABLED_DEFAULT = False

FLOPS_PROFILER_PROFILE_STEP = "profile_step"
FLOPS_PROFILER_PROFILE_STEP_DEFAULT = 1

FLOPS_PROFILER_MODULE_DEPTH = "module_depth"
FLOPS_PROFILER_MODULE_DEPTH_DEFAULT = -1

FLOPS_PROFILER_TOP_MODULES = "top_modules"
FLOPS_PROFILER_TOP_MODULES_DEFAULT = 3

FLOPS_PROFILER_DETAILED = "detailed"
FLOPS_PROFILER_DETAILED_DEFAULT = True


class DeepSpeedFlopsProfilerConfig:
    def __init__(self, param_dict):
        self.enabled = None
        self.profile_step = None
        self.module_depth = None
        self.top_modules = None
        self.detailed = None

        if FLOPS_PROFILER in param_dict:
            d = param_dict[FLOPS_PROFILER]
        else:
            d = {}
        self._initialize(d)

    def _initialize(self, d):
        self.enabled = get_scalar_param(d, FLOPS_PROFILER_ENABLED,
                                        FLOPS_PROFILER_ENABLED_DEFAULT)
        self.profile_step = get_scalar_param(d, FLOPS_PROFILER_PROFILE_STEP,
                                             FLOPS_PROFILER_PROFILE_STEP_DEFAULT)
        self.module_depth = get_scalar_param(d, FLOPS_PROFILER_MODULE_DEPTH,
                                             FLOPS_PROFILER_MODULE_DEPTH_DEFAULT)
        self.top_modules = get_scalar_param(d, FLOPS_PROFILER_TOP_MODULES,
                                            FLOPS_PROFILER_TOP_MODULES_DEFAULT)
        self.detailed = get_scalar_param(d, FLOPS_PROFILER_DETAILED,
                                         FLOPS_PROFILER_DETAILED_DEFAULT)
