"""FLOPS profiler — XLA HLO cost analysis instead of op monkey-patching.

Counterpart of `deepspeed/profiling/flops_profiler/profiler.py:11` (814
LoC). The reference wraps every `torch.nn.functional` entry point with a
flop-counting closure and installs module hooks; under XLA the compiler
already knows the exact cost of the compiled program —
`jitted.lower(args).compile().cost_analysis()` returns flops / bytes
accessed / transcendentals for the whole fused step, and flax's
`nn.tabulate` supplies the per-module breakdown that the reference builds
from hooks. `get_model_profile` (ref `profiler.py:738`) is the standalone
entry point.
"""

import re
import time

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


def _number_to_string(num, units=None, precision=2):
    if units is None:
        if num >= 1e12:
            return f"{num / 1e12:.{precision}f} T"
        if num >= 1e9:
            return f"{num / 1e9:.{precision}f} G"
        if num >= 1e6:
            return f"{num / 1e6:.{precision}f} M"
        if num >= 1e3:
            return f"{num / 1e3:.{precision}f} K"
        return f"{num:.{precision}f} "
    return f"{num:.{precision}f} {units}"


def flops_to_string(flops, units=None, precision=2):
    return _number_to_string(flops, units, precision) + "FLOPS"


def params_to_string(params_num, units=None, precision=2):
    return _number_to_string(params_num, units, precision).rstrip() or "0"


def duration_to_string(duration, units=None, precision=2):
    if duration >= 1:
        return f"{duration:.{precision}f} s"
    if duration >= 1e-3:
        return f"{duration * 1e3:.{precision}f} ms"
    return f"{duration * 1e6:.{precision}f} us"


def num_params(params) -> int:
    return int(sum(np.prod(l.shape) for l in
                   jax.tree_util.tree_leaves(params)))


def cost_analysis_of(fn, *args, **kwargs):
    """HLO cost analysis of `fn(*args)`: dict with 'flops',
    'bytes accessed', 'transcendentals' (keys mirror XLA's names)."""
    jitted = fn if isinstance(fn, jax.stages.Wrapped) else jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    cost = compiled.cost_analysis() or {}
    # some backends return a list of per-computation dicts
    if isinstance(cost, (list, tuple)):
        merged = {}
        for c in cost:
            for k, v in c.items():
                merged[k] = merged.get(k, 0.0) + v
        cost = merged
    return cost


# ----------------------------------------------------------------------
# per-fusion breakdown: where inside the compiled step the time goes
# ----------------------------------------------------------------------
# `compiled.cost_analysis()` is one aggregate number for the whole
# program; ranking the individual FUSIONS is what tells you which part
# of the step to fix. The optimized HLO text lists every fusion /
# custom-call (Pallas kernel) / bare dot with its operand and result
# shapes, so each one gets a roofline time estimate
# max(flops / peak_flops, bytes / hbm_bw) and the table below is the
# per-fusion time breakdown the bench publishes (top-3 sinks).

_SHAPE_RE = re.compile(r"(pred|[fbsu](?:f8\w*|\d+)|f8\w+)\[([\d,]*)\]")
_ELEM_BYTES = {"pred": 1, "s8": 1, "u8": 1, "f8": 1, "bf16": 2,
               "f16": 2, "s16": 2, "u16": 2, "f32": 4, "s32": 4,
               "u32": 4, "f64": 8, "s64": 8, "u64": 8}
# VPU transcendentals: roughly an order of magnitude costlier than a
# mul/add lane op; counted so exp/erf-heavy elementwise fusions rank
# above same-byte-count copy fusions
_TRANSCENDENTAL_RE = re.compile(
    r"\b(exponential|exponential-minus-one|log|log-plus-one|tanh|erf|"
    r"rsqrt|sqrt|power|sine|cosine|atan2|logistic)\(")


def _shape_bytes(fragment):
    """Total bytes of every shape literal in an HLO text fragment."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(fragment):
        elems = 1
        if dims:
            for d in dims.split(","):
                elems *= int(d)
        key = dtype if dtype in _ELEM_BYTES else dtype[:2]
        total += elems * _ELEM_BYTES.get(key, 4)
    return total


def _first_shape_elems(fragment):
    m = _SHAPE_RE.search(fragment)
    if not m:
        return 0
    elems = 1
    if m.group(2):
        for d in m.group(2).split(","):
            elems *= int(d)
    return elems


def _dot_flops(line):
    """2 * prod(result dims) * prod(lhs contracting dims) for one
    `... = <shape> dot(<lhs>, <rhs>), lhs_contracting_dims={...}` line."""
    head, _, tail = line.partition(" dot(")
    out_elems = _first_shape_elems(head.split("=", 1)[-1])
    lhs = _SHAPE_RE.search(tail)
    if not lhs or not out_elems:
        return 0
    lhs_dims = [int(d) for d in lhs.group(2).split(",")] if lhs.group(2) \
        else []
    mc = re.search(r"lhs_contracting_dims=\{([\d,]*)\}", line)
    contract = 1
    if mc and mc.group(1):
        for d in mc.group(1).split(","):
            contract *= lhs_dims[int(d)] if int(d) < len(lhs_dims) else 1
    return 2 * out_elems * contract


def _parse_hlo_computations(text):
    """HLO module text -> {comp_name: [instruction lines]}."""
    comps = {}
    cur = None
    for raw in text.splitlines():
        line = raw.strip()
        if cur is None:
            # the param list nests parens for tuple-typed params (every
            # while body: `(arg.1: (s32[], f32[64,64]))`) — a lazy group
            # that can grow past inner `)` is required or those
            # computations never parse and their rows are dropped
            m = re.match(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*(?:\(.*?\))?"
                         r"\s*->.*\{$", line)
            if m:
                cur = m.group(1)
                comps[cur] = []
        elif line == "}" or line.startswith("} "):
            cur = None
        elif line and not line.startswith("//"):
            comps[cur].append(line)
    return comps


def _comp_flops_transcendentals(lines):
    flops = 0
    trans = 0
    for line in lines:
        if " dot(" in line:
            flops += _dot_flops(line)
        m = _TRANSCENDENTAL_RE.search(line)
        if m:
            trans += _first_shape_elems(line.split("=", 1)[-1])
    return flops, trans


# a callee list is EITHER braced (branch_computations={%a, %b}) or a
# single unbraced name (calls=%f, body=%b, condition=%c) — an unbraced
# match must stop at the name so `condition=%c, body=%b` yields two
# matches instead of one capture that swallows the literal ", body"
_CALLS_RE = re.compile(r"(?:calls|to_apply|body|condition|branch_computations)="
                       r"(?:\{([^}]*)\}|%?([\w.\-]+))")
_TRIP_RE = re.compile(r'known_trip_count[\\"{:\s]+n[\\"\s:]+(\d+)')
_PARAM_DEF_RE = re.compile(r"^(?:ROOT )?%([\w.\-]+) = [^ ]+ parameter\(\d+\)")


def _sliced_fusion_bytes(body):
    """Byte estimate for a fusion that slices its operands, or None when
    the call-site estimate (full operand + result shapes) is already
    right.  XLA's scan lowering emits loop fusions whose ROOT is a
    dynamic-update-slice of a carry parameter (aliased in place) and
    whose reads go through dynamic-slice — per call they touch ONE
    layer's slice, so charging the full stacked buffer on every trip
    inflates their bytes by ~trip_count× and a near-free carry update
    tops the sink table above every real matmul."""
    root = next((l for l in body if l.startswith("ROOT ")), "")
    root_dus = " dynamic-update-slice(" in root
    if not root_dus and not any(" dynamic-slice(" in l for l in body):
        return None
    param_full = {}
    for line in body:
        m = _PARAM_DEF_RE.match(line)
        if m:
            param_full[m.group(1)] = _shape_bytes(
                line.split("=", 1)[1].split(" parameter", 1)[0])
    if not param_full:
        return None
    sliced_reads = {}      # param -> slice bytes actually read
    whole_use = set()      # params touched any other way: full charge
    carries = set()        # DUS first operands: in-place, no read
    writes = 0
    for line in body:
        if _PARAM_DEF_RE.match(line):
            continue
        rhs = line.split("=", 1)[-1]
        opm = re.match(r"\s*\S+\s+([\w\-]+)\(", rhs)
        op = opm.group(1) if opm else ""
        # operand list = after the op's "(", before any metadata (whose
        # op_name strings contain parens of their own)
        tail = rhs.split("(", 1)[1] if "(" in rhs else rhs
        names = re.findall(r"%([\w.\-]+)", tail.split(", metadata=", 1)[0])
        for name in set(names) & set(param_full):
            if op == "dynamic-update-slice" and names and \
                    names[0] == name:
                carries.add(name)
                # index operands may reuse the carry name; any other
                # position is a real full read
                if names.count(name) > 1:
                    whole_use.add(name)
            elif op == "dynamic-slice" and names and names[0] == name:
                # read = the slice RESULT shape (first shape on the rhs)
                sliced_reads[name] = sliced_reads.get(name, 0) + \
                    _shape_bytes(rhs.split(" dynamic-slice(", 1)[0])
            else:
                whole_use.add(name)
        if op == "dynamic-update-slice":
            shapes = _SHAPE_RE.findall(
                tail.split(", metadata=", 1)[0])
            if len(shapes) >= 2:
                dtype, dims = shapes[1]
                elems = 1
                for d in (dims.split(",") if dims else []):
                    elems *= int(d)
                key = dtype if dtype in _ELEM_BYTES else dtype[:2]
                writes += elems * _ELEM_BYTES.get(key, 4)
    reads = 0
    for name, full in param_full.items():
        if name in whole_use:
            reads += full
        elif name in sliced_reads:
            reads += min(sliced_reads[name], full)
        elif name in carries:
            reads += 0
        else:
            reads += full
    if not root_dus:
        writes = _shape_bytes(root.split("=", 1)[-1].split("(", 1)[0])
    return reads + writes


def device_peak_specs(device=None):
    """(peak_bf16_flops, hbm_GBps) for the current/given device from
    the nominal spec table; a generic 100 TF / 800 GB/s off-table
    (rankings and time_pct are scale-free either way).  Unknown
    backends (CPU) return the generic numbers; callers that need "no
    peak known" semantics (MFU) should check the platform first."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    table = {"v4": (275e12, 1228.0), "v5 lite": (197e12, 819.0),
             "v5e": (197e12, 819.0), "v5p": (459e12, 2765.0),
             "v6": (918e12, 1640.0)}
    for k, (p, b) in table.items():
        if k in kind:
            return p, b
    return 100e12, 800.0


# Pallas kernels appear in optimized HLO as custom-calls; the kernel
# identity lives in the op_name metadata (the jaxpr scope path, which
# includes any jax.named_scope the op wrapper opened and the
# pallas_call frame) and, failing that, the custom_call_target.
_PALLAS_NAME_RE = re.compile(r"pallas_call\[?[^\]\"]*?name=([\w./\-]+)")


def _custom_call_label(line):
    """Best-effort kernel label for a custom-call HLO line: the Pallas
    kernel name out of op_name metadata (`pallas_call[... name=...]`,
    or the innermost non-pallas scope segment — e.g. the
    jax.named_scope the fused-ops wrappers open), else the
    custom_call_target."""
    mo = re.search(r'op_name="([^"]+)"', line)
    if mo:
        op = mo.group(1)
        mk = _PALLAS_NAME_RE.search(op)
        if mk:
            return mk.group(1)
        if "pallas_call" in op:
            segs = [s for s in op.split("/")
                    if s and "pallas_call" not in s
                    and not s.startswith(("jit(", "jvp(", "transpose("))]
            if segs:
                return segs[-1]
    mt = re.search(r'custom_call_target="([^"]+)"', line)
    return mt.group(1) if mt else None


def per_fusion_costs(fn, *args, peak_flops=None, hbm_gbps=None, **kwargs):
    """Roofline time breakdown of `fn(*args)`'s optimized HLO, one row
    per top-level fusion / custom-call (Pallas kernel) / bare dot.

    Returns rows sorted by estimated time, each
    {name, op, kind, kernel, flops, bytes, transcendentals, calls,
    est_us, time_pct}: `op` is the semantic op_name metadata
    (model-layer path), `kernel` the resolved kernel label for
    custom-calls (Pallas kernel name / named_scope / call target — so
    the fused epilogue and flash kernels are attributable instead of
    an opaque "custom-call"), `calls` the executed multiplicity
    (propagated through call/while nesting; a while whose trip count
    the compiler did not record counts as 1 and the row says so via
    calls=1). est_us = max(flops/peak, bytes/bw [, transcendental
    time]) — an ESTIMATE for ranking sinks, not a measurement;
    custom-calls have no visible flops, so theirs is bytes-only (a
    lower bound).

    peak_flops/hbm_gbps default to the current device's nominal specs
    when known (v4/v5e/v5p table) else a generic 100 TF / 800 GB/s —
    the ranking and time_pct are scale-free either way."""
    jitted = fn if isinstance(fn, jax.stages.Wrapped) else jax.jit(fn)
    text = jitted.lower(*args, **kwargs).compile().as_text()
    return per_fusion_costs_from_text(text, peak_flops=peak_flops,
                                      hbm_gbps=hbm_gbps)


def per_fusion_costs_from_text(text, peak_flops=None, hbm_gbps=None):
    """`per_fusion_costs` off already-obtained optimized HLO module
    text (also the unit-testable seam for the parsing/labeling
    logic)."""
    if peak_flops is None or hbm_gbps is None:
        pf, bw = device_peak_specs()
        peak_flops = peak_flops or pf
        hbm_gbps = hbm_gbps or bw
    comps = _parse_hlo_computations(text)

    # executed multiplicity per computation (entry = the one whose name
    # the module repeats in `ENTRY`; approximated as the computation
    # nobody calls)
    called_by_fusion = set()
    callees = {}
    for name, lines in comps.items():
        for line in lines:
            targets = []
            for m in _CALLS_RE.finditer(line):
                names = m.group(1) if m.group(1) is not None else m.group(2)
                targets += [t.strip().lstrip("%")
                            for t in names.split(",") if t.strip()]
            if not targets:
                continue
            mult = 1
            if " while(" in line:
                t = _TRIP_RE.search(line)
                mult = int(t.group(1)) if t else 1
            callees.setdefault(name, []).append((targets, mult))
            if " fusion(" in line:
                called_by_fusion.update(targets)
    all_called = {t for calls in callees.values()
                  for targets, _ in calls for t in targets}
    mults = {name: (1 if name not in all_called else 0)
             for name in comps}
    # propagate in a few passes (call graphs are shallow; cycles don't
    # occur in HLO)
    for _ in range(16):
        changed = False
        for name, calls in callees.items():
            for targets, mult in calls:
                for t in targets:
                    if t in mults and mults[name]:
                        new = mults[name] * mult
                        if new > mults[t]:
                            mults[t] = new
                            changed = True
        if not changed:
            break

    rows = []
    for name, lines in comps.items():
        if name in called_by_fusion or not mults.get(name):
            continue
        for line in lines:
            kind = None
            if " fusion(" in line:
                kind = "fusion"
            elif " custom-call(" in line:
                kind = "custom-call"
            elif " dot(" in line:
                kind = "dot"
            elif " convolution(" in line:
                kind = "convolution"
            if kind is None:
                continue
            iname = line.split("=", 1)[0].strip()
            if iname.startswith("ROOT "):
                iname = iname[5:]
            iname = iname.lstrip("%")
            args_part = line.split("(", 1)[-1].split("), ")[0]
            out_part = line.split("=", 1)[-1].split("(", 1)[0]
            nbytes = _shape_bytes(args_part) + _shape_bytes(out_part)
            flops, trans = 0, 0
            if kind == "fusion":
                mcall = re.search(r"calls=%?([\w.\-]+)", line)
                if mcall and mcall.group(1) in comps:
                    flops, trans = _comp_flops_transcendentals(
                        comps[mcall.group(1)])
                    sliced = _sliced_fusion_bytes(comps[mcall.group(1)])
                    if sliced is not None:
                        nbytes = sliced
            elif kind in ("dot", "convolution"):
                flops = _dot_flops(line) if kind == "dot" else 0
            mop = re.search(r'op_name="([^"]+)"', line)
            kernel = _custom_call_label(line) if kind == "custom-call" \
                else None
            calls = mults.get(name, 1)
            est_s = max(flops / peak_flops,
                        nbytes / (hbm_gbps * 1e9),
                        # ~16 transcendental results per lane-cycle at
                        # ~1 GHz-ish VPU throughput: crude, but ranks
                        # erf/exp chains above pure copies
                        trans / (peak_flops / 16.0)) * calls
            rows.append({
                "name": iname, "op": mop.group(1) if mop else "",
                "kind": kind, "kernel": kernel,
                "flops": int(flops * calls),
                "bytes": int(nbytes * calls),
                "transcendentals": int(trans * calls),
                "calls": calls, "est_us": est_s * 1e6})
    total = sum(r["est_us"] for r in rows) or 1.0
    for r in rows:
        r["time_pct"] = round(100.0 * r["est_us"] / total, 2)
        r["est_us"] = round(r["est_us"], 2)
    rows.sort(key=lambda r: -r["est_us"])
    return rows


def top_fusion_sinks(fn, *args, top=3, **kwargs):
    """Compact top-N per-fusion sink table (bench extras): list of
    {op, kind, est_us, time_pct, flops, bytes, calls} rows (+ `kernel`
    for custom-calls — the Pallas kernel label, which also becomes the
    `op` fallback so a Pallas row is never an opaque "custom-call")."""
    rows = per_fusion_costs(fn, *args, **kwargs)
    out = []
    for r in rows[:top]:
        row = {"op": (r["op"] or r.get("kernel") or r["name"])[-120:],
               "kind": r["kind"],
               "est_us": r["est_us"], "time_pct": r["time_pct"],
               "flops": r["flops"], "bytes": r["bytes"],
               "calls": r["calls"]}
        if r.get("kernel"):
            row["kernel"] = r["kernel"]
        out.append(row)
    return out


class FlopsProfiler:
    """Profiles one step of a jitted function (ref `profiler.py:11`).

    Usage (engine drives this at `profile_step`, ref `engine.py:803-832`):
        prof = FlopsProfiler(model)
        prof.start_profile()
        cost = prof.profile_jitted(step_fn, *args)   # or measure manually
        prof.stop_profile()
    """

    def __init__(self, model=None, config=None):
        self.model = model
        self.config = config
        self.started = False
        self.total_flops = 0.0
        self.total_bytes = 0.0
        self.total_params = 0
        self.total_duration = 0.0

    def start_profile(self, ignore_list=None):
        self.started = True
        self.total_flops = 0.0
        self.total_bytes = 0.0
        self.total_duration = 0.0

    def stop_profile(self):
        self.started = False

    def end_profile(self):
        self.stop_profile()

    def profile_jitted(self, fn, *args, measure_time=True, **kwargs):
        cost = cost_analysis_of(fn, *args, **kwargs)
        self.total_flops = float(cost.get("flops", 0.0))
        self.total_bytes = float(cost.get("bytes accessed", 0.0))
        if measure_time:
            jitted = fn if isinstance(fn, jax.stages.Wrapped) else \
                jax.jit(fn)
            out = jitted(*args, **kwargs)       # warm (cache hit)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            out = jitted(*args, **kwargs)
            jax.block_until_ready(out)
            self.total_duration = time.perf_counter() - t0
        return cost

    # -- accessors (ref profiler.py naming) -----------------------------
    def get_total_flops(self, as_string=False):
        return flops_to_string(self.total_flops) if as_string \
            else self.total_flops

    def get_total_params(self, as_string=False):
        return params_to_string(self.total_params) if as_string \
            else self.total_params

    def get_total_duration(self, as_string=False):
        return duration_to_string(self.total_duration) if as_string \
            else self.total_duration

    def print_model_profile(self, profile_step=1, module_depth=-1,
                            top_modules=3, detailed=True):
        tflops = self.total_flops / self.total_duration / 1e12 \
            if self.total_duration else 0.0
        logger.info(
            f"\n-------------------------- DeepSpeed Flops Profiler "
            f"--------------------------\n"
            f"Profile at step {profile_step}:\n"
            f"  params:            {params_to_string(self.total_params)}\n"
            f"  fwd+bwd+step flops:{flops_to_string(self.total_flops)}\n"
            f"  HBM bytes:         {_number_to_string(self.total_bytes)}B\n"
            f"  step latency:      "
            f"{duration_to_string(self.total_duration)}\n"
            f"  achieved:          {tflops:.2f} TFLOPS")

    def print_model_aggregated_profile(self, module_depth=-1,
                                       top_modules=3):
        self.print_model_profile(module_depth=module_depth,
                                 top_modules=top_modules)


def get_model_profile(model=None,
                      input_shape=None,
                      args=None,
                      kwargs=None,
                      print_profile=True,
                      detailed=True,
                      module_depth=-1,
                      top_modules=3,
                      warm_up=1,
                      as_string=True,
                      ignore_modules=None,
                      fn=None,
                      params=None):
    """Standalone profile (ref `profiler.py:738`): returns (flops,
    macs, params). Accepts either a callable `fn(*args)` (jittable) or a
    flax `model` + example `args`.

    With a flax model, the per-module table comes from `nn.tabulate`
    (the hook-built tree of the reference)."""
    kwargs = kwargs or {}
    table = None
    if fn is None:
        assert model is not None and (args is not None or
                                      input_shape is not None)
        if args is None:
            args = (np.zeros(input_shape, np.float32),)
        variables = model.init(jax.random.PRNGKey(0), *args, **kwargs)

        def fn(*a):
            return model.apply(variables, *a, **kwargs)
        params = variables
        try:
            import flax.linen as nn
            table = nn.tabulate(
                model, jax.random.PRNGKey(0),
                compute_flops=True, compute_vjp_flops=detailed,
                depth=None if module_depth == -1 else module_depth)(
                    *args, **kwargs)
        except Exception:
            logger.warning("nn.tabulate breakdown unavailable",
                           exc_info=True)
    assert args is not None

    prof = FlopsProfiler(model)
    prof.total_params = num_params(params) if params is not None else 0
    prof.start_profile()
    prof.profile_jitted(fn, *args)
    prof.stop_profile()

    if print_profile:
        prof.print_model_profile(module_depth=module_depth,
                                 top_modules=top_modules,
                                 detailed=detailed)
        if table is not None and detailed:
            logger.info("\n" + table)

    flops = prof.get_total_flops(as_string)
    macs = prof.total_flops / 2
    if as_string:
        macs = _number_to_string(macs) + "MACs"
    n = prof.get_total_params(as_string)
    return flops, macs, n
