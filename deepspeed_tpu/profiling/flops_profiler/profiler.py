"""FLOPS profiler — XLA HLO cost analysis instead of op monkey-patching.

Counterpart of `deepspeed/profiling/flops_profiler/profiler.py:11` (814
LoC). The reference wraps every `torch.nn.functional` entry point with a
flop-counting closure and installs module hooks; under XLA the compiler
already knows the exact cost of the compiled program —
`jitted.lower(args).compile().cost_analysis()` returns flops / bytes
accessed / transcendentals for the whole fused step, and flax's
`nn.tabulate` supplies the per-module breakdown that the reference builds
from hooks. `get_model_profile` (ref `profiler.py:738`) is the standalone
entry point.
"""

import time

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


def _number_to_string(num, units=None, precision=2):
    if units is None:
        if num >= 1e12:
            return f"{num / 1e12:.{precision}f} T"
        if num >= 1e9:
            return f"{num / 1e9:.{precision}f} G"
        if num >= 1e6:
            return f"{num / 1e6:.{precision}f} M"
        if num >= 1e3:
            return f"{num / 1e3:.{precision}f} K"
        return f"{num:.{precision}f} "
    return f"{num:.{precision}f} {units}"


def flops_to_string(flops, units=None, precision=2):
    return _number_to_string(flops, units, precision) + "FLOPS"


def params_to_string(params_num, units=None, precision=2):
    return _number_to_string(params_num, units, precision).rstrip() or "0"


def duration_to_string(duration, units=None, precision=2):
    if duration >= 1:
        return f"{duration:.{precision}f} s"
    if duration >= 1e-3:
        return f"{duration * 1e3:.{precision}f} ms"
    return f"{duration * 1e6:.{precision}f} us"


def num_params(params) -> int:
    return int(sum(np.prod(l.shape) for l in
                   jax.tree_util.tree_leaves(params)))


def cost_analysis_of(fn, *args, **kwargs):
    """HLO cost analysis of `fn(*args)`: dict with 'flops',
    'bytes accessed', 'transcendentals' (keys mirror XLA's names)."""
    jitted = fn if isinstance(fn, jax.stages.Wrapped) else jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    cost = compiled.cost_analysis() or {}
    # some backends return a list of per-computation dicts
    if isinstance(cost, (list, tuple)):
        merged = {}
        for c in cost:
            for k, v in c.items():
                merged[k] = merged.get(k, 0.0) + v
        cost = merged
    return cost


class FlopsProfiler:
    """Profiles one step of a jitted function (ref `profiler.py:11`).

    Usage (engine drives this at `profile_step`, ref `engine.py:803-832`):
        prof = FlopsProfiler(model)
        prof.start_profile()
        cost = prof.profile_jitted(step_fn, *args)   # or measure manually
        prof.stop_profile()
    """

    def __init__(self, model=None, config=None):
        self.model = model
        self.config = config
        self.started = False
        self.total_flops = 0.0
        self.total_bytes = 0.0
        self.total_params = 0
        self.total_duration = 0.0

    def start_profile(self, ignore_list=None):
        self.started = True
        self.total_flops = 0.0
        self.total_bytes = 0.0
        self.total_duration = 0.0

    def stop_profile(self):
        self.started = False

    def end_profile(self):
        self.stop_profile()

    def profile_jitted(self, fn, *args, measure_time=True, **kwargs):
        cost = cost_analysis_of(fn, *args, **kwargs)
        self.total_flops = float(cost.get("flops", 0.0))
        self.total_bytes = float(cost.get("bytes accessed", 0.0))
        if measure_time:
            jitted = fn if isinstance(fn, jax.stages.Wrapped) else \
                jax.jit(fn)
            out = jitted(*args, **kwargs)       # warm (cache hit)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            out = jitted(*args, **kwargs)
            jax.block_until_ready(out)
            self.total_duration = time.perf_counter() - t0
        return cost

    # -- accessors (ref profiler.py naming) -----------------------------
    def get_total_flops(self, as_string=False):
        return flops_to_string(self.total_flops) if as_string \
            else self.total_flops

    def get_total_params(self, as_string=False):
        return params_to_string(self.total_params) if as_string \
            else self.total_params

    def get_total_duration(self, as_string=False):
        return duration_to_string(self.total_duration) if as_string \
            else self.total_duration

    def print_model_profile(self, profile_step=1, module_depth=-1,
                            top_modules=3, detailed=True):
        tflops = self.total_flops / self.total_duration / 1e12 \
            if self.total_duration else 0.0
        logger.info(
            f"\n-------------------------- DeepSpeed Flops Profiler "
            f"--------------------------\n"
            f"Profile at step {profile_step}:\n"
            f"  params:            {params_to_string(self.total_params)}\n"
            f"  fwd+bwd+step flops:{flops_to_string(self.total_flops)}\n"
            f"  HBM bytes:         {_number_to_string(self.total_bytes)}B\n"
            f"  step latency:      "
            f"{duration_to_string(self.total_duration)}\n"
            f"  achieved:          {tflops:.2f} TFLOPS")

    def print_model_aggregated_profile(self, module_depth=-1,
                                       top_modules=3):
        self.print_model_profile(module_depth=module_depth,
                                 top_modules=top_modules)


def get_model_profile(model=None,
                      input_shape=None,
                      args=None,
                      kwargs=None,
                      print_profile=True,
                      detailed=True,
                      module_depth=-1,
                      top_modules=3,
                      warm_up=1,
                      as_string=True,
                      ignore_modules=None,
                      fn=None,
                      params=None):
    """Standalone profile (ref `profiler.py:738`): returns (flops,
    macs, params). Accepts either a callable `fn(*args)` (jittable) or a
    flax `model` + example `args`.

    With a flax model, the per-module table comes from `nn.tabulate`
    (the hook-built tree of the reference)."""
    kwargs = kwargs or {}
    table = None
    if fn is None:
        assert model is not None and (args is not None or
                                      input_shape is not None)
        if args is None:
            args = (np.zeros(input_shape, np.float32),)
        variables = model.init(jax.random.PRNGKey(0), *args, **kwargs)

        def fn(*a):
            return model.apply(variables, *a, **kwargs)
        params = variables
        try:
            import flax.linen as nn
            table = nn.tabulate(
                model, jax.random.PRNGKey(0),
                compute_flops=True, compute_vjp_flops=detailed,
                depth=None if module_depth == -1 else module_depth)(
                    *args, **kwargs)
        except Exception as e:
            logger.warning(f"nn.tabulate breakdown unavailable: {e}")
    assert args is not None

    prof = FlopsProfiler(model)
    prof.total_params = num_params(params) if params is not None else 0
    prof.start_profile()
    prof.profile_jitted(fn, *args)
    prof.stop_profile()

    if print_profile:
        prof.print_model_profile(module_depth=module_depth,
                                 top_modules=top_modules,
                                 detailed=detailed)
        if table is not None and detailed:
            logger.info("\n" + table)

    flops = prof.get_total_flops(as_string)
    macs = prof.total_flops / 2
    if as_string:
        macs = _number_to_string(macs) + "MACs"
    n = prof.get_total_params(as_string)
    return flops, macs, n
