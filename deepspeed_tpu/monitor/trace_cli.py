"""`ds_trace` — merge and summarize Perfetto trace shards.

    ds_trace merge runA/trace_rank*.json -o merged.json
    ds_trace summary runA/trace_rank0.json [more.json ...]

`merge` concatenates per-rank shards (events are rank-tagged by `pid`
and anchored on the unix clock, so concatenation + sort IS the merge)
into one file Perfetto opens as a multi-rank timeline. `summary`
prints per-track busy/occupancy and — when pipeline events are present
— the measured bubble fraction next to the schedule's analytic
(p-1)/(v·m+p-1), the number the interleaved-1F1B work exists to move.
When the trace carries the memory ledger's counter tracks it also
prints per-category last/peak bytes and — when a memory plan rode in
the trace metadata — the per-component plan-vs-measured deltas.

`summary --serving` restricts the output to the serving view: the
per-request p50/p99 queue-wait / TTFT / per-token decode latency and
goodput-vs-throughput, recomputed from the `serving_request` finish
instants the ServingTracker stamps (monitor/serving.py).
"""

import argparse
import json
import sys

from deepspeed_tpu.monitor.trace_export import (load_trace,
                                                merge_traces,
                                                summarize_trace)


def _cmd_merge(args):
    docs = [load_trace(p) for p in args.paths]
    merged = merge_traces(docs)
    out = args.output or "trace_merged.json"
    with open(out, "w") as f:
        json.dump(merged, f, separators=(",", ":"))
    print(f"merged {len(docs)} shard(s), "
          f"{len(merged['traceEvents'])} events -> {out}")
    _print_summary(merged)
    return 0


def _cmd_summary(args):
    docs = [load_trace(p) for p in args.paths]
    doc = docs[0] if len(docs) == 1 else merge_traces(docs)
    if getattr(args, "serving", False):
        s = summarize_trace(doc)
        serving = s.get("serving")
        if not serving:
            print("no serving events in trace (run with a monitor "
                  "block + inference.observability enabled)")
            return 1
        _print_serving(serving)
        return 0
    _print_summary(doc)
    return 0


def _print_summary(doc):
    s = summarize_trace(doc)
    tracks = s.get("tracks", {})
    if tracks:
        width = max(len(n) for n in tracks)
        print(f"{'track'.ljust(width)}  events     busy_ms  occupancy")
        for name, tr in tracks.items():
            print(f"{name.ljust(width)}  {tr['events']:6d}  "
                  f"{tr['busy_ms']:10.3f}  {tr['occupancy']:9.4f}")
    pipe = s.get("pipeline")
    if pipe:
        print("pipeline:")
        print(f"  stages={pipe['stages']} "
              f"dispatch_windows={pipe['dispatch_windows']} "
              f"occupancy={pipe['occupancy']}")
        line = f"  bubble_fraction={pipe['bubble_fraction']}"
        if pipe.get("analytic_bubble_fraction") is not None:
            line += (" (schedule analytic "
                     f"{pipe['analytic_bubble_fraction']})")
        print(line)
        sched = pipe.get("schedule")
        if sched:
            print(f"  schedule: p={sched.get('stages')} "
                  f"m={sched.get('micro_batches')} "
                  f"v={sched.get('num_virtual_stages')} "
                  f"ticks={sched.get('ticks')}")
    mem = s.get("memory")
    if mem:
        _print_memory(mem)
    serving = s.get("serving")
    if serving:
        _print_serving(serving)
    if not tracks and not pipe and not mem and not serving:
        print("no complete events in trace")


def _fmt_gib(b):
    return f"{b / 2**30:.3f}"


def _print_memory(mem):
    """The memory ledger's counter tracks: final composition + peak
    per category, and plan-vs-measured deltas when a memory plan rode
    in the trace metadata."""
    for series in ("hbm_bytes", "host_bytes"):
        rows = mem.get(series)
        if not rows:
            continue
        print(f"memory ({series.split('_')[0]}):")
        width = max(len(k) for k in rows)
        print(f"  {'category'.ljust(width)}   last_gib   peak_gib")
        for name, r in rows.items():
            print(f"  {name.ljust(width)}  {_fmt_gib(r['last_bytes']):>9}"
                  f"  {_fmt_gib(r['peak_bytes']):>9}")
    pvm = mem.get("plan_vs_measured")
    if pvm:
        print("memory plan vs measured (per-device, peak):")
        width = max(len(k) for k in pvm)
        print(f"  {'component'.ljust(width)}  planned_gib  "
              "measured_gib  delta_pct")
        for comp, r in pvm.items():
            planned = "-" if r["planned_bytes"] is None else \
                _fmt_gib(r["planned_bytes"])
            got = "-" if r["measured_bytes"] is None else \
                _fmt_gib(r["measured_bytes"])
            delta = "-" if r["delta_pct"] is None else \
                f"{r['delta_pct']:+.2f}"
            print(f"  {comp.ljust(width)}  {planned:>11}  {got:>12}  "
                  f"{delta:>9}")


def _print_serving(s):
    """Per-request serving stats recomputed from the `serving_request`
    finish instants (fence-granularity host stamps — see
    docs/inference.md "Observability")."""
    print("serving (per-request, fence granularity):")
    good = s.get("goodput_fraction")
    share = s.get("queue_wait_share")
    print(f"  requests={s['requests']} new_tokens={s['new_tokens']} "
          f"goodput_tokens={s['goodput_tokens']}"
          + ("" if good is None else f" goodput_fraction={good}")
          + ("" if share is None else f" queue_wait_share={share}"))
    print(f"  {'metric'.ljust(12)}  {'p50_ms':>9}  {'p99_ms':>9}")
    for label, key in (("queue_wait", "queued_ms"),
                       ("ttft", "ttft_ms"),
                       ("token", "token_ms")):
        row = s.get(key) or {}

        def fmt(v):
            return "-" if v is None else f"{v:.3f}"

        print(f"  {label.ljust(12)}  {fmt(row.get('p50')):>9}  "
              f"{fmt(row.get('p99')):>9}")


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds_trace",
        description="merge / summarize deepspeed-tpu Perfetto traces")
    sub = parser.add_subparsers(dest="cmd", required=True)
    m = sub.add_parser("merge", help="merge per-rank trace shards")
    m.add_argument("paths", nargs="+")
    m.add_argument("-o", "--output", default=None)
    m.set_defaults(fn=_cmd_merge)
    s = sub.add_parser("summary",
                       help="per-track occupancy + pipeline bubble")
    s.add_argument("paths", nargs="+")
    s.add_argument("--serving", action="store_true",
                   help="per-request serving view: p50/p99 queue-wait/"
                        "TTFT/per-token latency + goodput vs "
                        "throughput")
    s.set_defaults(fn=_cmd_summary)
    args = parser.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        # `ds_trace summary | head` closing stdout is not an error
        try:
            sys.stdout.close()
        except Exception:  # ds-lint: allow[BROADEXC] closing an already-broken pipe; any error here is noise on exit
            pass
        return 0


cli_main = main

if __name__ == "__main__":
    sys.exit(main())
