"""Device-side numerics health: per-layer/per-group accumulators with
first-NaN attribution.

A loss blow-up's post-mortem question is never "did it NaN" (the
overflow flag says so) but "WHERE did it first NaN" — which layer's
activations, which parameter group's gradients. Answering that with
host-side inspection would re-synchronize the hot path per step;
instead the stats are computed INSIDE the jitted step, on tensors the
step already materializes:

  * activation stats — (abs-max, mean|x|, nonfinite count) at every
    layer boundary of layer-exposing models (PipelineModule's chained
    loss taps each boundary); a layer whose input stats are finite and
    whose output stats are not is the first-NaN layer;
  * gradient stats — (L2 norm, abs-max, nonfinite count) per top-level
    parameter group, computed on the unscaled gradients right before
    the overflow vote — the "overflow source" per group.

The per-step cost is a few fused reductions over tensors already in
registers/HBM, and the outputs are tiny device arrays ([L,3]/[G,3])
the registry RETAINS exactly like the loss scalar — a list append, no
dispatch, no sync — and drains in the same single per-fence
`device_get` (the guard test pins zero new per-step syncs). Long
windows compact through `fold_entries` (a handful of eager jnp reduces
alongside the registry's scalar compaction), which preserves the
first-nonfinite (window-step, kind, index) candidate on device before
per-step granularity is discarded.

Stats layout (always float32):
  activation rows: [absmax, mean_abs, nonfinite_count]
  gradient rows:   [l2_norm, absmax, nonfinite_flag]  (0/1 per step;
                   window-summed it counts affected steps — the flag
                   derives free from the two reductions, see
                   grad_group_stats)
"""

import numpy as np

KIND_ACT = 0
KIND_GRAD = 1

ACT_COLS = ("absmax", "mean_abs", "nonfinite")
GRAD_COLS = ("norm", "absmax", "nonfinite")


# ----------------------------------------------------------------------
# inside-jit stat computation
# ----------------------------------------------------------------------
def tensor_stats(x):
    """[3] f32 activation stats for one boundary tensor: abs-max,
    mean|x|, nonfinite count. Reductions only — no data-dependent
    control flow, so they trace into any jitted step."""
    import jax.numpy as jnp
    xf = x.astype(jnp.float32)
    ax = jnp.abs(xf)
    return jnp.stack([
        jnp.max(ax),
        jnp.mean(ax),
        jnp.sum(~jnp.isfinite(xf)).astype(jnp.float32),
    ])


def stack_act_stats(per_layer):
    """[L, 3] from a list of per-boundary tensor_stats vectors."""
    import jax.numpy as jnp
    return jnp.stack(per_layer)


def combine_act_microbatches(acts):
    """Reduce [gas, L, 3] per-microbatch activation stats to [L, 3]:
    absmax -> max, mean_abs -> mean, nonfinite -> sum."""
    import jax.numpy as jnp
    return jnp.stack([
        jnp.max(acts[..., 0], axis=0),
        jnp.mean(acts[..., 1], axis=0),
        jnp.sum(acts[..., 2], axis=0),
    ], axis=-1)


def group_paths(tree, depth=2):
    """Ordered leaf-group names: leaves grouped by the first `depth`
    path components (host-side; tree structure is static, so the same
    call inside a trace yields the same grouping)."""
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    names, seen = [], set()
    for path, _leaf in flat:
        name = _path_prefix(path, depth)
        if name not in seen:
            seen.add(name)
            names.append(name)
    return names


def _path_prefix(path, depth):
    import jax
    parts = []
    for entry in path[:depth]:
        s = jax.tree_util.keystr((entry,))
        parts.append(s.strip("[]'\""))
    return "/".join(parts) if parts else "<root>"


def leaf_sumsq(tree):
    """Per-leaf fused sum-of-squares tree (f32) — computed ONCE in the
    step and shared between the engine's global grad norm and the
    per-group stats below, so numerics health does not re-read the
    gradients for a second norm pass."""
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(
        # sum(g*g), NOT vdot: vdot lowers to a dot over a flattened
        # f32 copy of each leaf, while the elementwise square fuses
        # straight into the reduction
        lambda g: jnp.sum(g.astype(jnp.float32) ** 2), tree)


def grad_group_stats(grads, sq_tree=None, depth=2):
    """[G, 3] f32 per-group gradient stats (groups = group_paths order):
    L2 norm, abs-max, nonfinite FLAG (0/1 — summed over a window it
    counts affected steps). Called inside the jitted step on the
    unscaled grads; ZeRO's padded encoding is stats-neutral (pad lanes
    are zeros: finite, zero-norm contribution).

    Cost discipline: the sum-of-squares pass is SHARED with the
    engine's clip/overflow grad norm (`sq_tree` = leaf_sumsq output),
    so with clipping or fp16 enabled numerics adds exactly ONE new
    reduction pass per leaf (abs-max); NaN/Inf propagate through both
    reductions, so the nonfinite flag is a free scalar derivation
    instead of a third full `isfinite` sweep over every parameter
    (the sweep alone showed up as measurable step-time overhead in the
    `numerics_overhead` A/B). Activation stats keep exact element
    counts — they run on L boundary tensors, not every parameter."""
    import jax
    import jax.numpy as jnp
    if sq_tree is None:
        sq_tree = leaf_sumsq(grads)
    flat, _ = jax.tree_util.tree_flatten_with_path(grads)
    sq_flat = jax.tree_util.tree_leaves(sq_tree)
    groups = {}
    order = []
    for (path, leaf), sq in zip(flat, sq_flat):
        name = _path_prefix(path, depth)
        if name not in groups:
            groups[name] = []
            order.append(name)
        groups[name].append((leaf, sq))
    rows = []
    for name in order:
        sq = jnp.sum(jnp.stack([s for _, s in groups[name]]))
        absmax = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(leaf.astype(jnp.float32)))
             for leaf, _ in groups[name]]))
        bad = (~(jnp.isfinite(sq) & jnp.isfinite(absmax))) \
            .astype(jnp.float32)
        rows.append(jnp.stack([jnp.sqrt(sq), absmax, bad]))
    return jnp.stack(rows)


# ----------------------------------------------------------------------
# window compaction (device-side, eager — runs with the registry's
# scalar compaction every _COMPACT_AT retained steps)
# ----------------------------------------------------------------------
def _first_bad_of_block(steps, acts, grads):
    """Device [3] i32 candidate (win_step, kind, index) for the first
    nonfinite in a block of retained entries; win_step == -1 when the
    whole block is finite. Activations outrank gradients within a step
    (the forward runs first)."""
    import jax.numpy as jnp
    n = len(steps)
    steps = jnp.asarray(steps, jnp.int32)
    act_bad = jnp.zeros((n,), bool) if acts is None \
        else jnp.any(acts[..., 2] > 0, axis=-1)
    grad_bad = jnp.zeros((n,), bool) if grads is None \
        else jnp.any(grads[..., 2] > 0, axis=-1)
    any_bad = act_bad | grad_bad
    has = jnp.any(any_bad)
    n0 = jnp.argmax(any_bad)           # first True
    kind = jnp.where(act_bad[n0], KIND_ACT, KIND_GRAD)
    idx_act = jnp.int32(0) if acts is None else \
        jnp.argmax(acts[n0, :, 2] > 0).astype(jnp.int32)
    idx_grad = jnp.int32(0) if grads is None else \
        jnp.argmax(grads[n0, :, 2] > 0).astype(jnp.int32)
    idx = jnp.where(kind == KIND_ACT, idx_act, idx_grad)
    return jnp.where(
        has,
        jnp.stack([steps[n0], kind.astype(jnp.int32), idx]),
        jnp.asarray([-1, -1, -1], jnp.int32))


def fold_entries(steps, healths, acc):
    """Reduce a block of retained (win_step, health) entries into the
    running device accumulator. health = {"act": [L,3]|None,
    "grad": [G,3]|None} with constant presence within one engine run.
    Eager jnp only — async like the step, never a sync."""
    import jax.numpy as jnp
    acts = None
    grads = None
    if healths and healths[0].get("act") is not None:
        acts = jnp.stack([h["act"] for h in healths])
    if healths and healths[0].get("grad") is not None:
        grads = jnp.stack([h["grad"] for h in healths])
    new = {
        "act_last": None if acts is None else acts[-1],
        "act_absmax": None if acts is None
        else jnp.max(acts[..., 0], axis=0),
        "act_nonfinite": None if acts is None
        else jnp.sum(acts[..., 2], axis=0),
        "grad_last": None if grads is None else grads[-1],
        "grad_absmax": None if grads is None
        else jnp.max(grads[..., 1], axis=0),
        "grad_nonfinite": None if grads is None
        else jnp.sum(grads[..., 2], axis=0),
        "first_bad": _first_bad_of_block(steps, acts, grads),
    }
    if acc is None:
        return new
    out = dict(new)
    for key in ("act_absmax", "grad_absmax"):
        if acc.get(key) is not None and new.get(key) is not None:
            out[key] = jnp.maximum(acc[key], new[key])
    for key in ("act_nonfinite", "grad_nonfinite"):
        if acc.get(key) is not None and new.get(key) is not None:
            out[key] = acc[key] + new[key]
    # the EARLIER candidate wins (acc covers earlier window steps)
    prev = acc["first_bad"]
    out["first_bad"] = jnp.where(prev[0] >= 0, prev, new["first_bad"])
    return out


# ----------------------------------------------------------------------
# host-side fence summary (runs on fetched numpy, after the one
# per-fence device_get)
# ----------------------------------------------------------------------
def _named(names, values, as_int=False):
    if values is None:
        return None
    vals = np.asarray(values)
    names = list(names) if names else \
        [f"group{i}" for i in range(len(vals))]
    cast = int if as_int else float
    return {names[i] if i < len(names) else f"group{i}": cast(vals[i])
            for i in range(len(vals))}


def summarize_window(entries, acc, grad_names=None, act_names=None):
    """The fence's numerics event fields, from the fetched (numpy)
    pending entries + compacted accumulator. Returns None when the
    window held no health data."""
    if not entries and acc is None:
        return None
    steps = [s for s, _ in entries]
    acts = [h["act"] for _, h in entries
            if h.get("act") is not None]
    grads = [h["grad"] for _, h in entries
            if h.get("grad") is not None]
    acts = np.stack(acts) if acts else None
    grads = np.stack(grads) if grads else None

    def _merge(tail_last, tail_red, acc_last, acc_red, how):
        """tail (post-compaction entries) takes `last`; reductions
        merge with the accumulated block."""
        last = tail_last if tail_last is not None else acc_last
        reds = [r for r in (tail_red, acc_red) if r is not None]
        red = None if not reds else \
            (np.maximum.reduce(reds) if how == "max" else sum(reds))
        return last, red

    act_last, act_absmax = _merge(
        None if acts is None else acts[-1],
        None if acts is None else acts[..., 0].max(axis=0),
        None if acc is None else acc.get("act_last"),
        None if acc is None else acc.get("act_absmax"), "max")
    _, act_bad = _merge(
        None,
        None if acts is None else acts[..., 2].sum(axis=0),
        None,
        None if acc is None else acc.get("act_nonfinite"), "sum")
    grad_last, grad_absmax = _merge(
        None if grads is None else grads[-1],
        None if grads is None else grads[..., 1].max(axis=0),
        None if acc is None else acc.get("grad_last"),
        None if acc is None else acc.get("grad_absmax"), "max")
    _, grad_bad = _merge(
        None,
        None if grads is None else grads[..., 2].sum(axis=0),
        None,
        None if acc is None else acc.get("grad_nonfinite"), "sum")

    # first-nonfinite: the compacted candidate covers earlier steps
    first = None
    if acc is not None and acc.get("first_bad") is not None:
        fb = np.asarray(acc["first_bad"])
        if fb[0] >= 0:
            first = (int(fb[0]), int(fb[1]), int(fb[2]))
    if first is None and entries:
        for (step, h) in entries:
            a = h.get("act")
            if a is not None and (np.asarray(a)[:, 2] > 0).any():
                first = (int(step), KIND_ACT,
                         int(np.argmax(np.asarray(a)[:, 2] > 0)))
                break
            g = h.get("grad")
            if g is not None and (np.asarray(g)[:, 2] > 0).any():
                first = (int(step), KIND_GRAD,
                         int(np.argmax(np.asarray(g)[:, 2] > 0)))
                break

    out = {
        "grad_norm": _named(grad_names,
                            None if grad_last is None
                            else np.asarray(grad_last)[:, 0]),
        "grad_absmax": _named(grad_names, grad_absmax),
        "grad_nonfinite": _named(grad_names, grad_bad, as_int=True),
        "act_absmax": _named(act_names, act_absmax),
        "act_mean": _named(act_names,
                           None if act_last is None
                           else np.asarray(act_last)[:, 1]),
        "act_nonfinite": _named(act_names, act_bad, as_int=True),
        "window_steps": len(steps),
    }
    if first is not None:
        step, kind, idx = first
        names = act_names if kind == KIND_ACT else grad_names
        name = names[idx] if names and idx < len(names) else str(idx)
        out["first_nonfinite"] = {
            "kind": "activation" if kind == KIND_ACT else "gradient",
            "name": name, "index": idx, "window_step": step,
        }
    else:
        out["first_nonfinite"] = None
    return out
