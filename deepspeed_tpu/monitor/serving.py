"""Request-level serving observability (ISSUE 14).

The training side has Perfetto timelines, a flight recorder and a
byte-attributed memory ledger; this module extends that forensic stack
to the serving engine. One `ServingTracker` per `InferenceEngine`
stamps every request's lifecycle phases

    queued -> admitted -> prefill chunk(s) -> decode -> finished

from **host dispatch timestamps captured at the existing serving
hooks only** — admission, prefill-chunk dispatch, and the serving
fence (`fetch_state` already carries every slot's progress counters,
so per-token attribution needs ZERO new host<->device syncs; the
PR-5/7 fence discipline, pinned statically by ds_lint's HOTSYNC rule
and dynamically by tests/test_inference.py's sync-counter guards).

From those records it derives three things:

  * a **Perfetto serving timeline** through the PR-7 TraceExporter:
    one track per decode slot (`serve/slot<N>`) with queue-wait,
    per-chunk prefill and per-fence decode windows as distinct slice
    types (request-id / prompt-len / token-count args), one
    `serving_request` instant per finished request carrying its full
    lifecycle stats, and counter tracks for queue depth, batch
    occupancy, KV-page utilization (read from the PR-8 ledger's
    `kv_cache` category) and tokens/s. `ds_trace summary --serving`
    recomputes per-request p50/p99 queue-wait / TTFT / per-token
    latency and goodput-vs-throughput from the instants.
  * **live SLO metrics** at each serving fence: a `serving_slo` event
    with streaming TTFT / per-token-latency / queue-wait histograms
    (FIXED log-spaced bucket edges — `HIST_EDGES_MS` — so the JSONL
    payload stays schema-stable), admission-rejection and
    eviction-reason counters, and the saturation signal (queue-wait
    share of end-to-end latency).
  * **serving forensics**: the flight recorder's sticky context gains
    the live request table (per slot: request id, phase, tokens
    emitted, pages held), so an OOM/crash/stall dump names exactly
    which requests were in flight, and `serving_oom_hints` ranks the
    serving knobs (kv_cache.num_pages vs max_slots vs prefill_chunk)
    by what the reconciled ledger says actually dominates.

Granularity caveat (docs/inference.md "Observability"): timestamps are
host dispatch stamps at fence granularity. TTFT is an upper bound by
at most one fence window (`sync_every` decode iterations), and a
request's per-token decode latency is its decode wall time divided by
its token count — the inter-token latency its streaming client feels,
not a per-kernel device measurement (that belongs to the profiler).

Everything here is host-side arithmetic on small per-slot tables:
no device access, no new syncs, thread-safe where the flight
recorder's off-thread dumps can observe it.
"""

import threading
import time
from bisect import bisect_right

from deepspeed_tpu.monitor import memory as memory_mod
from deepspeed_tpu.monitor.trace_export import (CAT_SERVE_DECODE,
                                                CAT_SERVE_PREFILL,
                                                CAT_SERVE_QUEUE,
                                                CAT_SERVE_REQUEST)

HIST_SCHEMA_VERSION = 1
# Fixed log-spaced bucket edges in milliseconds: 0.02 ms .. ~20.9 s,
# factor 2^(1/3) per bucket (61 edges). Fixed by constant — not by
# config — so `serving_slo` JSONL payloads stay schema-stable across
# runs and readers can diff histograms bucket-for-bucket. Values below
# the first edge land in bucket 0; values past the last edge land in
# the final (overflow) bucket. A percentile read off the histogram is
# accurate to one bucket (~26% relative), which is the trade for a
# bounded, mergeable payload.
HIST_EDGES_MS = tuple(round(0.02 * 2.0 ** (i / 3.0), 6)
                      for i in range(61))
_HIST_FACTOR = 2.0 ** (1.0 / 3.0)


class LatencyHistogram:
    """Streaming latency histogram over the fixed `HIST_EDGES_MS`
    edges. `record` is O(log buckets); `percentile` answers from the
    counts (bucket-resolution accurate, never a sorted-sample sync)."""

    edges_ms = HIST_EDGES_MS

    def __init__(self):
        self._counts = [0] * len(HIST_EDGES_MS)
        self._n = 0
        self._sum_ms = 0.0

    def record(self, ms, count=1):
        if count <= 0:
            return
        i = bisect_right(HIST_EDGES_MS, float(ms)) - 1
        i = min(max(i, 0), len(self._counts) - 1)
        self._counts[i] += int(count)
        self._n += int(count)
        self._sum_ms += float(ms) * int(count)

    @property
    def count(self):
        return self._n

    def percentile(self, p):
        """The p-quantile (p in (0, 1]) as the geometric midpoint of
        the bucket holding it; None while empty."""
        if self._n <= 0:
            return None
        target = p * self._n
        acc = 0
        for i, c in enumerate(self._counts):
            acc += c
            if acc >= target:
                lo = HIST_EDGES_MS[i]
                return lo * (_HIST_FACTOR ** 0.5)
        return HIST_EDGES_MS[-1] * (_HIST_FACTOR ** 0.5)

    def to_event(self):
        """The schema-stable JSONL payload: version, unit, total count
        and sum, and the full fixed-width counts vector."""
        return {"v": HIST_SCHEMA_VERSION, "unit": "ms",
                "count": self._n, "sum_ms": round(self._sum_ms, 3),
                "counts": list(self._counts)}


class ServingTracker:
    """Per-request lifecycle tracker for one InferenceEngine.

    The ServingLoop calls the hooks below from its (single) serving
    thread at the phases it already executes host-side; the lock only
    guards the live table and counters against the flight recorder's
    off-thread snapshot reads. Sink emission and trace stamping happen
    OUTSIDE the lock (the LOCKBLOCK discipline)."""

    def __init__(self, monitor, cache, config):
        self._monitor = monitor
        self._cache = cache
        self._max_slots = int(config.max_slots)
        self._prefill_chunk = int(config.prefill_chunk)
        self._slo_ttft_ms = float(config.slo_ttft_ms or 0.0)
        self._slo_token_ms = float(config.slo_token_ms or 0.0)
        self._lock = threading.Lock()
        self.hist_queue_ms = LatencyHistogram()
        self.hist_ttft_ms = LatencyHistogram()
        self.hist_token_ms = LatencyHistogram()
        self._live = {}          # slot -> lifecycle row
        self._queue_depth = 0
        self.counters = {
            "finished_eos": 0, "finished_max_tokens": 0,
            "rejected_submit": 0, "admission_deferred": 0,
        }
        self.total_tokens = 0
        self.goodput_tokens = 0
        self._queue_wait_s = 0.0     # over finished requests
        self._e2e_s = 0.0            # queued + wall over finished
        # speculative decoding (ISSUE 18): cumulative draft/verify
        # split. The times are host DISPATCH spans (the zero-sync loop
        # cannot time device execution per program; everything settles
        # at the fence), handed over by the scheduler each fence.
        self.spec = {"draft_dispatch_s": 0.0, "verify_dispatch_s": 0.0,
                     "drafted_tokens": 0, "accepted_tokens": 0,
                     "verified_rounds": 0, "rollback_events": 0}
        self._armed = False

    # ------------------------------------------------------------------
    # lifecycle hooks (serving-loop thread; host-side only)
    # ------------------------------------------------------------------
    def on_rejected(self):
        """A submit-time rejection (never-fits pool / too long / bad
        sampling params): counted, since a production front-end's
        admission-rejection rate is an SLO of its own."""
        with self._lock:
            self.counters["rejected_submit"] += 1

    def on_admission_deferred(self):
        """A ready request could not take a free slot because the page
        pool cannot cover its worst case yet — one count per serving
        iteration it head-of-line blocks (the pool-pressure signal)."""
        with self._lock:
            self.counters["admission_deferred"] += 1

    def on_admitted(self, slot, request_id, prompt_tokens,
                    max_new_tokens, queued_s, pages_reserved):
        now = time.perf_counter()
        row = {
            "slot": int(slot), "request_id": str(request_id),
            "phase": "prefill",
            "prompt_tokens": int(prompt_tokens),
            "max_new_tokens": int(max_new_tokens),
            "tokens_emitted": 0, "pages_held": 0,
            "queued_s": float(max(queued_s, 0.0)),
            "admitted_t": now, "live_t": None, "ttft_ms": None,
        }
        with self._lock:
            self._live[int(slot)] = row
        self.hist_queue_ms.record(row["queued_s"] * 1e3)
        tr = self._monitor.trace_export
        if tr is not None:
            # back-dated to the arrival: the wait is VISIBLE as its own
            # slice type ahead of the prefill/decode slices (export
            # sorts by ts, so retroactive stamps stay valid)
            tr.complete(
                f"serve/slot{int(slot)}", f"queued {row['request_id']}",
                now - row["queued_s"], row["queued_s"],
                cat=CAT_SERVE_QUEUE,
                args={"request_id": row["request_id"],
                      "prompt_tokens": row["prompt_tokens"],
                      "queued_ms": round(row["queued_s"] * 1e3, 3)})
        self._update_flight()

    def on_prefill_chunk(self, slot, t_start, dur, start, end):
        """One prefill program dispatch for `slot` covering prompt
        positions [start, end) — a host dispatch window (the program
        itself runs async; the PR-5 span semantics)."""
        with self._lock:
            row = self._live.get(int(slot))
            if row is not None:
                row["pages_held"] = self._cache.allocated_pages(slot)
        tr = self._monitor.trace_export
        if tr is not None and row is not None:
            tr.complete(
                f"serve/slot{int(slot)}",
                f"prefill {row['request_id']} [{int(start)}:{int(end)}]",
                t_start, max(dur, 0.0), cat=CAT_SERVE_PREFILL,
                args={"request_id": row["request_id"],
                      "tokens": int(end) - int(start),
                      "start": int(start)})

    def on_live(self, slot):
        """The slot's prompt is fully cached: it joins the decode
        batch."""
        with self._lock:
            row = self._live.get(int(slot))
            if row is not None:
                row["phase"] = "decode"
                row["live_t"] = time.perf_counter()
                row["pages_held"] = self._cache.allocated_pages(slot)
        self._update_flight()

    def on_fence_progress(self, decode_t0, iterations, slot_tokens):
        """Per-slot progress from the fence's fetched counters:
        `slot_tokens` maps live slots to tokens generated this window.
        First-token fences record TTFT; decode windows land on the
        timeline per slot."""
        now = time.perf_counter()
        slices = []
        with self._lock:
            for slot, delta in slot_tokens.items():
                row = self._live.get(int(slot))
                if row is None:
                    continue
                row["tokens_emitted"] += int(delta)
                row["pages_held"] = self._cache.allocated_pages(slot)
                if delta > 0 and row["ttft_ms"] is None:
                    # fence-granularity upper bound: the token appeared
                    # somewhere inside this window
                    row["ttft_ms"] = (now - row["admitted_t"]) * 1e3
                    self.hist_ttft_ms.record(row["ttft_ms"])
                if delta > 0 and decode_t0 is not None:
                    slices.append((int(slot), row["request_id"],
                                   int(delta)))
        tr = self._monitor.trace_export
        if tr is not None:
            for slot, rid, delta in slices:
                tr.complete(
                    f"serve/slot{slot}", f"decode {rid} +{delta}",
                    decode_t0, max(now - decode_t0, 0.0),
                    cat=CAT_SERVE_DECODE,
                    args={"request_id": rid, "tokens": delta,
                          "iterations": int(iterations)})

    def on_finished(self, slot, reason):
        """Eviction (EOS / max-tokens) at the fence: close the row,
        fold its stats into the streaming histograms and counters, and
        leave the per-request record on the timeline."""
        now = time.perf_counter()
        with self._lock:
            row = self._live.pop(int(slot), None)
            if row is None:
                return
            live_t = row["live_t"] if row["live_t"] is not None \
                else row["admitted_t"]
            prefill_s = max(live_t - row["admitted_t"], 0.0)
            decode_s = max(now - live_t, 1e-9)
            n = max(row["tokens_emitted"], 1)
            token_ms = decode_s * 1e3 / n
            self.hist_token_ms.record(token_ms, count=n)
            key = "finished_eos" if reason == "eos" \
                else "finished_max_tokens"
            self.counters[key] += 1
            slo_ok = True
            if self._slo_ttft_ms > 0:
                slo_ok = slo_ok and row["ttft_ms"] is not None and \
                    row["ttft_ms"] <= self._slo_ttft_ms
            if self._slo_token_ms > 0:
                slo_ok = slo_ok and token_ms <= self._slo_token_ms
            self.total_tokens += row["tokens_emitted"]
            if slo_ok:
                self.goodput_tokens += row["tokens_emitted"]
            wall_s = max(now - row["admitted_t"], 0.0)
            self._queue_wait_s += row["queued_s"]
            self._e2e_s += row["queued_s"] + wall_s
        tr = self._monitor.trace_export
        if tr is not None:
            tr.instant(
                f"serve/slot{int(slot)}", f"finished {row['request_id']}",
                t_at=now, cat=CAT_SERVE_REQUEST,
                args={"request_id": row["request_id"],
                      "reason": str(reason),
                      "prompt_tokens": row["prompt_tokens"],
                      "new_tokens": row["tokens_emitted"],
                      "queued_ms": round(row["queued_s"] * 1e3, 3),
                      "ttft_ms": None if row["ttft_ms"] is None
                      else round(row["ttft_ms"], 3),
                      "token_ms": round(token_ms, 3),
                      "prefill_ms": round(prefill_s * 1e3, 3),
                      "decode_ms": round(decode_s * 1e3, 3),
                      "wall_ms": round(wall_s * 1e3, 3),
                      "slo_ok": bool(slo_ok)})
        self._update_flight()

    def on_fence_metrics(self, window_s, window_tokens, queue_depth,
                         active_slots, prefilling_slots):
        """The fence's SLO rendezvous: one `serving_slo` event + the
        counter tracks, after evictions settled (so the counts include
        this fence's finishes)."""
        with self._lock:
            self._queue_depth = int(queue_depth)
            c = dict(self.counters)
            total = self.total_tokens
            good = self.goodput_tokens
            qw, e2e = self._queue_wait_s, self._e2e_s
        in_use, free, util = self._kv_pages()
        window_s = max(window_s, 1e-9)
        tps = window_tokens / window_s
        self._monitor.event(
            "serving_slo",
            window_ms=round(window_s * 1e3, 3),
            window_tokens=int(window_tokens),
            tokens_per_sec=round(tps, 3),
            active_slots=int(active_slots),
            prefilling_slots=int(prefilling_slots),
            queue_depth=int(queue_depth),
            kv_pages_in_use=in_use,
            kv_pages_free=free,
            kv_page_utilization=round(util, 4),
            queue_wait_share=round(qw / e2e, 4) if e2e > 0 else None,
            ttft_ms=self.hist_ttft_ms.to_event(),
            token_ms=self.hist_token_ms.to_event(),
            queue_ms=self.hist_queue_ms.to_event(),
            ttft_p50_ms=_r(self.hist_ttft_ms.percentile(0.50)),
            ttft_p99_ms=_r(self.hist_ttft_ms.percentile(0.99)),
            token_p50_ms=_r(self.hist_token_ms.percentile(0.50)),
            token_p99_ms=_r(self.hist_token_ms.percentile(0.99)),
            queue_p50_ms=_r(self.hist_queue_ms.percentile(0.50)),
            queue_p99_ms=_r(self.hist_queue_ms.percentile(0.99)),
            finished_eos=c["finished_eos"],
            finished_max_tokens=c["finished_max_tokens"],
            rejected_submit=c["rejected_submit"],
            admission_deferred=c["admission_deferred"],
            total_tokens=int(total),
            goodput_tokens=int(good),
            goodput_fraction=round(good / total, 4) if total else None)
        tr = self._monitor.trace_export
        if tr is not None:
            tr.counter("serving", "queue_depth",
                       {"queued": int(queue_depth)})
            tr.counter("serving", "batch_occupancy",
                       {"decoding": int(active_slots),
                        "prefilling": int(prefilling_slots)})
            tr.counter("serving", "kv_page_utilization",
                       {"in_use": in_use, "free": free})
            tr.counter("serving", "tokens_per_sec",
                       {"tokens_per_sec": round(tps, 3)})
        if not self._armed:
            # the engine actually served: an abnormal exit from here on
            # leaves a flight dump naming the in-flight requests (the
            # training loop arms on its first on_step; serving arms on
            # its first fence)
            self._armed = True
            if self._monitor.flight is not None:
                self._monitor.flight.arm()
        self._update_flight()

    def on_speculative(self, draft_s, verify_s, drafted, accepted,
                       verified, rollbacks):
        """Per-fence speculative accounting from the scheduler: the
        drafted-vs-verified dispatch-time split plus the round
        counters (cumulative — they describe the run)."""
        with self._lock:
            sp = self.spec
            sp["draft_dispatch_s"] += float(draft_s)
            sp["verify_dispatch_s"] += float(verify_s)
            sp["drafted_tokens"] += int(drafted)
            sp["accepted_tokens"] += int(accepted)
            sp["verified_rounds"] += int(verified)
            sp["rollback_events"] += int(rollbacks)

    def on_reset(self):
        """engine.reset() dropped every slot (bench A/B hygiene): the
        live table empties; cumulative histograms/counters survive —
        they describe the run, not the batch."""
        with self._lock:
            self._live.clear()
            self._queue_depth = 0
        self._update_flight()

    # ------------------------------------------------------------------
    # forensics
    # ------------------------------------------------------------------
    def live_table(self):
        """The JSON-able per-slot request table: who is in flight
        right now (the flight-recorder context and the crash extra)."""
        with self._lock:
            rows = [{"slot": r["slot"], "request_id": r["request_id"],
                     "phase": r["phase"],
                     "prompt_tokens": r["prompt_tokens"],
                     "tokens_emitted": r["tokens_emitted"],
                     "pages_held": r["pages_held"]}
                    for _, r in sorted(self._live.items())]
            depth = self._queue_depth
        return {"queue_depth": depth, "requests": rows}

    def snapshot(self):
        """Forensic snapshot: the live table plus pool geometry,
        utilization, counters and the current percentiles — what
        `Monitor.on_crash` attaches and `serving_oom_hints` ranks."""
        in_use, free, util = self._kv_pages()
        table = self.live_table()
        with self._lock:
            c = dict(self.counters)
        table.update(
            max_slots=self._max_slots,
            prefill_chunk=self._prefill_chunk,
            num_pages=self._cache.num_pages,
            kv_pages_in_use=in_use, kv_pages_free=free,
            kv_page_utilization=round(util, 4),
            counters=c,
            ttft_p50_ms=_r(self.hist_ttft_ms.percentile(0.50)),
            ttft_p99_ms=_r(self.hist_ttft_ms.percentile(0.99)),
            token_p50_ms=_r(self.hist_token_ms.percentile(0.50)),
            token_p99_ms=_r(self.hist_token_ms.percentile(0.99)))
        with self._lock:
            sp = dict(self.spec)
        if sp["verified_rounds"] > 0:
            d = sp["drafted_tokens"]
            table["speculative"] = dict(
                sp,
                acceptance_rate=round(sp["accepted_tokens"] / d, 4)
                if d > 0 else None,
                tokens_per_verify=round(
                    (sp["accepted_tokens"] + sp["verified_rounds"]) /
                    sp["verified_rounds"], 3))
        return table

    def _update_flight(self):
        if self._monitor.flight is not None:
            self._monitor.flight.set_context(serving=self.live_table())

    def _kv_pages(self):
        """(pages in use, pages free, utilization) derived from the
        PR-8 ledger's `kv_cache` category: the per-request dynamic
        entries are the in-use bytes, `pool.unallocated` the rest —
        pure host reads of registered shape math."""
        rows = self._monitor.ledger.category_breakdown(memory_mod.CAT_KV)
        in_use_bytes = sum(b for name, b in rows.items()
                           if name != "pool.unallocated")
        page_bytes = max(self._cache.page_bytes, 1)
        allocatable = max(self._cache.num_pages - 1, 1)
        in_use = int(in_use_bytes // page_bytes)
        free = max(allocatable - in_use, 0)
        return in_use, free, in_use / allocatable


def _r(v, nd=3):
    return None if v is None else round(v, nd)


def serving_oom_hints(payload, snapshot):
    """Serving-aware OOM hint ranking: which of the three serving
    knobs — `inference.kv_cache.num_pages`, `inference.max_slots`,
    `inference.prefill_chunk` — to turn, ordered by what the
    reconciled memory payload and the live request table say actually
    dominates. Appended ahead of the generic `oom_hints` by
    `Monitor.on_crash` when a tracker is attached."""
    snapshot = snapshot or {}
    hbm = (payload or {}).get("hbm", {}) or {}
    cats = hbm.get("categories", {}) or {}
    ledger = hbm.get("ledger_bytes") or 0
    kv = cats.get(memory_mod.CAT_KV, 0)
    util = float(snapshot.get("kv_page_utilization") or 0.0)
    reqs = snapshot.get("requests") or []
    prefilling = sum(1 for r in reqs if r.get("phase") == "prefill")
    scored = []
    if kv and ledger:
        share = kv / ledger
        if share > 0.2 and util < 0.5:
            scored.append((
                share * (1.0 - util),
                f"the kv_cache pool holds {kv / 2**30:.2f} GiB but only "
                f"{util:.0%} of its pages are in use: lower "
                "inference.kv_cache.num_pages — the pool is "
                "preallocated, every page costs HBM whether or not a "
                "request holds it"))
        elif share > 0.2:
            scored.append((
                share * util,
                f"the kv_cache pool is {util:.0%} utilized with "
                f"{len(reqs)} request(s) in flight: lower "
                "inference.max_slots (admission reserves each "
                "request's worst case, so fewer slots cap the "
                "reserved pages) or shorten max_new_tokens; raise "
                "inference.kv_cache.num_pages only if HBM headroom "
                "allows"))
    residual = hbm.get("residual_bytes")
    measured = hbm.get("measured_in_use_per_device")
    if prefilling and residual and measured and \
            residual > 0.3 * measured:
        scored.append((
            residual / measured,
            f"{prefilling} slot(s) were mid-prefill with "
            f"activations/XLA temporaries at {residual / 2**30:.2f} "
            "GiB: lower inference.prefill_chunk — the prefill "
            "program's activation footprint scales with the chunk"))
    return [text for _, text in
            sorted(scored, key=lambda t: -t[0])]
