"""Async-safe metrics core.

Hot-path metrics (loss, grad-norm, loss-scale, overflow, tokens) stay
DEVICE-SIDE between fences: the jitted step functions already compute
each of them on device, and the registry simply RETAINS those scalar
buffers (a Python list append — no new dispatch, no host<->device
sync) until the engine's `steps_per_sync` fence, where everything
drains in exactly ONE `jax.device_get` of the whole pytree
(tests/test_monitor.py pins both properties). Retention costs nothing
on the hot path — unlike a per-step jitted fold, which pays a dispatch
per step for a 6-float add.

Long fence windows stay bounded: every `_COMPACT_AT` retained steps
the pending scalars are reduced on device into a 3-scalar partial
accumulator (a handful of eager jnp dispatches, still no host sync),
so a steps_per_sync of 100k holds at most _COMPACT_AT+3 scalar
buffers.

Host-side state splits into:
  * counters — monotonically increasing floats bumped by host events
    (checkpoint commits, wire bytes, stall fires); thread-safe, since
    the checkpoint writer and watchdog threads increment them.
  * gauges — callables sampled at drain time (checkpoint queue depth,
    prefetch occupancy, device memory); a gauge may return a float or
    a flat dict of floats. Gauge failures are swallowed: telemetry
    must never kill training.
"""

import threading

import numpy as np


class MetricsRegistry:
    _COMPACT_AT = 256

    def __init__(self):
        self._pending = []        # [(loss, grad_norm, overflow), ...]
        self._acc = None          # (loss_sum, gnorm_sum, ovf_sum) device
        self._scale_last = 0.0    # device scalar or host float
        self._steps = 0
        self._loss_steps = 0      # steps that actually reported a loss
        self._gnorm_steps = 0     # ... and a grad norm
        self._tokens = 0.0        # host sum (token counts are host ints)
        self._lock = threading.Lock()
        self._counters = {}
        self._gauges = {}
        # numerics health (monitor/numerics.py): per-step [L,3]/[G,3]
        # device stat arrays retained exactly like the scalars — a list
        # append per step, compacted on device, fetched in the SAME
        # per-fence device_get
        self._pending_health = []   # [(window_step, {"act","grad"})]
        self._health_acc = None
        # MoE router stats (deepspeed_tpu/moe/router.py): per-step
        # [E+2] device vectors (per-expert load, drop frac, aux loss)
        # retained the same way — list append, summed on device at
        # compaction, drained in the same per-fence device_get; the
        # fence reports the window MEAN
        self._pending_router = []
        self._router_acc = None     # device [E+2] sum over compacted
        self._router_steps = 0

    # ------------------------------------------------------------------
    # device-side accumulator
    # ------------------------------------------------------------------
    def fold_step(self, loss, grad_norm, loss_scale, overflow, tokens,
                  health=None, router=None):
        """Retain one step's device scalars. NO device work, NO sync —
        a list append; the buffers were produced by the step anyway.
        (Never `bool()`/`float()` a device value here: that would be a
        hidden per-step sync.)

        A None loss/grad_norm (backward(release_loss=True) loops, paths
        that skip the norm) folds as 0 on device but is EXCLUDED from
        the window mean — reporting a bogus 0.0 loss would read as
        sudden convergence on a dashboard.

        `health` ({"act": [L,3], "grad": [G,3]} device arrays, either
        key possibly None) retains numerics-health stats the same way."""
        self._pending.append((0.0 if loss is None else loss,
                              0.0 if grad_norm is None else grad_norm,
                              False if overflow is None else overflow))
        if health is not None and (health.get("act") is not None or
                                   health.get("grad") is not None):
            self._pending_health.append((self._steps, health))
        if router is not None:
            self._pending_router.append(router)
            self._router_steps += 1
        if loss is not None:
            self._loss_steps += 1
        if grad_norm is not None:
            self._gnorm_steps += 1
        if loss_scale is not None:
            self._scale_last = loss_scale
        self._tokens += float(tokens)
        self._steps += 1
        if len(self._pending) >= self._COMPACT_AT:
            self._compact()

    def _compact(self):
        """Reduce the pending scalars into the device partial
        accumulator — a few eager jnp dispatches (async like the step),
        amortized over _COMPACT_AT steps. Bounds retained buffers for
        arbitrarily long fence windows."""
        import jax.numpy as jnp
        pend, self._pending = self._pending, []
        losses, gnorms, ovfs = zip(*pend)
        part = (
            jnp.sum(jnp.stack(losses).astype(jnp.float32)),
            jnp.sum(jnp.stack(gnorms).astype(jnp.float32)),
            jnp.sum(jnp.stack(ovfs).astype(jnp.int32)),
        )
        if self._acc is not None:
            part = tuple(a + p for a, p in zip(self._acc, part))
        self._acc = part
        if self._pending_health:
            from deepspeed_tpu.monitor import numerics
            ph, self._pending_health = self._pending_health, []
            self._health_acc = numerics.fold_entries(
                [s for s, _ in ph], [h for _, h in ph],
                self._health_acc)
        if self._pending_router:
            pr, self._pending_router = self._pending_router, []
            part = jnp.sum(jnp.stack(pr).astype(jnp.float32), axis=0)
            self._router_acc = part if self._router_acc is None \
                else self._router_acc + part

    # ------------------------------------------------------------------
    # host-side counters + gauges
    # ------------------------------------------------------------------
    def inc(self, name, value=1.0):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + \
                float(value)

    def set_counter(self, name, value):
        with self._lock:
            self._counters[name] = float(value)

    def counters(self):
        with self._lock:
            return dict(self._counters)

    def add_gauge(self, name, fn):
        self._gauges[name] = fn

    def sample_gauges(self):
        out = {}
        for name, fn in self._gauges.items():
            try:
                val = fn()
            except Exception:  # ds-lint: allow[BROADEXC] host gauges are best-effort callables sampled at the fence; one bad gauge must not kill the drain
                continue
            if isinstance(val, dict):
                for k, v in val.items():
                    out[f"{name}/{k}"] = float(v)
            elif val is not None:
                out[name] = float(val)
        return out

    # ------------------------------------------------------------------
    # fence drain
    # ------------------------------------------------------------------
    def drain_device(self):
        """ONE device_get of everything retained (partial accumulator +
        pending scalars + last loss scale, fetched as a single pytree);
        resets the window. Returns None when nothing was folded since
        the last drain."""
        if self._steps == 0:
            return None
        import jax
        (acc, pend, scale, health_acc, pend_health, router_acc,
         pend_router) = jax.device_get(
            (self._acc, self._pending, self._scale_last,
             self._health_acc, self._pending_health,
             self._router_acc, self._pending_router))
        steps, self._steps = self._steps, 0
        loss_steps, self._loss_steps = self._loss_steps, 0
        gnorm_steps, self._gnorm_steps = self._gnorm_steps, 0
        router_steps, self._router_steps = self._router_steps, 0
        tokens, self._tokens = self._tokens, 0.0
        self._pending, self._acc = [], None
        self._pending_health, self._health_acc = [], None
        self._pending_router, self._router_acc = [], None

        loss_sum = gnorm_sum = ovf_sum = 0.0
        if acc is not None:
            loss_sum, gnorm_sum, ovf_sum = (float(acc[0]), float(acc[1]),
                                            float(acc[2]))
        for loss, gnorm, ovf in pend:
            loss_sum += float(loss)
            gnorm_sum += float(gnorm)
            ovf_sum += float(ovf)
        scale = float(np.asarray(scale))
        # loss_scale persists across windows (the next window may hold
        # only overflow-skipped steps that never touch the scale)
        self._scale_last = scale
        out = {
            "steps": int(steps),
            "loss": loss_sum / loss_steps if loss_steps else None,
            "grad_norm": gnorm_sum / gnorm_steps if gnorm_steps
            else None,
            "loss_scale": scale,
            "overflow_count": int(ovf_sum),
            "tokens": int(tokens),
        }
        if pend_health or health_acc is not None:
            # fetched numpy already (it rode the fused device_get
            # above); the Monitor summarizes with its host-side labels
            out["health"] = (pend_health, health_acc)
        if router_steps:
            # window MEAN of the [E+2] router stats vector (per-expert
            # load fractions, drop fraction, aux loss) — fetched numpy
            # via the same fused device_get
            total = np.zeros_like(np.asarray(
                pend_router[0] if pend_router else router_acc,
                np.float64))
            if router_acc is not None:
                total = total + np.asarray(router_acc, np.float64)
            for r in pend_router:
                total = total + np.asarray(r, np.float64)
            out["router"] = (total / router_steps, int(router_steps))
        return out
