"""Memory ledger: live HBM/host byte accounting with attribution.

The monitor stack sees time (spans, pipeline timelines) and values
(loss, numerics health); this module makes it see MEMORY — the
resource ZeRO exists to manage. Every long-lived allocation site
registers its logical buffers here by category, with bytes computed
from abstract shapes/dtypes and sharding metadata (`shard_shape` is
pure index math — NO device sync anywhere in this module):

  params          compute-dtype parameters (engine / pipe flat layout)
  master          device fp32 master copies (mixed precision)
  opt_state       optimizer moments (device)
  grads           the persistent fp32 grad accumulator (gas > 1)
  zero3_gather    the stage-3 scheduler's live gathered-param window —
                  (prefetch_layers + 1) layers of full params (a
                  DYNAMIC entry; runtime/zero/stage3.py)
  moe_dispatch    the MoE layers' all-to-all dispatch buffers — the
                  [E, C, H] send + expert-output recv pair per MoE
                  layer (a DYNAMIC entry learned at first trace;
                  deepspeed_tpu/moe/dispatch.py)
  host_master     ZeRO-Offload fp32 masters in host RAM
  host_opt_state  ZeRO-Offload CPU-Adam moments in host RAM
  wire            compressed-wire state: device residual / device flat
                  param copy / host shadow
  kv_cache        the serving engine's preallocated paged KV pool —
                  one DYNAMIC entry per live request (its allocated
                  pages) plus the unallocated remainder, so the
                  category total is always the true pool bytes
                  (inference/kv_cache.py)
  kv_cache_draft  the speculative-decoding draft model's KV pool —
                  same page tables and allocator as `kv_cache`, fewer
                  layers; same unallocated + per-request split so the
                  category total is the true draft pool bytes
                  (inference/kv_cache.py attach_draft)
  ckpt_snapshot   checkpoint snapshot double-buffers — alive only
                  between the jitted snapshot and the writer's commit
  prefetch        staged batches queued ahead of the step loop
                  (a DYNAMIC entry: occupancy x staged bytes)
  pipe_buffers    the 1F1B executor's saved-input/ring buffers

At each existing telemetry fence the Monitor calls `reconcile`, which
samples the allocator (`device_memory_stats`) and host RSS and splits
the measured numbers into ledger-known bytes and a RESIDUAL — the
activations/XLA temporaries no registry can see. The peak watermark
keeps the attribution snapshot taken AT the fence that observed the
peak: an OOM post-mortem needs to know what was alive when memory
crested, not what is alive now.

`classify_oom` + `oom_hints` turn a RESOURCE_EXHAUSTED crash into an
attributed flight-recorder dump with actionable knobs; `plan_vs_
measured` scores a ZeRO memory plan (`ZeroShardingPolicy.memory_plan`)
against the ledger per component — the validation ROADMAP item 2
(ZeRO-3 at 13B) is contingent on.

Everything here is host-side arithmetic over shape metadata; the
per-fence cost is a dict walk, guard-tested to add zero per-step
host<->device syncs.
"""

import os
import re
import threading

MEMORY_SCHEMA_VERSION = 1

SPACE_HBM = "hbm"
SPACE_HOST = "host"

CAT_PARAMS = "params"
CAT_MASTER = "master"
CAT_OPT = "opt_state"
CAT_GRADS = "grads"
CAT_ZERO3 = "zero3_gather"
CAT_HOST_MASTER = "host_master"
CAT_HOST_OPT = "host_opt_state"
CAT_WIRE = "wire"
CAT_CKPT = "ckpt_snapshot"
CAT_PREFETCH = "prefetch"
CAT_PIPE = "pipe_buffers"
CAT_KV = "kv_cache"
CAT_KV_DRAFT = "kv_cache_draft"
CAT_MOE = "moe_dispatch"
CAT_OVERLAP = "overlap_inflight"

# canonical ordering for stacked rendering (Perfetto counter tracks,
# event dicts): state groups first, transients last (zero3_gather —
# the stage-3 scheduler's live gathered-param prefetch window — sits
# with the state groups: it is persistent working memory of the step;
# kv_cache — the serving engine's preallocated page pool — likewise:
# the pool is resident for the engine's lifetime, with per-request
# entries carving it up; moe_dispatch — the MoE layers' all-to-all
# send/recv capacity buffers [E, C, H] — is per-step working memory
# like zero3_gather: a DYNAMIC entry learned at first trace;
# overlap_inflight — the comm/compute overlap runtime's in-flight
# collective staging windows (MoE dispatch pair + ring send/recv
# rotations, ops/overlap.py) — likewise: per-step working memory that
# scales with overlap.issue_distance)
CATEGORIES = (CAT_PARAMS, CAT_MASTER, CAT_OPT, CAT_GRADS, CAT_ZERO3,
              CAT_MOE, CAT_OVERLAP, CAT_KV, CAT_KV_DRAFT, CAT_HOST_MASTER,
              CAT_HOST_OPT, CAT_WIRE, CAT_CKPT, CAT_PREFETCH,
              CAT_PIPE)


# ----------------------------------------------------------------------
# byte arithmetic (shape/dtype metadata only — never a device value)
# ----------------------------------------------------------------------
def host_rss_bytes():
    """Resident set size of this process from /proc/self/statm
    (stdlib-only; None where /proc is unavailable). The host-space twin
    of the device allocator gauge: off-TPU (device_count == 0 — the
    backend exposes no memory_stats) the ledger reconciles against
    THIS, so CPU/virtual-mesh rehearsal runs keep a meaningful memory
    signal — the peak_flops_override precedent."""
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        return rss_pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        # no /proc (non-Linux) or malformed statm: gauge degrades
        return None


def leaf_nbytes(leaf, per_device=True):
    """Logical bytes of one array-like leaf from shape/dtype metadata.
    `per_device=True` divides a sharded jax.Array by its sharding
    (`shard_shape` — pure index math, no transfer): the ledger answers
    "what does ONE device hold", the question HBM pressure asks.
    Replicated leaves count full-size per device, which is exactly
    their per-chip cost."""
    import numpy as np
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return 0
    if per_device:
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            try:
                shape = sharding.shard_shape(tuple(shape))
            except Exception:  # ds-lint: allow[BROADEXC] exotic shardings without shard_shape fall back to full-size accounting
                pass
    return int(np.prod(shape)) * np.dtype(dtype).itemsize


def tree_nbytes(tree, per_device=True):
    """Summed `leaf_nbytes` over a pytree (jax Arrays, numpy arrays,
    ShapeDtypeStructs — anything with .shape/.dtype)."""
    import jax
    return sum(leaf_nbytes(l, per_device=per_device)
               for l in jax.tree_util.tree_leaves(tree))


# ----------------------------------------------------------------------
# the ledger
# ----------------------------------------------------------------------
class MemoryLedger:
    """Registry of long-lived logical buffers by (category, name).

    Thread-safe: the checkpoint writer registers/releases snapshot
    entries from its own thread while the fence reconciles. `register`
    replaces an existing (category, name) entry — a fresh prefetch
    loader or a resaved checkpoint tag supersedes its predecessor.
    Dynamic entries hold a zero-arg callable sampled at reconcile time
    (host-side ints only — e.g. prefetch occupancy x staged bytes).
    """

    def __init__(self):
        self._entries = {}       # (category, name) -> entry dict
        self._lock = threading.Lock()
        self._peak = None        # attribution snapshot AT the peak
        self._plan = None        # {component: planned bytes} (hbm)

    # -- registration ---------------------------------------------------
    def register(self, category, name, nbytes, space=SPACE_HBM,
                 meta=None):
        """Register a static entry; returns the token `release` takes."""
        key = (str(category), str(name))
        with self._lock:
            self._entries[key] = {
                "category": key[0], "name": key[1], "space": space,
                "bytes": int(nbytes), "fn": None, "meta": meta or {}}
        return key

    def register_tree(self, category, name, tree, space=SPACE_HBM,
                      per_device=True, meta=None):
        """Register a pytree's bytes (sharding-aware, metadata only)."""
        try:
            nbytes = tree_nbytes(tree, per_device=per_device)
        except Exception:  # ds-lint: allow[BROADEXC] ledger registration over arbitrary client pytrees must never kill engine init
            nbytes = 0
        return self.register(category, name, nbytes, space=space,
                             meta=meta)

    def register_dynamic(self, category, name, fn, space=SPACE_HBM,
                         meta=None):
        """Register a callable sampled at reconcile time. The callable
        must be host-side only (no device access) and may return None
        (counted as 0)."""
        key = (str(category), str(name))
        with self._lock:
            self._entries[key] = {
                "category": key[0], "name": key[1], "space": space,
                "bytes": 0, "fn": fn, "meta": meta or {}}
        return key

    def release(self, token):
        """Drop an entry by the token `register` returned (or a
        (category, name) tuple). Unknown tokens are a no-op — release
        paths run in finally blocks and must never raise."""
        try:
            key = (str(token[0]), str(token[1]))
        except (TypeError, IndexError, KeyError):
            return
        with self._lock:
            self._entries.pop(key, None)

    # -- queries --------------------------------------------------------
    def _sampled(self):
        """[(entry, bytes)] with dynamic entries sampled; failures are
        swallowed (telemetry must never kill training)."""
        with self._lock:
            entries = list(self._entries.values())
        out = []
        for e in entries:
            b = e["bytes"]
            if e["fn"] is not None:
                try:
                    b = int(e["fn"]() or 0)
                except Exception:  # ds-lint: allow[BROADEXC] dynamic gauges are client callables; telemetry must never kill training
                    b = 0
            out.append((e, b))
        return out

    def totals(self):
        """{space: {category: bytes}} over the live entries."""
        out = {SPACE_HBM: {}, SPACE_HOST: {}}
        for e, b in self._sampled():
            space = out.setdefault(e["space"], {})
            space[e["category"]] = space.get(e["category"], 0) + b
        return out

    def top_buffers(self, n=8):
        """The n largest live buffers, for the OOM dump."""
        rows = sorted(self._sampled(), key=lambda t: -t[1])[:max(n, 0)]
        return [{"category": e["category"], "name": e["name"],
                 "space": e["space"], "bytes": b} for e, b in rows]

    def category_breakdown(self, category, space=SPACE_HBM):
        """{entry name: sampled bytes} for ONE category's live entries
        (all of them — `top_buffers` truncates). The serving tracker
        reads the `kv_cache` split (per-request entries vs
        `pool.unallocated`) from here to derive page utilization."""
        out = {}
        for e, b in self._sampled():
            if e["category"] == str(category) and e["space"] == space:
                out[e["name"]] = out.get(e["name"], 0) + b
        return out

    def set_plan(self, plan):
        """Attach a per-component memory plan ({component: planned
        bytes per device}, hbm space); `reconcile` reports
        plan-vs-ledger deltas from then on."""
        self._plan = dict(plan) if plan else None

    @property
    def plan(self):
        return dict(self._plan) if self._plan else None

    @property
    def peak(self):
        with self._lock:
            return dict(self._peak) if self._peak else None

    # -- fence reconciliation -------------------------------------------
    def reconcile(self, device_stats=None, rss=None, step=None,
                  top_n=8):
        """Ledger vs measured at a fence. `device_stats` is the
        `device_memory_stats()` dict (or None), `rss` the host RSS (or
        None). Returns the JSON-able `memory` event payload; updates
        the peak watermark WITH the attribution snapshot at the fence
        that observed it. Pure host arithmetic — zero device syncs."""
        totals = self.totals()
        hbm_cats = totals.get(SPACE_HBM, {})
        host_cats = totals.get(SPACE_HOST, {})
        hbm_ledger = int(sum(hbm_cats.values()))
        host_ledger = int(sum(host_cats.values()))

        dev_count = int((device_stats or {}).get("device_count", 0))
        in_use = (device_stats or {}).get("in_use_bytes")
        dev_peak = (device_stats or {}).get("peak_bytes")
        if not dev_count:
            in_use = dev_peak = None
        if rss is None:
            rss = (device_stats or {}).get("host_rss_bytes")

        # the ledger counts what ONE device holds; the allocator's
        # in_use is summed over ALL local devices — compare in
        # per-device terms or a D-device host inflates the residual by
        # (D-1)x the ledger and every OOM hint blames activations
        in_use_per_dev = None if in_use is None \
            else int(in_use) // max(dev_count, 1)
        payload = {
            "schema": MEMORY_SCHEMA_VERSION,
            "hbm": {
                "categories": dict(hbm_cats),
                "ledger_bytes": hbm_ledger,
                "measured_in_use": None if in_use is None
                else int(in_use),
                "measured_in_use_per_device": in_use_per_dev,
                "measured_peak": None if dev_peak is None
                else int(dev_peak),
                # residual = activations + XLA temporaries + allocator
                # overhead: what one device's measured allocation holds
                # beyond every registered long-lived buffer (per-device,
                # like the ledger and the per-chip peak)
                "residual_bytes": None if in_use_per_dev is None
                else in_use_per_dev - hbm_ledger,
                "device_count": dev_count,
            },
            "host": {
                "categories": dict(host_cats),
                "ledger_bytes": host_ledger,
                "rss_bytes": None if rss is None else int(rss),
                "residual_bytes": None if rss is None
                else int(rss) - host_ledger,
            },
            "top_buffers": self.top_buffers(top_n),
        }
        # watermark: the binding pressure number is the allocator peak
        # on-device; host RSS stands in off-TPU (device_count == 0)
        watermark = dev_peak if dev_peak is not None else rss
        if watermark is not None:
            with self._lock:
                if self._peak is None or \
                        watermark > self._peak["bytes"]:
                    self._peak = {
                        "bytes": int(watermark),
                        "space": SPACE_HBM if dev_peak is not None
                        else SPACE_HOST,
                        "step": step,
                        "categories": dict(
                            hbm_cats if dev_peak is not None
                            else host_cats),
                        "residual_bytes":
                            payload["hbm"]["residual_bytes"]
                            if dev_peak is not None
                            else payload["host"]["residual_bytes"],
                    }
                peak = dict(self._peak)
        else:
            peak = self.peak
        payload["peak"] = peak
        if self._plan:
            payload["plan"] = plan_vs_measured(self._plan, hbm_cats)
        return payload


# ----------------------------------------------------------------------
# plan-vs-measured validation
# ----------------------------------------------------------------------
def plan_vs_measured(plan, measured_categories):
    """Per-component deltas between a memory plan ({component:
    planned bytes per device}) and measured/ledger category bytes.
    delta_pct is signed relative to the plan; None planned-or-measured
    components report a None delta rather than fabricating 0."""
    out = {}
    for comp in sorted(set(plan) | set(measured_categories)):
        planned = plan.get(comp)
        got = measured_categories.get(comp)
        row = {"planned_bytes": None if planned is None
               else int(planned),
               "measured_bytes": None if got is None else int(got)}
        if planned and got is not None:
            row["delta_pct"] = round(
                (got - planned) / planned * 100.0, 3)
        else:
            row["delta_pct"] = None
        out[comp] = row
    return out


# ----------------------------------------------------------------------
# OOM forensics
# ----------------------------------------------------------------------
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED",
                "OUT OF MEMORY", "ALLOCATION FAILURE",
                "FAILED TO ALLOCATE")
# "OOM" needs word boundaries: "room"/"zoom"/"bloom" in an ordinary
# error message must not trigger memory forensics
_OOM_WORD = re.compile(r"\bOOM\b")


def classify_oom(exc):
    """True when an exception out of the step loop is an allocator
    failure (XLA RESOURCE_EXHAUSTED, host MemoryError, or any error
    whose message carries an out-of-memory marker). Classification is
    textual by design: jaxlib's XlaRuntimeError carries the gRPC
    status only in its message, and the flight path must not import
    backend-specific exception types to read it."""
    if isinstance(exc, MemoryError):
        return True
    try:
        text = f"{type(exc).__name__}: {exc}".upper()
    except Exception:  # ds-lint: allow[BROADEXC] classifying an exception whose __str__ itself raises; must not mask the original failure
        return False
    return any(m in text for m in _OOM_MARKERS) or \
        bool(_OOM_WORD.search(text))


def oom_hints(payload):
    """Actionable knobs ranked by what the reconciled payload says
    actually dominates. Every hint names the config key to turn."""
    hints = []
    hbm = payload.get("hbm", {})
    cats = hbm.get("categories", {})
    ledger = hbm.get("ledger_bytes") or 0
    # per-device, like the ledger and the residual
    measured = hbm.get("measured_in_use_per_device")
    residual = hbm.get("residual_bytes")
    if measured and residual is not None and residual > 0.5 * measured:
        hints.append(
            "activations/XLA temporaries dominate (residual "
            f"{residual / 2**30:.2f} GiB of {measured / 2**30:.2f} GiB "
            "in use): tighten remat — set activation checkpointing / "
            '"checkpoint_policy": "save_fused_epilogues" — or reduce '
            "train_micro_batch_size_per_gpu")
    if cats.get(CAT_CKPT):
        hints.append(
            "a checkpoint snapshot double-buffer was alive "
            f"({cats[CAT_CKPT] / 2**30:.2f} GiB): lower "
            "checkpoint.writer_queue_depth / keep_last, save less "
            "often, or set checkpoint.async_save false (inline saves "
            "skip the snapshot copy)")
    if cats.get(CAT_PREFETCH) and ledger and \
            cats[CAT_PREFETCH] > 0.1 * ledger:
        hints.append(
            "prefetch staging holds "
            f"{cats[CAT_PREFETCH] / 2**30:.2f} GiB: reduce "
            "async_dispatch.prefetch_depth")
    if cats.get(CAT_ZERO3) and ledger and \
            cats[CAT_ZERO3] > 0.15 * ledger:
        hints.append(
            "the ZeRO-3 gathered-param prefetch window holds "
            f"{cats[CAT_ZERO3] / 2**30:.2f} GiB: lower "
            "zero_optimization.stage3.prefetch_layers (live full-param "
            "bytes scale with prefetch_layers + 1), or set "
            "stage3.release_after_use true if the naive up-front "
            "gather mode is on")
    if cats.get(CAT_MOE) and ledger and \
            cats[CAT_MOE] > 0.15 * ledger:
        hints.append(
            "MoE dispatch buffers (all-to-all send/recv + capacity "
            f"slots) hold {cats[CAT_MOE] / 2**30:.2f} GiB of "
            f"{ledger / 2**30:.2f} GiB ledgered: lower "
            "moe.capacity_factor (buffer rows scale linearly with it) "
            "or raise moe.num_experts only together with the mesh "
            "expert axis (per-device buffer bytes scale with "
            "num_experts / expert-axis size)")
    if cats.get(CAT_OVERLAP) and ledger and \
            cats[CAT_OVERLAP] > 0.15 * ledger:
        hints.append(
            "comm/compute overlap in-flight staging (MoE dispatch "
            "window + ring send/recv rotations) holds "
            f"{cats[CAT_OVERLAP] / 2**30:.2f} GiB of "
            f"{ledger / 2**30:.2f} GiB ledgered: lower "
            "overlap.issue_distance (the ring window scales linearly "
            "with it), pin overlap.sites to fewer sites, or set "
            '"overlap": {"enabled": false} to trade the hidden '
            "collective latency back for the staging bytes")
    if cats.get(CAT_KV) and ledger and \
            cats[CAT_KV] > 0.3 * ledger:
        hints.append(
            "the serving KV-cache page pool holds "
            f"{cats[CAT_KV] / 2**30:.2f} GiB of {ledger / 2**30:.2f} "
            "GiB ledgered: lower inference.kv_cache.num_pages (the "
            "pool is preallocated — every page counts against HBM "
            "whether or not a request holds it), shrink "
            "inference.max_slots / max_seq_len, or serve int8 weights "
            '("inference": {"weight_bits": 8}) to free headroom')
    state = (cats.get(CAT_MASTER, 0) + cats.get(CAT_OPT, 0) +
             cats.get(CAT_GRADS, 0))
    if ledger and state > 0.5 * ledger:
        hints.append(
            "optimizer state (master+moments+accumulator) is "
            f"{state / 2**30:.2f} GiB of {ledger / 2**30:.2f} GiB "
            "ledgered: raise zero_optimization.stage, or offload "
            'masters to host ("cpu_offload": true)')
    if not hints:
        hints.append(
            "no single ledger category dominates: compare the "
            "per-category bytes in this dump against the memory plan "
            "(ZeroShardingPolicy.memory_plan) to find what grew")
    return hints
