"""Step tracing: named spans without per-step device fences.

The legacy `wall_clock_breakdown` timers (`utils/timer.py`) call
`jax.effects_barrier()` on every start/stop — per MICRO-step — which
serializes exactly the async-dispatch pipeline the engine is built
around. Spans here do two things instead:

  * when a JAX profiler is attached, each span wraps its region in
    `jax.profiler.TraceAnnotation`, so forward/backward/step/ckpt/
    prefetch show up as named ranges in the trace viewer (the
    annotation is near-free when no profiler is listening);
  * host wall time per span is accumulated WITHOUT any device fence
    and reported fence-aligned at the engine's sync fences. Under
    async dispatch a span therefore measures host-side DISPATCH time
    (what the hot loop actually pays), not device execution — device
    time belongs to the profiler. This is the documented
    `wall_clock_breakdown` behavior change (docs/monitoring.md).
"""

import threading
import time

SPAN_FORWARD = "forward"
SPAN_BACKWARD = "backward"
SPAN_STEP = "step"
SPAN_CKPT = "ckpt"
SPAN_PREFETCH = "prefetch"


_TRACE_ANNOTATION = None


def _annotation_cls():
    global _TRACE_ANNOTATION
    if _TRACE_ANNOTATION is None:
        try:
            import jax
            _TRACE_ANNOTATION = jax.profiler.TraceAnnotation
        except Exception:  # ds-lint: allow[BROADEXC] profiler API varies across jax versions; spans degrade to wall time only
            _TRACE_ANNOTATION = False
    return _TRACE_ANNOTATION


def _annotation(name):
    cls = _annotation_cls()
    if not cls:
        return None
    try:
        return cls(f"ds_tpu/{name}")
    except Exception:  # ds-lint: allow[BROADEXC] profiler annotation is decorative; the hot path must not fail on it
        return None


class _Span:
    __slots__ = ("t0", "annotation")

    def __init__(self, name):
        self.t0 = time.perf_counter()
        self.annotation = _annotation(name)
        if self.annotation is not None:
            try:
                self.annotation.__enter__()
            except Exception:  # ds-lint: allow[BROADEXC] profiler annotation is decorative; the hot path must not fail on it
                self.annotation = None


class StepTrace:
    """start/stop named spans (timer-style, so the engine's split
    forward()/backward()/step() call sites can use it) plus a `span`
    context manager; totals drain at fences."""

    def __init__(self):
        self._open = {}
        self._lock = threading.Lock()
        self._totals = {}
        self._counts = {}
        self._export = None      # (name, t0, dur) hook -> TraceExporter

    def set_export_sink(self, fn):
        """Route every closed span to the Perfetto exporter as well
        (monitor/trace_export.py) — spans are timed once, rendered in
        both the fence metrics and the trace file."""
        self._export = fn

    def start(self, name):
        self._open[name] = _Span(name)

    def stop(self, name):
        sp = self._open.pop(name, None)
        if sp is None:
            return
        if sp.annotation is not None:
            try:
                sp.annotation.__exit__(None, None, None)
            except Exception:  # ds-lint: allow[BROADEXC] profiler annotation is decorative; the hot path must not fail on it
                pass
        dt = time.perf_counter() - sp.t0
        with self._lock:
            self._totals[name] = self._totals.get(name, 0.0) + dt
            self._counts[name] = self._counts.get(name, 0) + 1
        if self._export is not None:
            try:
                self._export(name, sp.t0, dt)
            except Exception:  # ds-lint: allow[BROADEXC] trace-export hook on the hot path; a broken exporter must not stall the step loop
                pass

    def span(self, name):
        return _SpanCtx(self, name)

    def drain(self):
        """{name: {"ms": total, "count": n, "ms_per": mean}} since the
        last drain; resets the window."""
        with self._lock:
            totals, self._totals = self._totals, {}
            counts, self._counts = self._counts, {}
        return {
            name: {"ms": round(totals[name] * 1e3, 3),
                   "count": counts.get(name, 0),
                   "ms_per": round(
                       totals[name] * 1e3 / max(counts.get(name, 1), 1),
                       3)}
            for name in totals
        }


class _SpanCtx:
    def __init__(self, trace, name):
        self._trace = trace
        self._name = name

    def __enter__(self):
        self._trace.start(self._name)
        return self

    def __exit__(self, *exc):
        self._trace.stop(self._name)
        return False
