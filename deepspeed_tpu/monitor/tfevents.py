"""Dependency-free TensorBoard event-file (tfevents) writer + reader.

The reference routes TensorBoard through
`torch.utils.tensorboard.SummaryWriter` (engine.py:491-504 of our port
inherited that hard torch dependency). The scalar-event subset of the
format is tiny, so we write it natively:

  * a tfevents file is a sequence of TFRecords:
      uint64 length | uint32 masked-crc32c(length) |
      data[length]  | uint32 masked-crc32c(data)
    with CRC32C (Castagnoli) masked the TensorFlow way
    (((crc >> 15) | (crc << 17)) + 0xa282ead8).
  * each record is a serialized `Event` proto; we hand-encode the three
    fields the scalar dashboard needs — wall_time (field 1, double),
    step (field 2, varint), and either file_version (field 3, string —
    the mandatory first record, "brain.Event:2") or summary (field 5)
    holding `Summary.Value{tag, simple_value}` messages.

`read_tfevents` is the inverse (with CRC verification) so tests and
tools can load the files without torch or tensorflow installed.
"""

import os
import socket
import struct
import threading
import time

# ----------------------------------------------------------------------
# CRC32C (Castagnoli, reflected poly 0x82F63B78) — table-driven
# ----------------------------------------------------------------------
_CRC_TABLE = []


def _crc_table():
    if not _CRC_TABLE:
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def crc32c(data, crc=0):
    table = _crc_table()
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data):
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ----------------------------------------------------------------------
# minimal protobuf wire encoding (varint + the two wire types we emit)
# ----------------------------------------------------------------------
def _varint(n):
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field, wire_type):
    return _varint((field << 3) | wire_type)


def _len_delim(field, payload):
    return _key(field, 2) + _varint(len(payload)) + payload


def encode_scalar_event(wall_time, step, scalars):
    """Serialize one Event carrying `scalars` ({tag: float})."""
    summary = b"".join(
        _len_delim(1,                               # Summary.value
                   _len_delim(1, str(tag).encode("utf-8")) +   # tag
                   _key(2, 5) + struct.pack("<f", float(val)))  # simple_value
        for tag, val in scalars.items())
    return (_key(1, 1) + struct.pack("<d", float(wall_time)) +
            _key(2, 0) + _varint(max(0, int(step))) +
            _len_delim(5, summary))


def encode_file_version_event(wall_time):
    return (_key(1, 1) + struct.pack("<d", float(wall_time)) +
            _len_delim(3, b"brain.Event:2"))


def _record(data):
    header = struct.pack("<Q", len(data))
    return (header + struct.pack("<I", masked_crc32c(header)) +
            data + struct.pack("<I", masked_crc32c(data)))


class TFEventsWriter:
    """Append scalar events to one `events.out.tfevents.*` file."""

    def __init__(self, log_dir, filename_suffix=""):
        os.makedirs(log_dir, exist_ok=True)
        try:
            host = socket.gethostname()
        except OSError:
            host = "localhost"
        self.path = os.path.join(
            log_dir,
            f"events.out.tfevents.{int(time.time())}.{host}"
            f".{os.getpid()}{filename_suffix}")
        self._lock = threading.Lock()
        self._f = open(self.path, "ab")
        self._write(_record(encode_file_version_event(time.time())))

    def _write(self, blob):
        self._f.write(blob)

    def add_scalars(self, scalars, step, wall_time=None):
        """Write {tag: float} as one Event at `step`."""
        if not scalars:
            return
        wall_time = time.time() if wall_time is None else wall_time
        blob = _record(encode_scalar_event(wall_time, step, scalars))
        with self._lock:
            self._write(blob)

    def add_scalar(self, tag, value, step, wall_time=None):
        self.add_scalars({tag: value}, step, wall_time)

    def flush(self):
        """Make buffered records visible to a live TensorBoard reader
        (no fsync — durability is close()'s job; a per-fence fsync
        costs more than the fenced training window)."""
        with self._lock:
            self._f.flush()

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                # ds-lint: allow[LOCKBLOCK] one fsync at close only; the lock orders it against concurrent add_scalars writers
                os.fsync(self._f.fileno())
                self._f.close()


class SummaryWriter:
    """Drop-in for the `torch.utils.tensorboard.SummaryWriter` subset
    the engine uses (`add_scalar`/`flush`/`close`), backed by the
    native tfevents writer — no torch, no tensorflow."""

    def __init__(self, log_dir):
        self.log_dir = log_dir
        self._writer = TFEventsWriter(log_dir)

    def add_scalar(self, tag, scalar_value, global_step=None,
                   walltime=None):
        self._writer.add_scalar(tag, float(scalar_value),
                                0 if global_step is None else global_step,
                                wall_time=walltime)

    def flush(self):
        self._writer.flush()

    def close(self):
        self._writer.close()


# ----------------------------------------------------------------------
# reader (tests / tooling; torch-free loading proof)
# ----------------------------------------------------------------------
def _read_varint(buf, pos):
    shift, val = 0, 0
    while True:
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7


def _parse_fields(buf):
    """Yield (field_number, wire_type, value) over one message."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            val, pos = _read_varint(buf, pos)
        elif wt == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


def read_tfevents(path):
    """Parse a tfevents file into a list of event dicts
    ({wall_time, step, file_version?, scalars: {tag: value}}),
    verifying every record CRC."""
    events = []
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        header = data[pos:pos + 8]
        (length,) = struct.unpack("<Q", header)
        (hcrc,) = struct.unpack("<I", data[pos + 8:pos + 12])
        if hcrc != masked_crc32c(header):
            raise ValueError(f"corrupt record header at byte {pos}")
        body = data[pos + 12:pos + 12 + length]
        (bcrc,) = struct.unpack("<I",
                                data[pos + 12 + length:pos + 16 + length])
        if bcrc != masked_crc32c(body):
            raise ValueError(f"corrupt record body at byte {pos}")
        pos += 16 + length

        ev = {"wall_time": 0.0, "step": 0, "scalars": {}}
        for field, wt, val in _parse_fields(body):
            if field == 1 and wt == 1:
                ev["wall_time"] = struct.unpack("<d", val)[0]
            elif field == 2 and wt == 0:
                ev["step"] = val
            elif field == 3 and wt == 2:
                ev["file_version"] = val.decode("utf-8")
            elif field == 5 and wt == 2:
                for f2, wt2, v2 in _parse_fields(val):
                    if f2 == 1 and wt2 == 2:   # Summary.value
                        tag, sv = None, None
                        for f3, wt3, v3 in _parse_fields(v2):
                            if f3 == 1 and wt3 == 2:
                                tag = v3.decode("utf-8")
                            elif f3 == 2 and wt3 == 5:
                                sv = struct.unpack("<f", v3)[0]
                        if tag is not None and sv is not None:
                            ev["scalars"][tag] = sv
        events.append(ev)
    return events
