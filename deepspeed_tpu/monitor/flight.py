"""Crash/stall flight recorder.

When a run dies — SIGKILL'd by the scheduler, wedged until the
watchdog fires, or killed by an exception out of the step loop — the
monitor's evidence normally evaporates with the process. The flight
recorder is the bounded black box: a ring buffer retaining the last
`capacity` monitor events (metrics fences, ckpt commits, stalls,
numerics windows, crash records) plus the current per-subsystem
heartbeat ages, dumped ATOMICALLY (tmp + fsync + rename — the PR-3
writer discipline) to `flight_<ts>.json` so the run's final seconds
survive it.

Dump triggers (monitor/__init__.py wires them):
  * watchdog fire — the stall diagnostic rides along as `extra`;
  * uncaught exception out of `train_batch` — the exception repr +
    traceback tail ride along;
  * SIGTERM — a module-level handler (installed once, chaining any
    existing handler) dumps every live recorder, then re-raises the
    default action so exit codes stay honest;
  * abnormal interpreter exit — an atexit hook dumps recorders whose
    engine stepped but never reached `monitor.close()` (a clean close
    disarms it; an idle engine that never trained stays silent).

Everything here is host-side and thread-safe: `record` is a deque
append under a lock (the watchdog and checkpoint writer call it from
their threads), and `dump` never touches the device — a wedged chip
cannot wedge the dump that is supposed to explain it.
"""

import collections
import json
import os
import signal
import threading
import time
import traceback
import weakref

from deepspeed_tpu.utils.logging import logger

FLIGHT_SCHEMA_VERSION = 1
FLIGHT_PREFIX = "flight_"

# live recorders for the process-level SIGTERM/atexit hooks
_LIVE = weakref.WeakSet()
_HOOKS_INSTALLED = False
_PREV_SIGTERM = None
_hooks_lock = threading.Lock()


def _dump_all(reason):
    for rec in list(_LIVE):
        try:
            rec.dump(reason)
        except Exception:  # ds-lint: allow[BROADEXC] a post-mortem dump must never raise out of a signal handler
            pass


def _on_sigterm(signum, frame):
    _dump_all("sigterm")
    # restore + re-raise so the process still dies with the SIGTERM
    # disposition the sender expects (chained handlers run first)
    prev = _PREV_SIGTERM
    if callable(prev):
        prev(signum, frame)
    else:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


def _on_atexit():
    # only recorders still armed (engine stepped, monitor.close()
    # never ran) dump here — a clean shutdown leaves no crumbs; an
    # output dir already deleted (ephemeral run dirs) is not recreated
    for rec in list(_LIVE):
        try:
            if rec.armed and os.path.isdir(rec.out_dir):
                rec.dump("atexit")
        except Exception:  # ds-lint: allow[BROADEXC] atexit hooks must not raise during interpreter teardown
            pass


def _install_hooks():
    global _HOOKS_INSTALLED, _PREV_SIGTERM
    with _hooks_lock:
        if _HOOKS_INSTALLED:
            return
        import atexit
        atexit.register(_on_atexit)
        try:
            if threading.current_thread() is threading.main_thread():
                prev = signal.getsignal(signal.SIGTERM)
                # leave a non-default handler alone — the application
                # owns SIGTERM then; it can call dump() itself
                if prev in (signal.SIG_DFL, None):
                    _PREV_SIGTERM = prev
                    signal.signal(signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):
            pass          # non-main thread / restricted environment
        _HOOKS_INSTALLED = True


class FlightRecorder:
    """Bounded event ring + atomic post-mortem dumps."""

    def __init__(self, out_dir, capacity=256, rank=0, step_fn=None,
                 heartbeats_fn=None, context_fn=None):
        self.out_dir = out_dir
        self.capacity = int(capacity)
        self.rank = int(rank)
        self._step_fn = step_fn              # () -> current step
        self._heartbeats_fn = heartbeats_fn  # () -> (ages, terminal)
        self._context_fn = context_fn        # () -> extra context dict
        try:
            # eager: the atexit hook only dumps into a STILL-existing
            # dir (ephemeral run dirs deleted before exit are left
            # alone), so the dir must exist from the start
            os.makedirs(out_dir, exist_ok=True)
        except OSError:
            # unwritable dir: dump() retries and logs at dump time
            pass
        self._ring = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._context = {}
        self._dumps = []          # paths written this life
        self.armed = False        # True once the engine stepped
        _LIVE.add(self)
        _install_hooks()

    # ------------------------------------------------------------------
    def record(self, event):
        """Retain one (already JSON-able) monitor event."""
        with self._lock:
            self._ring.append(event)

    def set_context(self, **kv):
        """Sticky forensic context (e.g. the last numerics window and
        its first-NaN attribution) included in every dump."""
        with self._lock:
            self._context.update(kv)

    def record_exception(self, exc):
        tb = traceback.format_exc(limit=20)
        self.record({
            "kind": "crash", "ts": round(time.time(), 6),
            "error": repr(exc), "traceback_tail": tb[-4000:]})

    def arm(self):
        self.armed = True

    def disarm(self):
        """A clean close: no atexit dump for this recorder."""
        self.armed = False
        _LIVE.discard(self)

    # ------------------------------------------------------------------
    def snapshot(self, reason, extra=None):
        with self._lock:
            events = list(self._ring)
            context = dict(self._context)
        heartbeats, terminal = {}, []
        if self._heartbeats_fn is not None:
            try:
                heartbeats, terminal = self._heartbeats_fn()
            except Exception:  # ds-lint: allow[BROADEXC] a broken context callback must not kill the dump that documents the crash
                pass
        if self._context_fn is not None:
            try:
                context.update(self._context_fn() or {})
            except Exception:  # ds-lint: allow[BROADEXC] a broken context callback must not kill the dump that documents the crash
                pass
        step = None
        if self._step_fn is not None:
            try:
                step = self._step_fn()
            except Exception:  # ds-lint: allow[BROADEXC] a broken context callback must not kill the dump that documents the crash
                pass
        doc = {
            "v": FLIGHT_SCHEMA_VERSION,
            "kind": "flight",
            "reason": reason,
            "ts": round(time.time(), 6),
            "rank": self.rank,
            "step": step,
            "heartbeat_age_sec": heartbeats,
            "terminal_subsystems": sorted(terminal),
            "context": context,
            "events": events,
        }
        if extra:
            doc["extra"] = extra
        return doc

    def dump(self, reason, extra=None):
        """Atomic dump: `flight_<ts>.json.tmp` -> fsync -> rename.
        Returns the path, or None when the directory is unwritable (a
        post-mortem must never raise out of a signal handler)."""
        doc = self.snapshot(reason, extra=extra)
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            ts = time.strftime("%Y%m%d_%H%M%S")
            ms = int((time.time() % 1) * 1000)
            path = os.path.join(
                self.out_dir,
                f"{FLIGHT_PREFIX}{ts}_{ms:03d}_r{self.rank}.json")
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, separators=(",", ":"),
                          default=_json_default)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except Exception:
            try:
                logger.warning("flight recorder dump failed",
                               exc_info=True)
            except Exception:  # ds-lint: allow[BROADEXC] logging during interpreter teardown may itself fail; the dump path must not raise
                pass
            return None
        self._dumps.append(path)
        try:
            logger.warning(
                f"flight recorder: dumped last {len(doc['events'])} "
                f"events to {path} (reason: {reason})")
        except Exception:  # ds-lint: allow[BROADEXC] logging during interpreter teardown may itself fail; the dump path must not raise
            pass
        return path


def _json_default(x):
    try:
        return float(x)
    except (TypeError, ValueError):
        return str(x)


def list_flight_dumps(out_dir):
    """flight_*.json files in a monitor output dir, oldest first."""
    if not os.path.isdir(out_dir):
        return []
    names = sorted(n for n in os.listdir(out_dir)
                   if n.startswith(FLIGHT_PREFIX) and
                   n.endswith(".json"))
    return [os.path.join(out_dir, n) for n in names]
