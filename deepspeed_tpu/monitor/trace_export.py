"""Perfetto/Chrome trace-event export.

Renders the monitor's forensic timeline into the Chrome trace-event
JSON format (the `{"traceEvents": [...]}` object form) that opens
directly in Perfetto (ui.perfetto.dev) or chrome://tracing:

  * the fence-aligned host spans (forward/backward/step/ckpt) become
    complete ("X") events on one track per span name — the StepTrace
    feeds them through its export sink, so span timing is recorded
    once and rendered everywhere;
  * host subsystems (checkpoint writer commits, prefetch staging,
    offload host steps) get their own tracks, stamped from the threads
    that actually did the work;
  * the pipeline timeline: the 1F1B / interleaved clock tables
    (`runtime/pipe/schedule.py` via `interp.build_clock_tables`) are
    the compiled executor's exact per-tick (stage, microbatch, chunk)
    placement; each `train_batch` dispatch stamps them with its real
    host dispatch window (`pipe/engine.py`), and the exporter lays the
    ticks out uniformly across that window — one track per stage, one
    "X" event per busy (tick, stage) carrying mb/chunk args, idle
    ticks left empty so the fill/drain bubble is VISIBLE as white
    space. The computed bubble fraction (1 - busy/(ticks*stages))
    rides in the trace metadata next to the schedule's analytic
    (p-1)/(v*m+p-1).

Events use the documented trace-format keys: `name`, `ph`, `ts`
(microseconds), `dur` ("X" only), `pid`, `tid`, `cat`, `args`.
`pid` is the JAX process index (rank), so per-rank shards merge into
one multi-rank timeline (`bin/ds_trace merge`). Track naming rides
"M"/thread_name metadata events.

The buffer is a bounded deque (`monitor.trace.max_events`): a run that
traces forever retains the LAST window, which is exactly the forensic
slice a post-mortem needs. `write(path)` is atomic
(tmp + fsync + rename — the PR-3 writer discipline): a dump racing a
reader or a kill never leaves a torn JSON.
"""

import collections
import json
import os
import threading
import time

TRACE_SCHEMA_VERSION = 1

# Perfetto renders these category colors distinctly; they also make
# programmatic filtering (ds_trace summary) unambiguous.
CAT_SPAN = "host_span"
CAT_SUBSYSTEM = "subsystem"
CAT_PIPE_FWD = "pipe_fwd"
CAT_PIPE_BWD = "pipe_bwd"
CAT_MARK = "mark"
# the serving timeline (monitor/serving.py, ISSUE 14): one track per
# decode slot; queue-wait, prefill chunks and decode windows are
# distinct slice types, and each finished request leaves one instant
# carrying its lifecycle stats (the `ds_trace summary --serving` rows)
CAT_SERVE_QUEUE = "serving_queue"
CAT_SERVE_PREFILL = "serving_prefill"
CAT_SERVE_DECODE = "serving_decode"
CAT_SERVE_REQUEST = "serving_request"


def analytic_bubble_fraction(stages, micro_batches, num_virtual_stages=1):
    """The schedule's fill/drain bubble: (p-1)/(v*m+p-1) stage-time
    units idle per stage (Megatron interleaved-1F1B formula; v=1 gives
    plain 1F1B's (p-1)/(m+p-1))."""
    p, m, v = stages, micro_batches, num_virtual_stages
    return (p - 1) / float(v * m + p - 1)


def tables_bubble_fraction(tables):
    """Measured bubble of a clock-table set: the fraction of
    (tick, stage) slots executing neither a forward nor a backward."""
    fwd, bwd = tables["fwd_mb"], tables["bwd_mb"]
    total = fwd.shape[0] * fwd.shape[1]
    busy = int((fwd >= 0).sum() + (bwd >= 0).sum())
    return 1.0 - busy / float(total)


class TraceExporter:
    """Bounded trace-event buffer with atomic JSON export.

    Thread-safe: the checkpoint writer and prefetch worker stamp their
    tracks from their own threads. Appends are deque ops under a lock;
    nothing here touches the device.
    """

    def __init__(self, rank=0, max_events=200000, meta=None):
        self.rank = int(rank)
        self._events = collections.deque(maxlen=int(max_events))
        self._lock = threading.Lock()
        self._tracks = {}            # name -> tid
        self._track_meta = []        # emitted thread_name records
        self._meta = dict(meta or {})
        self._pipeline = None        # bubble/occupancy metadata
        self._t0 = time.perf_counter()
        self._epoch = time.time() - self._t0   # perf_counter -> unix

    # ------------------------------------------------------------------
    # track + event primitives
    # ------------------------------------------------------------------
    def set_meta(self, **kv):
        """Attach JSON-able metadata to the trace's otherData (e.g. a
        memory plan for `ds_trace summary`'s plan-vs-measured)."""
        with self._lock:
            self._meta.update(kv)

    def _tid(self, track):
        tid = self._tracks.get(track)
        if tid is None:
            tid = self._tracks[track] = len(self._tracks)
            self._track_meta.append({
                "name": "thread_name", "ph": "M", "pid": self.rank,
                "tid": tid, "args": {"name": track}})
        return tid

    def _us(self, t_perf):
        # trace `ts` is microseconds; anchor on the unix clock so
        # shards from different processes merge on one axis
        return (t_perf + self._epoch) * 1e6

    def complete(self, track, name, t_start, dur, cat=CAT_SPAN,
                 args=None):
        """One complete ("X") slice. `t_start` is a time.perf_counter()
        stamp; `dur` seconds."""
        with self._lock:
            ev = {"name": name, "ph": "X", "cat": cat,
                  "ts": round(self._us(t_start), 3),
                  "dur": round(dur * 1e6, 3),
                  "pid": self.rank, "tid": self._tid(track)}
            if args:
                ev["args"] = args
            self._events.append(ev)

    def instant(self, track, name, t_at=None, cat=CAT_MARK, args=None):
        with self._lock:
            ev = {"name": name, "ph": "i", "s": "t", "cat": cat,
                  "ts": round(self._us(
                      time.perf_counter() if t_at is None else t_at), 3),
                  "pid": self.rank, "tid": self._tid(track)}
            if args:
                ev["args"] = args
            self._events.append(ev)

    def counter(self, track, name, values, t_at=None):
        with self._lock:
            self._events.append({
                "name": name, "ph": "C",
                "ts": round(self._us(
                    time.perf_counter() if t_at is None else t_at), 3),
                "pid": self.rank, "tid": self._tid(track),
                "args": {k: float(v) for k, v in values.items()}})

    # ------------------------------------------------------------------
    # pipeline timeline
    # ------------------------------------------------------------------
    def add_pipeline_step(self, tables, meta, t_start, t_end, step=None):
        """Lay one train_batch dispatch window out over the clock
        tables: tick t of T occupies
        [t_start + t*dt, t_start + (t+1)*dt), dt = (t_end-t_start)/T.
        Real per-tick device time is not host-observable without a
        fence; the uniform layout preserves exactly what the tables
        guarantee — order, concurrency and the bubble — which is what
        a bubble post-mortem needs.

        `tables`: build_clock_tables output (numpy). `meta`:
        {"stages", "micro_batches", "num_virtual_stages"}."""
        fwd_mb, bwd_mb = tables["fwd_mb"], tables["bwd_mb"]
        fwd_ch, bwd_ch = tables["fwd_chunk"], tables["bwd_chunk"]
        T, S = fwd_mb.shape
        dt = max((t_end - t_start), 1e-9) / T
        s_args = None if step is None else {"step": int(step)}
        for t in range(T):
            ts = t_start + t * dt
            for s in range(S):
                if fwd_mb[t, s] >= 0:
                    args = {"mb": int(fwd_mb[t, s]),
                            "chunk": int(fwd_ch[t, s]), "tick": t}
                    if s_args:
                        args.update(s_args)
                    self.complete(
                        f"pipe/stage{s}",
                        f"F mb{int(fwd_mb[t, s])} c{int(fwd_ch[t, s])}",
                        ts, dt, cat=CAT_PIPE_FWD, args=args)
                if bwd_mb[t, s] >= 0:
                    args = {"mb": int(bwd_mb[t, s]),
                            "chunk": int(bwd_ch[t, s]), "tick": t}
                    if s_args:
                        args.update(s_args)
                    self.complete(
                        f"pipe/stage{s}",
                        f"B mb{int(bwd_mb[t, s])} c{int(bwd_ch[t, s])}",
                        ts, dt, cat=CAT_PIPE_BWD, args=args)
        if self._pipeline is None:
            p = int(meta["stages"])
            m = int(meta["micro_batches"])
            v = int(meta.get("num_virtual_stages", 1))
            self._pipeline = {
                "stages": p, "micro_batches": m,
                "num_virtual_stages": v, "ticks": int(T),
                "bubble_fraction": round(tables_bubble_fraction(tables),
                                         6),
                "analytic_bubble_fraction": round(
                    analytic_bubble_fraction(p, m, v), 6),
            }

    # ------------------------------------------------------------------
    # export
    # ------------------------------------------------------------------
    def to_dict(self):
        with self._lock:
            events = self._track_meta + list(self._events)
            other = {"schema": TRACE_SCHEMA_VERSION, "rank": self.rank,
                     **self._meta}
            if self._pipeline is not None:
                other["pipeline"] = dict(self._pipeline)
        # exported order is ts order (metadata first, like merge):
        # some slices are stamped retroactively — the serving tracker
        # back-dates a request's queue-wait to its arrival when the
        # slot is granted — and the Chrome format (and our validator)
        # wants per-track monotonic ts regardless of append order
        events.sort(key=lambda e: (e.get("ph") != "M",
                                   e.get("ts", 0)))
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": other}

    def write(self, path):
        """Atomic dump: serialize to `<path>.tmp`, fsync, rename —
        a concurrent reader or a kill mid-write never sees torn JSON."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path


# ----------------------------------------------------------------------
# shard merge + summary (the bin/ds_trace CLI core)
# ----------------------------------------------------------------------
def load_trace(path):
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):          # bare-array trace format
        doc = {"traceEvents": doc, "otherData": {}}
    return doc


def merge_traces(docs):
    """Merge per-rank trace shards into one document. Events already
    carry their rank as `pid` and absolute unix-anchored `ts`, so the
    merge is concatenation + a stable ts sort; per-rank otherData nests
    under "ranks"."""
    events = []
    ranks = {}
    pipeline = None
    memory_plan = None
    for doc in docs:
        events.extend(doc.get("traceEvents", []))
        other = doc.get("otherData", {}) or {}
        ranks[str(other.get("rank", len(ranks)))] = other
        pipeline = pipeline or other.get("pipeline")
        memory_plan = memory_plan or other.get("memory_plan")
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    other = {"schema": TRACE_SCHEMA_VERSION, "merged_ranks": len(docs),
             "ranks": ranks}
    if pipeline:
        other["pipeline"] = pipeline
    if memory_plan:
        # promoted like `pipeline`: summary of a merged doc must keep
        # plan-vs-measured working
        other["memory_plan"] = memory_plan
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": other}


def summarize_trace(doc):
    """Occupancy per track + pipeline bubble, computed FROM THE EVENTS
    (not the metadata), so a merged/filtered trace still summarizes
    honestly. Returns a JSON-able dict."""
    tracks = {}      # (pid, tid) -> {"busy_us", "t0", "t1", "events"}
    names = {}
    pipe_busy = {}
    mem_counters = {}   # series name -> {key: {"last", "peak"}}
    serving_reqs = []   # args of serving_request finish instants
    for ev in doc.get("traceEvents", []):
        ph = ev.get("ph")
        if ph == "M" and ev.get("name") == "thread_name":
            names[(ev.get("pid"), ev.get("tid"))] = \
                ev.get("args", {}).get("name")
            continue
        if ph in ("i", "I") and ev.get("cat") == CAT_SERVE_REQUEST:
            # one instant per finished request, args = its lifecycle
            # stats (monitor/serving.py) — the summary recomputes the
            # percentiles FROM these, so merged/filtered traces still
            # summarize honestly (the pipeline-bubble convention)
            serving_reqs.append(ev.get("args") or {})
            continue
        if ph == "C" and ev.get("name") in ("hbm_bytes", "host_bytes"):
            # the memory ledger's per-category counter tracks, keyed
            # per RANK (pid): events are ts-ordered within a rank, so
            # "last wins" + running max give that rank's final
            # composition and per-category peak — mixing ranks here
            # would interleave unrelated series
            series = mem_counters.setdefault(
                (ev.get("pid"), ev["name"]), {})
            for k, v in (ev.get("args") or {}).items():
                row = series.setdefault(k, {"last": 0.0, "peak": 0.0})
                row["last"] = float(v)
                row["peak"] = max(row["peak"], float(v))
            continue
        if ph != "X":
            continue
        key = (ev.get("pid"), ev.get("tid"))
        tr = tracks.setdefault(
            key, {"busy_us": 0.0, "t0": float("inf"), "t1": 0.0,
                  "events": 0})
        ts, dur = float(ev.get("ts", 0)), float(ev.get("dur", 0))
        tr["busy_us"] += dur
        tr["t0"] = min(tr["t0"], ts)
        tr["t1"] = max(tr["t1"], ts + dur)
        tr["events"] += 1
        if ev.get("cat") in (CAT_PIPE_FWD, CAT_PIPE_BWD):
            # group by dispatch window (the "step" arg every pipeline
            # event carries): the gap BETWEEN train_batch dispatches is
            # host time, not pipeline bubble — a global span would bill
            # it to the schedule
            win = (ev.get("pid"), (ev.get("args") or {}).get("step"))
            pb = pipe_busy.setdefault(
                win, {"busy": 0.0, "t0": float("inf"), "t1": 0.0,
                      "stages": set()})
            pb["busy"] += dur
            pb["t0"] = min(pb["t0"], ts)
            pb["t1"] = max(pb["t1"], ts + dur)
            pb["stages"].add(key)
    out = {"tracks": {}}
    for key, tr in sorted(tracks.items()):
        span = max(tr["t1"] - tr["t0"], 1e-9)
        name = names.get(key) or f"pid{key[0]}/tid{key[1]}"
        out["tracks"][name] = {
            "events": tr["events"],
            "busy_ms": round(tr["busy_us"] / 1e3, 3),
            "span_ms": round(span / 1e3, 3),
            "occupancy": round(tr["busy_us"] / span, 4),
        }
    if pipe_busy:
        busy = wall = 0.0
        stages = 0
        for pb in pipe_busy.values():
            stages = max(stages, len(pb["stages"]))
            busy += pb["busy"]
            wall += max(pb["t1"] - pb["t0"], 1e-9) * len(pb["stages"])
        out["pipeline"] = {
            "stages": stages,
            "dispatch_windows": len(pipe_busy),
            "busy_ms": round(busy / 1e3, 3),
            "wall_stage_ms": round(wall / 1e3, 3),
            "occupancy": round(busy / wall, 4),
            "bubble_fraction": round(1.0 - busy / wall, 4),
        }
        analytic = (doc.get("otherData", {}) or {}).get("pipeline", {})
        if analytic:
            out["pipeline"]["analytic_bubble_fraction"] = \
                analytic.get("analytic_bubble_fraction")
            out["pipeline"]["schedule"] = {
                k: analytic.get(k) for k in
                ("stages", "micro_batches", "num_virtual_stages",
                 "ticks")}
    if mem_counters:
        # merge ranks by MAX: ledger values are per-device, so the
        # cross-rank max is the binding pressure number (under SPMD
        # the ranks are near-identical anyway); `ranks` says how many
        # were merged so an asymmetric fleet is visible
        merged = {}
        pids = set()
        for (pid, name), rows in mem_counters.items():
            pids.add(pid)
            series = merged.setdefault(name, {})
            for k, v in rows.items():
                row = series.setdefault(k, {"last": 0.0, "peak": 0.0})
                row["last"] = max(row["last"], v["last"])
                row["peak"] = max(row["peak"], v["peak"])
        mem = {name: {k: {"last_bytes": int(v["last"]),
                          "peak_bytes": int(v["peak"])}
                      for k, v in sorted(rows.items())}
               for name, rows in merged.items()}
        if len(pids) > 1:
            mem["ranks"] = len(pids)
        plan = (doc.get("otherData", {}) or {}).get("memory_plan")
        if plan:
            from deepspeed_tpu.monitor.memory import plan_vs_measured
            peaks = {k: v["peak_bytes"]
                     for k, v in mem.get("hbm_bytes", {}).items()
                     if k != "residual"}
            mem["plan_vs_measured"] = plan_vs_measured(plan, peaks)
        out["memory"] = mem
    if serving_reqs:
        out["serving"] = summarize_serving_requests(serving_reqs)
    return out


def _weighted_percentile(pairs, p):
    """Percentile over (value, weight) pairs (weight = token count for
    per-token latencies; 1 for per-request stats). None when empty."""
    pairs = sorted((float(v), max(int(w), 0)) for v, w in pairs
                   if v is not None)
    total = sum(w for _, w in pairs)
    if total <= 0:
        return None
    target = p * total
    acc = 0
    for v, w in pairs:
        acc += w
        if acc >= target:
            return v
    return pairs[-1][0]


def summarize_serving_requests(rows):
    """Per-request serving stats from the `serving_request` finish
    instants: p50/p99 queue-wait, TTFT and per-token decode latency
    (token-weighted), plus goodput vs throughput (tokens from requests
    that met every configured SLO target vs all tokens) and the
    queue-wait share of end-to-end latency — the saturation signal."""
    def pcts(key, weighted=False):
        pairs = [(r.get(key), r.get("new_tokens", 1) if weighted else 1)
                 for r in rows]
        return {"p50": _weighted_percentile(pairs, 0.50),
                "p99": _weighted_percentile(pairs, 0.99)}

    tokens = sum(int(r.get("new_tokens") or 0) for r in rows)
    goodput = sum(int(r.get("new_tokens") or 0) for r in rows
                  if r.get("slo_ok"))
    queued = sum(float(r.get("queued_ms") or 0.0) for r in rows)
    e2e = queued + sum(float(r.get("wall_ms") or 0.0) for r in rows)
    return {
        "requests": len(rows),
        "new_tokens": tokens,
        "queued_ms": pcts("queued_ms"),
        "ttft_ms": pcts("ttft_ms"),
        "token_ms": pcts("token_ms", weighted=True),
        "goodput_tokens": goodput,
        "goodput_fraction": round(goodput / tokens, 4) if tokens else None,
        "queue_wait_share": round(queued / e2e, 4) if e2e > 0 else None,
    }
