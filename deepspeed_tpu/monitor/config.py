"""`monitor` config block parsing.

    {"monitor": {"enabled": true,
                 "sinks": ["jsonl", {"type": "tensorboard"}],
                 "output_path": "runs/exp1/monitor",
                 "job_name": "",
                 "flush_interval": 0,
                 "stall_timeout_sec": 0,
                 "stall_probe": false,
                 "all_ranks": false}}

enabled: master switch; off (the default) makes every monitor hook a
  single attribute check.
sinks: list of sink names or {"type": name, ...opts} dicts
  (monitor/sinks.py). Default ["jsonl"].
output_path: directory sinks write under (default "./ds_monitor").
flush_interval: seconds between sink flushes (0 = flush every fence).
  A flush makes buffered records VISIBLE to readers; it never fsyncs —
  crash durability is paid once, at close() (a per-fence fsync costs
  more than the fenced training window on some filesystems).
stall_timeout_sec: fire the stall watchdog when no sync fence advances
  for this long (0 = watchdog off).
stall_probe: on a stall, also time an `effects_barrier` on a
  sacrificial thread to tell a wedged device from a stalled host.
all_ranks: emit events from every process (default: rank 0 only, with
  a per-rank filename suffix when enabled).
"""

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config_utils import get_scalar_param


class MonitorConfigError(Exception):
    pass


class DeepSpeedMonitorConfig:
    def __init__(self, param_dict):
        block = param_dict.get(C.MONITOR, {})
        if not isinstance(block, dict):
            raise MonitorConfigError(
                f'"monitor" must be a dict, got {block!r}')
        self.enabled = bool(get_scalar_param(
            block, C.MONITOR_ENABLED, C.MONITOR_ENABLED_DEFAULT))
        self.sinks = block.get(C.MONITOR_SINKS,
                               list(C.MONITOR_SINKS_DEFAULT))
        if not isinstance(self.sinks, (list, tuple)):
            raise MonitorConfigError(
                f"monitor.sinks must be a list, got {self.sinks!r}")
        from deepspeed_tpu.monitor.sinks import VALID_SINKS
        for spec in self.sinks:
            name = spec if isinstance(spec, str) else \
                (spec or {}).get("type")
            if name not in VALID_SINKS:
                raise MonitorConfigError(
                    f"unknown monitor sink {name!r}; valid: "
                    f"{list(VALID_SINKS)}")
        self.output_path = get_scalar_param(
            block, C.MONITOR_OUTPUT_PATH, C.MONITOR_OUTPUT_PATH_DEFAULT)
        self.job_name = get_scalar_param(
            block, C.MONITOR_JOB_NAME, C.MONITOR_JOB_NAME_DEFAULT)
        self.flush_interval = float(get_scalar_param(
            block, C.MONITOR_FLUSH_INTERVAL,
            C.MONITOR_FLUSH_INTERVAL_DEFAULT))
        if self.flush_interval < 0:
            raise MonitorConfigError(
                "monitor.flush_interval must be >= 0 "
                f"(0 = flush every fence), got {self.flush_interval}")
        self.stall_timeout_sec = float(get_scalar_param(
            block, C.MONITOR_STALL_TIMEOUT_SEC,
            C.MONITOR_STALL_TIMEOUT_SEC_DEFAULT))
        if self.stall_timeout_sec < 0:
            raise MonitorConfigError(
                "monitor.stall_timeout_sec must be >= 0 (0 = off), "
                f"got {self.stall_timeout_sec}")
        self.stall_probe = bool(get_scalar_param(
            block, C.MONITOR_STALL_PROBE, C.MONITOR_STALL_PROBE_DEFAULT))
        self.all_ranks = bool(get_scalar_param(
            block, C.MONITOR_ALL_RANKS, C.MONITOR_ALL_RANKS_DEFAULT))
