"""`monitor` config block parsing.

    {"monitor": {"enabled": true,
                 "sinks": ["jsonl", {"type": "tensorboard"}],
                 "output_path": "runs/exp1/monitor",
                 "job_name": "",
                 "flush_interval": 0,
                 "stall_timeout_sec": 0,
                 "stall_probe": false,
                 "all_ranks": false}}

enabled: master switch; off (the default) makes every monitor hook a
  single attribute check.
sinks: list of sink names or {"type": name, ...opts} dicts
  (monitor/sinks.py). Default ["jsonl"].
output_path: directory sinks write under (default "./ds_monitor").
flush_interval: seconds between sink flushes (0 = flush every fence).
  A flush makes buffered records VISIBLE to readers; it never fsyncs —
  crash durability is paid once, at close() (a per-fence fsync costs
  more than the fenced training window on some filesystems).
stall_timeout_sec: fire the stall watchdog when no sync fence advances
  for this long (0 = watchdog off).
stall_probe: on a stall, also time an `effects_barrier` on a
  sacrificial thread to tell a wedged device from a stalled host.
stall_escalate_after: consecutive watchdog fires (one per further
  stall_timeout_sec of silence) before ONE terminal `stall_escalated`
  event is emitted — flight dump + sink event — and the episode goes
  quiet (0 = off; the elastic supervisor consumes the verdict).
all_ranks: emit events from every process (default: rank 0 only, with
  a per-rank filename suffix when enabled).
peak_flops_override: MFU denominator in FLOP/s per chip (0 = auto:
  nominal TPU peak on real chips, None off-TPU). Makes MFU and
  tokens_per_sec_per_chip meaningful on CPU/virtual-mesh runs.
trace: {"enabled", "path", "max_events"} — Perfetto/Chrome
  trace-event export (monitor/trace_export.py): fence-aligned spans +
  the per-microbatch pipeline timeline, written at close()/watchdog
  fire/export_trace(), merged across ranks by bin/ds_trace.
flight: {"enabled" (default true), "capacity", "path"} — crash/stall
  flight recorder (monitor/flight.py): the last N events + heartbeat
  ages, dumped atomically on watchdog fire / uncaught train_batch
  exception / SIGTERM / abnormal exit.
numerics: {"enabled"} — device-side per-layer numerics health
  (monitor/numerics.py): per-group grad stats + per-layer activation
  stats folded inside the jitted step, drained at the same fences.
memory: {"enabled" (default true), "top_buffers"} — live HBM/host
  byte ledger (monitor/memory.py): per-subsystem allocation
  attribution reconciled against the allocator at every fence, peak
  watermark with at-peak attribution, Perfetto per-category counter
  tracks, and OOM forensics on RESOURCE_EXHAUSTED crashes.
"""

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config_utils import get_scalar_param


class MonitorConfigError(Exception):
    pass


class DeepSpeedMonitorConfig:
    def __init__(self, param_dict):
        block = param_dict.get(C.MONITOR, {})
        if not isinstance(block, dict):
            raise MonitorConfigError(
                f'"monitor" must be a dict, got {block!r}')
        self.enabled = bool(get_scalar_param(
            block, C.MONITOR_ENABLED, C.MONITOR_ENABLED_DEFAULT))
        self.sinks = block.get(C.MONITOR_SINKS,
                               list(C.MONITOR_SINKS_DEFAULT))
        if not isinstance(self.sinks, (list, tuple)):
            raise MonitorConfigError(
                f"monitor.sinks must be a list, got {self.sinks!r}")
        from deepspeed_tpu.monitor.sinks import VALID_SINKS
        for spec in self.sinks:
            name = spec if isinstance(spec, str) else \
                (spec or {}).get("type")
            if name not in VALID_SINKS:
                raise MonitorConfigError(
                    f"unknown monitor sink {name!r}; valid: "
                    f"{list(VALID_SINKS)}")
        self.output_path = get_scalar_param(
            block, C.MONITOR_OUTPUT_PATH, C.MONITOR_OUTPUT_PATH_DEFAULT)
        self.job_name = get_scalar_param(
            block, C.MONITOR_JOB_NAME, C.MONITOR_JOB_NAME_DEFAULT)
        self.flush_interval = float(get_scalar_param(
            block, C.MONITOR_FLUSH_INTERVAL,
            C.MONITOR_FLUSH_INTERVAL_DEFAULT))
        if self.flush_interval < 0:
            raise MonitorConfigError(
                "monitor.flush_interval must be >= 0 "
                f"(0 = flush every fence), got {self.flush_interval}")
        self.stall_timeout_sec = float(get_scalar_param(
            block, C.MONITOR_STALL_TIMEOUT_SEC,
            C.MONITOR_STALL_TIMEOUT_SEC_DEFAULT))
        if self.stall_timeout_sec < 0:
            raise MonitorConfigError(
                "monitor.stall_timeout_sec must be >= 0 (0 = off), "
                f"got {self.stall_timeout_sec}")
        self.stall_probe = bool(get_scalar_param(
            block, C.MONITOR_STALL_PROBE, C.MONITOR_STALL_PROBE_DEFAULT))
        self.stall_escalate_after = int(get_scalar_param(
            block, C.MONITOR_STALL_ESCALATE_AFTER,
            C.MONITOR_STALL_ESCALATE_AFTER_DEFAULT))
        if self.stall_escalate_after < 0:
            raise MonitorConfigError(
                "monitor.stall_escalate_after must be >= 0 (0 = off), "
                f"got {self.stall_escalate_after}")
        self.all_ranks = bool(get_scalar_param(
            block, C.MONITOR_ALL_RANKS, C.MONITOR_ALL_RANKS_DEFAULT))
        self.peak_flops_override = float(get_scalar_param(
            block, C.MONITOR_PEAK_FLOPS_OVERRIDE,
            C.MONITOR_PEAK_FLOPS_OVERRIDE_DEFAULT))
        if self.peak_flops_override < 0:
            raise MonitorConfigError(
                "monitor.peak_flops_override must be >= 0 (0 = auto), "
                f"got {self.peak_flops_override}")

        trace = block.get(C.MONITOR_TRACE, {})
        if not isinstance(trace, dict):
            raise MonitorConfigError(
                f'"monitor.trace" must be a dict, got {trace!r}')
        self.trace_enabled = bool(get_scalar_param(
            trace, C.MONITOR_TRACE_ENABLED,
            C.MONITOR_TRACE_ENABLED_DEFAULT))
        self.trace_path = get_scalar_param(
            trace, C.MONITOR_TRACE_PATH, C.MONITOR_TRACE_PATH_DEFAULT)
        self.trace_max_events = int(get_scalar_param(
            trace, C.MONITOR_TRACE_MAX_EVENTS,
            C.MONITOR_TRACE_MAX_EVENTS_DEFAULT))
        if self.trace_max_events <= 0:
            raise MonitorConfigError(
                "monitor.trace.max_events must be > 0, got "
                f"{self.trace_max_events}")

        flight = block.get(C.MONITOR_FLIGHT, {})
        if not isinstance(flight, dict):
            raise MonitorConfigError(
                f'"monitor.flight" must be a dict, got {flight!r}')
        self.flight_enabled = bool(get_scalar_param(
            flight, C.MONITOR_FLIGHT_ENABLED,
            C.MONITOR_FLIGHT_ENABLED_DEFAULT))
        self.flight_capacity = int(get_scalar_param(
            flight, C.MONITOR_FLIGHT_CAPACITY,
            C.MONITOR_FLIGHT_CAPACITY_DEFAULT))
        if self.flight_capacity <= 0:
            raise MonitorConfigError(
                "monitor.flight.capacity must be > 0, got "
                f"{self.flight_capacity}")
        self.flight_path = get_scalar_param(
            flight, C.MONITOR_FLIGHT_PATH, C.MONITOR_FLIGHT_PATH_DEFAULT)

        numerics = block.get(C.MONITOR_NUMERICS, {})
        if not isinstance(numerics, dict):
            raise MonitorConfigError(
                f'"monitor.numerics" must be a dict, got {numerics!r}')
        self.numerics_enabled = bool(get_scalar_param(
            numerics, C.MONITOR_NUMERICS_ENABLED,
            C.MONITOR_NUMERICS_ENABLED_DEFAULT))

        memory = block.get(C.MONITOR_MEMORY, {})
        if not isinstance(memory, dict):
            raise MonitorConfigError(
                f'"monitor.memory" must be a dict, got {memory!r}')
        self.memory_enabled = bool(get_scalar_param(
            memory, C.MONITOR_MEMORY_ENABLED,
            C.MONITOR_MEMORY_ENABLED_DEFAULT))
        self.memory_top_buffers = int(get_scalar_param(
            memory, C.MONITOR_MEMORY_TOP_BUFFERS,
            C.MONITOR_MEMORY_TOP_BUFFERS_DEFAULT))
        if self.memory_top_buffers < 0:
            raise MonitorConfigError(
                "monitor.memory.top_buffers must be >= 0, got "
                f"{self.memory_top_buffers}")
