"""Pluggable telemetry sinks.

Every sink consumes flat JSON-able event dicts produced by the Monitor
at sync fences (kind="metrics") and from host-side subsystems
(kind="ckpt_commit" / "stall" / ...). Sinks must be thread-safe: the
checkpoint writer thread and the stall watchdog emit from off the main
thread.

  * JsonlSink — schema-versioned newline-delimited JSON, one os.write
    per event on an O_APPEND fd (atomic append: concurrent writers
    interleave whole lines, never bytes).
  * TensorBoardSink — the native tfevents writer (monitor/tfevents.py);
    numeric fields of metric events become scalars under `monitor/...`.

Events carry `"v": SCHEMA_VERSION` so log consumers can gate parsing;
bump the version when a field changes meaning (adding fields is not a
version bump).
"""

import json
import os
import threading
import time

from deepspeed_tpu.utils.logging import logger

SCHEMA_VERSION = 1

JSONL_SINK = "jsonl"
TENSORBOARD_SINK = "tensorboard"
VALID_SINKS = (JSONL_SINK, TENSORBOARD_SINK)


class Sink:
    name = "base"

    def emit(self, event):
        raise NotImplementedError

    def flush(self):
        pass

    def close(self):
        pass


class JsonlSink(Sink):
    """Newline-delimited JSON event log with atomic appends."""

    name = JSONL_SINK

    def __init__(self, path):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                           0o644)
        self._lock = threading.Lock()

    def emit(self, event):
        line = json.dumps(event, separators=(",", ":"),
                          default=_json_default) + "\n"
        with self._lock:
            os.write(self._fd, line.encode("utf-8"))

    def flush(self):
        # os.write on the O_APPEND fd is already visible to readers;
        # fsync (crash durability) is deliberately reserved for sync()
        # and close() — an fsync per fence costs more than the fenced
        # training window on some filesystems
        pass

    def sync(self):
        with self._lock:
            try:
                # ds-lint: allow[LOCKBLOCK] durability point (close/escalation only, never per-fence); the lock orders it against concurrent emit writers
                os.fsync(self._fd)
            except OSError:
                pass

    def close(self):
        self.sync()
        with self._lock:
            if self._fd >= 0:
                try:
                    os.close(self._fd)
                finally:
                    self._fd = -1


def _json_default(x):
    # numpy / jax scalars that slip into an event
    try:
        return float(x)
    except (TypeError, ValueError):
        return str(x)


def _flatten_numeric(event, prefix="", out=None):
    out = {} if out is None else out
    for k, v in event.items():
        # event metadata, not scalars — but only at the TOP level: a
        # nested field may legitimately be named "step" (the span) etc.
        if not prefix and k in ("v", "ts", "step", "kind"):
            continue
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            _flatten_numeric(v, prefix=f"{key}/", out=out)
        elif isinstance(v, bool):
            out[key] = float(v)
        elif isinstance(v, (int, float)):
            out[key] = float(v)
    return out


class TensorBoardSink(Sink):
    """Scalars for the TensorBoard dashboard via the native tfevents
    writer — no torch import anywhere on this path."""

    name = TENSORBOARD_SINK

    def __init__(self, log_dir):
        from deepspeed_tpu.monitor.tfevents import TFEventsWriter
        self.log_dir = log_dir
        self._writer = TFEventsWriter(log_dir)

    def emit(self, event):
        kind = event.get("kind", "event")
        scalars = {f"monitor/{kind}/{k}": v
                   for k, v in _flatten_numeric(event).items()}
        if scalars:
            self._writer.add_scalars(scalars, event.get("step", 0),
                                     wall_time=event.get("ts"))

    def flush(self):
        self._writer.flush()

    def close(self):
        self._writer.close()


def build_sinks(sink_specs, output_dir, job_name=""):
    """Instantiate sinks from the config's `monitor.sinks` list. Each
    spec is a name ("jsonl" / "tensorboard") or a dict
    {"type": name, ...opts}. A sink that fails to construct is skipped
    with a warning — telemetry must never kill training."""
    sinks = []
    base = os.path.join(output_dir, job_name) if job_name else output_dir
    for spec in sink_specs:
        if isinstance(spec, str):
            name, opts = spec, {}
        else:
            spec = dict(spec)
            name, opts = spec.pop("type"), spec
        try:
            if name == JSONL_SINK:
                path = opts.get("path") or os.path.join(base,
                                                        "events.jsonl")
                sinks.append(JsonlSink(path))
            elif name == TENSORBOARD_SINK:
                sinks.append(TensorBoardSink(
                    opts.get("log_dir") or os.path.join(base, "tb")))
            else:
                raise ValueError(
                    f"unknown monitor sink {name!r}; valid: "
                    f"{list(VALID_SINKS)}")
        except ValueError:
            raise
        except Exception:
            logger.warning(f"monitor sink {name!r} unavailable",
                           exc_info=True)
    return sinks


def base_event(kind, step):
    return {"v": SCHEMA_VERSION, "ts": round(time.time(), 6),
            "kind": kind, "step": int(step)}
