"""deepspeed_tpu.monitor — unified async-safe telemetry.

One subsystem, three parts (docs/monitoring.md):

  * MetricsRegistry (registry.py): hot-path metrics live as ONE
    device-side accumulator vector folded per step with an async jitted
    add and drained with exactly one `device_get` at the engine's
    `steps_per_sync` fences — zero new per-step host syncs; host
    gauges (checkpoint queue depth / commit latency, prefetch
    occupancy, device memory) sample at the same fences.
  * Pluggable sinks (sinks.py): schema-versioned JSONL event log and a
    dependency-free native tfevents writer (tfevents.py) — plus the
    in-process `engine.monitor.snapshot()` API bench.py reuses, so
    bench extras and training telemetry share one schema.
  * Step tracing + stall watchdog (trace.py / watchdog.py): named
    spans via `jax.profiler.TraceAnnotation` recorded fence-aligned
    (`wall_clock_breakdown=true` rides this path instead of the
    barrier-per-microstep timers), and a background thread that fires
    when no fence advances within `stall_timeout_sec`.

The Monitor object orchestrates the three against one engine; every
hook is a no-op behind a single attribute check when
`monitor.enabled` is false (the default).
"""

import os
import time
import weakref

from deepspeed_tpu.monitor.config import (DeepSpeedMonitorConfig,
                                          MonitorConfigError)
from deepspeed_tpu.monitor.registry import MetricsRegistry
from deepspeed_tpu.monitor.sinks import (SCHEMA_VERSION, base_event,
                                         build_sinks)
from deepspeed_tpu.monitor.trace import (SPAN_BACKWARD, SPAN_CKPT,
                                         SPAN_FORWARD, SPAN_PREFETCH,
                                         SPAN_STEP, StepTrace)
from deepspeed_tpu.monitor.watchdog import StallWatchdog

__all__ = [
    "Monitor", "MetricsRegistry", "StepTrace", "StallWatchdog",
    "DeepSpeedMonitorConfig", "MonitorConfigError", "SCHEMA_VERSION",
    "SPAN_FORWARD", "SPAN_BACKWARD", "SPAN_STEP", "SPAN_CKPT",
    "SPAN_PREFETCH",
]

_MONITOR_OUTPUT_DEFAULT = "ds_monitor"


class Monitor:
    """Per-engine telemetry orchestrator.

    Lifecycle: the engine constructs one Monitor in __init__ and calls
    `on_step` after each fused step (device-side fold, no sync) and
    `on_fence` inside `_sync_fence` (the one drain + sink emit point).
    Subsystems running off the main thread (checkpoint writer, stall
    watchdog, prefetch worker) use `event`/`heartbeat`, which are
    thread-safe.
    """

    def __init__(self, engine, config: DeepSpeedMonitorConfig):
        self.config = config
        self.enabled = bool(config.enabled)
        # weakref: the watchdog thread must not pin dead engines (and
        # their device state) alive through the monitor
        self._engine_ref = weakref.ref(engine)
        self.registry = MetricsRegistry()
        self.trace = StepTrace()
        self.sinks = []
        self.watchdog = None
        self._armed = False
        self._last_fence_t = None
        self._last_flush_t = 0.0
        self._prefetch_ref = None
        self._cum = {"steps": 0, "overflow_count": 0, "tokens": 0}
        self._last = {}          # most recent drained window metrics
        # gauges register even when disabled so snapshot() keeps its
        # stable key set on a monitor-off engine
        self._register_default_gauges()
        if not self.enabled:
            return

        import jax
        rank0 = jax.process_index() == 0
        if rank0 or config.all_ranks:
            out_dir = config.output_path or _MONITOR_OUTPUT_DEFAULT
            job = config.job_name
            if config.all_ranks and not rank0:
                job = os.path.join(job or "",
                                   f"rank{jax.process_index()}")
            self.sinks = build_sinks(config.sinks, out_dir, job)
        if config.stall_timeout_sec > 0:
            self.watchdog = StallWatchdog(
                config.stall_timeout_sec,
                probe=config.stall_probe,
                emit=self._emit_kind)

    # ------------------------------------------------------------------
    # gauges
    # ------------------------------------------------------------------
    def _register_default_gauges(self):
        ref = self._engine_ref

        def ckpt_queue_depth():
            e = ref()
            w = getattr(e, "_ckpt_writer", None) if e else None
            return 0.0 if w is None else float(w.queue_depth())

        def prefetch_occupancy():
            loader = self._prefetch_ref() if self._prefetch_ref else None
            if loader is None:
                return None
            return {"occupancy": loader.occupancy(),
                    "depth": loader.depth}

        from deepspeed_tpu.utils.timer import device_memory_stats
        self.registry.add_gauge("checkpoint/queue_depth",
                                ckpt_queue_depth)
        self.registry.add_gauge("prefetch", prefetch_occupancy)
        self.registry.add_gauge("memory", device_memory_stats)

    def attach_prefetch(self, loader):
        """Remember the live PrefetchLoader for the occupancy gauge."""
        self._prefetch_ref = weakref.ref(loader)

    def heartbeat(self, source):
        if self.watchdog is not None:
            self.watchdog.heartbeat(source)

    # ------------------------------------------------------------------
    # hot path
    # ------------------------------------------------------------------
    def on_step(self, loss=None, grad_norm=None, loss_scale=None,
                overflow=None, tokens=0, wire_stats=None):
        """Fold one step's metrics. Device scalars stay on device (one
        async jitted add); host numbers go to counters. NO host<->
        device sync on this path — the fence-alignment guard test pins
        it."""
        if not self.enabled:
            return
        self.registry.fold_step(loss, grad_norm, loss_scale, overflow,
                                tokens)
        if wire_stats:
            self.registry.inc("wire/d2h_bytes",
                              wire_stats.get("d2h_bytes", 0))
            self.registry.inc("wire/h2d_bytes",
                              wire_stats.get("h2d_bytes", 0))
        if not self._armed and self.watchdog is not None:
            self._armed = True
            self.watchdog.arm()

    # ------------------------------------------------------------------
    # fence drain
    # ------------------------------------------------------------------
    def _wire_dict(self, counters):
        e = self._engine_ref()
        stats = getattr(e, "wire_stats", None) if e else None
        stats = stats or {}
        return {
            "d2h_bytes": int(counters.get("wire/d2h_bytes", 0)),
            "h2d_bytes": int(counters.get("wire/h2d_bytes", 0)),
            "grad_bits": stats.get("grad_bits"),
            "param_bits": stats.get("param_bits"),
        }

    def _checkpoint_dict(self, counters, gauges):
        return {
            "queue_depth": int(gauges.get("checkpoint/queue_depth", 0)),
            "commits": int(counters.get("ckpt/commits", 0)),
            "last_commit_ms": counters.get("ckpt/last_commit_ms"),
        }

    def _throughput_derived(self):
        """tokens/s/chip + MFU once the throughput timer has a warmed
        measurement window (None before that, and MFU None off-TPU
        where no nominal peak applies).  Same convention as bench.py's
        headline: conservative 6·N·tokens/s against the chip's nominal
        bf16 peak — MFU becomes observable IN-LOOP instead of
        bench-only."""
        e = self._engine_ref()
        if e is None:
            return {"tokens_per_sec_per_chip": None, "mfu": None}
        sps = e.tput_timer.avg_samples_per_sec()
        t_per_sample = getattr(e, "_tokens_per_sample", None)
        if not sps or not t_per_sample:
            return {"tokens_per_sec_per_chip": None, "mfu": None}
        import jax
        tps_chip = sps * t_per_sample / max(len(jax.devices()), 1)
        mfu = None
        n = getattr(e, "_n_model_params", 0)
        if n and jax.devices()[0].platform == "tpu":
            from deepspeed_tpu.profiling.flops_profiler.profiler import \
                device_peak_specs
            peak, _ = device_peak_specs()
            if peak:
                mfu = round(6.0 * n * tps_chip / peak, 4)
        return {"tokens_per_sec_per_chip": round(tps_chip, 1),
                "mfu": mfu}

    def on_fence(self):
        """The ONE telemetry rendezvous: drain the device accumulator
        (a single device_get), sample host gauges, emit a metrics
        event, and tell the watchdog the run is alive. Returns the
        event (or None) so the engine can reuse it for breakdown
        logging."""
        if not self.enabled:
            return None
        if self.watchdog is not None:
            self.watchdog.notify_fence()
        e = self._engine_ref()
        if e is None:
            return None
        window = self.registry.drain_device()
        now = time.perf_counter()
        if window is None:
            self._maybe_flush()
            return None
        self._last = window
        self._cum["steps"] += window["steps"]
        self._cum["overflow_count"] += window["overflow_count"]
        self._cum["tokens"] += window["tokens"]

        counters = self.registry.counters()
        gauges = self.registry.sample_gauges()
        event = base_event("metrics", e._host_steps)
        event.update(
            micro_steps=e.micro_steps,
            # None when no step in the window reported one (e.g.
            # release_loss=True loops) — never a fabricated 0.0
            loss=None if window["loss"] is None
            else round(window["loss"], 6),
            grad_norm=None if window["grad_norm"] is None
            else round(window["grad_norm"], 6),
            loss_scale=window["loss_scale"],
            lr=e._current_lr(),
            window_steps=window["steps"],
            overflow_count=self._cum["overflow_count"],
            tokens=self._cum["tokens"],
            samples_per_sec=round(e.tput_timer.avg_samples_per_sec(), 3),
        )
        event.update(self._throughput_derived())
        if self._last_fence_t is not None and now > self._last_fence_t:
            event["tokens_per_sec"] = round(
                window["tokens"] / (now - self._last_fence_t), 1)
        self._last_fence_t = now
        event["memory"] = {
            k.split("/", 1)[1]: v for k, v in gauges.items()
            if k.startswith("memory/")}
        event["wire"] = self._wire_dict(counters)
        event["checkpoint"] = self._checkpoint_dict(counters, gauges)
        event["prefetch"] = {
            "occupancy": gauges.get("prefetch/occupancy"),
            "depth": gauges.get("prefetch/depth"),
        }
        spans = self.trace.drain()
        if spans:
            event["spans"] = spans
        self._emit(event)
        self._maybe_flush()
        return event

    # ------------------------------------------------------------------
    # events / sinks
    # ------------------------------------------------------------------
    def _emit(self, event):
        for sink in self.sinks:
            try:
                sink.emit(event)
            except Exception:
                pass

    def _emit_kind(self, kind, fields):
        """Thread-safe host-event hook (checkpoint writer, watchdog)."""
        if not self.enabled:
            return
        e = self._engine_ref()
        event = base_event(kind, e._host_steps if e else 0)
        event.update(fields)
        self._emit(event)

    def event(self, kind, **fields):
        self._emit_kind(kind, fields)

    def _maybe_flush(self):
        now = time.monotonic()
        if now - self._last_flush_t >= self.config.flush_interval:
            self._last_flush_t = now
            for sink in self.sinks:
                try:
                    sink.flush()
                except Exception:
                    pass

    # ------------------------------------------------------------------
    # snapshot API (bench.py shares this schema)
    # ------------------------------------------------------------------
    SNAPSHOT_KEYS = (
        "schema", "enabled", "step", "micro_steps", "loss", "grad_norm",
        "loss_scale", "lr", "overflow_count", "tokens",
        "samples_per_sec", "tokens_per_sec_per_chip", "mfu",
        "memory", "wire", "checkpoint", "prefetch",
    )

    def snapshot(self):
        """In-process telemetry snapshot with a STABLE key set across
        engine modes (bf16 / fp16 / ZeRO-2 / offload) — unknown values
        are None, never missing keys. This is a user-initiated sync
        point (it drains the device accumulator)."""
        e = self._engine_ref()
        window = self.registry.drain_device()
        if window is not None:
            self._last = window
            self._cum["steps"] += window["steps"]
            self._cum["overflow_count"] += window["overflow_count"]
            self._cum["tokens"] += window["tokens"]
            # snapshot consumed the token window: the next fence's
            # tokens_per_sec must measure from here, not from the
            # pre-snapshot fence
            self._last_fence_t = time.perf_counter()
        last = self._last
        counters = self.registry.counters()
        gauges = self.registry.sample_gauges()
        snap = {
            "schema": SCHEMA_VERSION,
            "enabled": self.enabled,
            "step": e._host_steps if e else None,
            "micro_steps": e.micro_steps if e else None,
            "loss": last.get("loss"),
            "grad_norm": last.get("grad_norm"),
            "loss_scale": last.get("loss_scale"),
            "lr": e._current_lr() if e else None,
            "overflow_count": self._cum["overflow_count"],
            "tokens": self._cum["tokens"],
            "samples_per_sec":
                round(e.tput_timer.avg_samples_per_sec(), 3) if e
                else None,
            **self._throughput_derived(),
            "memory": {
                k.split("/", 1)[1]: v for k, v in gauges.items()
                if k.startswith("memory/")},
            "wire": self._wire_dict(counters),
            "checkpoint": self._checkpoint_dict(counters, gauges),
            "prefetch": {
                "occupancy": gauges.get("prefetch/occupancy"),
                "depth": gauges.get("prefetch/depth"),
            },
        }
        return snap

    # ------------------------------------------------------------------
    def close(self):
        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None
        for sink in self.sinks:
            try:
                sink.flush()
                sink.close()
            except Exception:
                pass
        self.sinks = []
