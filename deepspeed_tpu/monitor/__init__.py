"""deepspeed_tpu.monitor — unified async-safe telemetry.

One subsystem, three parts (docs/monitoring.md):

  * MetricsRegistry (registry.py): hot-path metrics live as ONE
    device-side accumulator vector folded per step with an async jitted
    add and drained with exactly one `device_get` at the engine's
    `steps_per_sync` fences — zero new per-step host syncs; host
    gauges (checkpoint queue depth / commit latency, prefetch
    occupancy, device memory) sample at the same fences.
  * Pluggable sinks (sinks.py): schema-versioned JSONL event log and a
    dependency-free native tfevents writer (tfevents.py) — plus the
    in-process `engine.monitor.snapshot()` API bench.py reuses, so
    bench extras and training telemetry share one schema.
  * Step tracing + stall watchdog (trace.py / watchdog.py): named
    spans via `jax.profiler.TraceAnnotation` recorded fence-aligned
    (`wall_clock_breakdown=true` rides this path instead of the
    barrier-per-microstep timers), and a background thread that fires
    when no fence advances within `stall_timeout_sec`.

Forensic layer (ISSUE 7):

  * Perfetto trace export (trace_export.py, `monitor.trace`): span +
    subsystem tracks and the per-microbatch pipeline timeline from
    the 1F1B clock tables, merged across ranks by bin/ds_trace.
  * Flight recorder (flight.py, `monitor.flight`, default on): a
    bounded ring of the last events + heartbeat ages, dumped
    atomically on watchdog fire / uncaught train_batch exception /
    SIGTERM / abnormal exit.
  * Numerics health (numerics.py, `monitor.numerics`): device-side
    per-group grad + per-layer activation stats folded inside the
    jitted step, drained in the same one-device_get-per-fence path,
    with sticky first-NaN layer attribution.

Memory layer (ISSUE 8):

  * Memory ledger (memory.py, `monitor.memory`, default on): every
    long-lived allocation site registers its logical buffers by
    category from shape metadata; fences reconcile ledger vs
    device_memory_stats + host RSS into a `memory` event with
    per-category attribution, a peak watermark (attribution AT peak),
    Perfetto counter tracks, and OOM-classified flight dumps.

The Monitor object orchestrates these against one engine; every
hook is a no-op behind a single attribute check when
`monitor.enabled` is false (the default).
"""

import os
import time
import weakref

from deepspeed_tpu.monitor import memory as memory_mod
from deepspeed_tpu.monitor.config import (DeepSpeedMonitorConfig,
                                          MonitorConfigError)
from deepspeed_tpu.monitor.flight import FlightRecorder
from deepspeed_tpu.monitor.memory import MemoryLedger
from deepspeed_tpu.monitor.registry import MetricsRegistry
from deepspeed_tpu.monitor.sinks import (SCHEMA_VERSION, base_event,
                                         build_sinks)
from deepspeed_tpu.monitor.trace import (SPAN_BACKWARD, SPAN_CKPT,
                                         SPAN_FORWARD, SPAN_PREFETCH,
                                         SPAN_STEP, StepTrace)
from deepspeed_tpu.monitor.trace_export import (CAT_SUBSYSTEM,
                                                TraceExporter)
from deepspeed_tpu.monitor.watchdog import StallWatchdog
from deepspeed_tpu.utils.logging import logger

__all__ = [
    "Monitor", "MetricsRegistry", "StepTrace", "StallWatchdog",
    "FlightRecorder", "TraceExporter", "MemoryLedger",
    "DeepSpeedMonitorConfig", "MonitorConfigError", "SCHEMA_VERSION",
    "SPAN_FORWARD", "SPAN_BACKWARD", "SPAN_STEP", "SPAN_CKPT",
    "SPAN_PREFETCH",
]

_MONITOR_OUTPUT_DEFAULT = "ds_monitor"


class Monitor:
    """Per-engine telemetry orchestrator.

    Lifecycle: the engine constructs one Monitor in __init__ and calls
    `on_step` after each fused step (device-side fold, no sync) and
    `on_fence` inside `_sync_fence` (the one drain + sink emit point).
    Subsystems running off the main thread (checkpoint writer, stall
    watchdog, prefetch worker) use `event`/`heartbeat`, which are
    thread-safe.
    """

    def __init__(self, engine, config: DeepSpeedMonitorConfig):
        self.config = config
        self.enabled = bool(config.enabled)
        # weakref: the watchdog thread must not pin dead engines (and
        # their device state) alive through the monitor
        self._engine_ref = weakref.ref(engine)
        self.registry = MetricsRegistry()
        self.trace = StepTrace()
        self.sinks = []
        self._sink_emit_warned = set()
        self.watchdog = None
        self.trace_export = None
        self.flight = None
        self._armed = False
        self._last_fence_t = None
        self._last_flush_t = 0.0
        self._prefetch_ref = None
        self._cum = {"steps": 0, "overflow_count": 0, "tokens": 0}
        self._last = {}          # most recent drained window metrics
        self._last_numerics = None
        self._last_router = None   # last fence's router-event fields
        self._serving_ref = None     # live ServingTracker (serving)
        self._first_nonfinite = None   # sticky first-NaN attribution
        # host-side heartbeat mirror (ages for the flight recorder even
        # when no watchdog is configured)
        self._hb = {}
        self._hb_terminal = set()
        self._numerics_names = {"grad": None, "act": None}
        # the memory ledger exists even when the monitor is disabled:
        # allocation sites register unconditionally (init-time shape
        # math, no per-step cost) so enabling the monitor later — or a
        # user-initiated snapshot — still has full attribution
        self.ledger = MemoryLedger()
        self._last_memory = None
        # categories last emitted nonzero per counter series: a
        # released buffer must emit one explicit 0 — Chrome counter
        # semantics keep the last seen value per key, so omitting it
        # would freeze the stacked area at its old height forever
        self._mem_counter_keys = {"hbm": set(), "host": set()}
        # gauges register even when disabled so snapshot() keeps its
        # stable key set on a monitor-off engine
        self._register_default_gauges()
        if not self.enabled:
            return

        import jax
        rank = jax.process_index()
        rank0 = rank == 0
        out_dir = config.output_path or _MONITOR_OUTPUT_DEFAULT
        if config.job_name:
            out_dir = os.path.join(out_dir, config.job_name)
        self._out_dir = out_dir
        if rank0 or config.all_ranks:
            job = config.job_name
            if config.all_ranks and not rank0:
                job = os.path.join(job or "", f"rank{rank}")
            self.sinks = build_sinks(
                config.sinks, config.output_path or
                _MONITOR_OUTPUT_DEFAULT, job)
        if config.trace_enabled and (rank0 or config.all_ranks):
            self.trace_export = TraceExporter(
                rank=rank, max_events=config.trace_max_events,
                meta={"job_name": config.job_name})
            self.trace.set_export_sink(
                lambda name, t0, dur: self.trace_export.complete(
                    f"host/{name}", name, t0, dur))
        if config.flight_enabled:
            self.flight = FlightRecorder(
                out_dir=config.flight_path or out_dir,
                capacity=config.flight_capacity,
                rank=rank,
                step_fn=self._flight_step,
                heartbeats_fn=self._heartbeat_state)
        if config.stall_timeout_sec > 0:
            self.watchdog = StallWatchdog(
                config.stall_timeout_sec,
                probe=config.stall_probe,
                escalate_after=config.stall_escalate_after,
                emit=self._emit_kind)

    def _flight_step(self):
        e = self._engine_ref()
        return e._host_steps if e is not None else None

    def _heartbeat_state(self):
        """(age per ACTIVE subsystem, terminal list) from the monitor's
        own heartbeat mirror — available to the flight recorder with or
        without a watchdog."""
        now = time.monotonic()
        return ({src: round(now - t, 3) for src, t in self._hb.items()
                 if src not in self._hb_terminal},
                sorted(self._hb_terminal))

    # ------------------------------------------------------------------
    # gauges
    # ------------------------------------------------------------------
    def _register_default_gauges(self):
        ref = self._engine_ref

        def ckpt_queue_depth():
            e = ref()
            w = getattr(e, "_ckpt_writer", None) if e else None
            return 0.0 if w is None else float(w.queue_depth())

        def prefetch_occupancy():
            loader = self._prefetch_ref() if self._prefetch_ref else None
            if loader is None:
                return None
            return {"occupancy": loader.occupancy(),
                    "depth": loader.depth}

        from deepspeed_tpu.utils.timer import device_memory_stats
        self.registry.add_gauge("checkpoint/queue_depth",
                                ckpt_queue_depth)
        self.registry.add_gauge("prefetch", prefetch_occupancy)
        self.registry.add_gauge("memory", device_memory_stats)

    def attach_prefetch(self, loader):
        """Remember the live PrefetchLoader for the occupancy gauge and
        the memory ledger's dynamic prefetch-staging entry (occupancy x
        staged-batch bytes, sampled at reconcile time; a fresh loader
        supersedes the previous entry)."""
        self._prefetch_ref = weakref.ref(loader)
        ref = self._prefetch_ref
        self.ledger.register_dynamic(
            memory_mod.CAT_PREFETCH, "prefetch.staged",
            lambda: (lambda l: l.buffer_bytes() if l else 0)(ref()))

    def attach_serving(self, tracker):
        """Remember the live ServingTracker (monitor/serving.py) so
        crash forensics can attach the in-flight request table and the
        serving-aware OOM hint ranking. The tracker updates the flight
        context itself at every phase change."""
        self._serving_ref = weakref.ref(tracker)

    def heartbeat(self, source):
        self._hb[source] = time.monotonic()
        self._hb_terminal.discard(source)
        if self.watchdog is not None:
            self.watchdog.heartbeat(source)

    def heartbeat_done(self, source):
        """A subsystem finished cleanly (e.g. the prefetch worker after
        its source exhausted): its heartbeat goes terminal — excluded
        from stall verdicts, listed as finished in diagnostics."""
        self._hb_terminal.add(source)
        if self.watchdog is not None:
            self.watchdog.mark_terminal(source)

    def subsystem_span(self, track, name, t_start, dur, args=None):
        """Stamp one host-subsystem slice (prefetch staging, ckpt
        commit, offload host step) onto the Perfetto timeline.
        Thread-safe, no-op without trace export."""
        if self.trace_export is not None:
            self.trace_export.complete(track, name, t_start, dur,
                                       cat=CAT_SUBSYSTEM, args=args)

    def set_numerics_labels(self, grad=None, act=None):
        """Host-side names for the numerics stat rows: `grad` labels
        the [G,3] gradient-group rows, `act` the [L,3] activation
        boundary rows (the engine knows both at build time)."""
        if grad is not None:
            self._numerics_names["grad"] = list(grad)
        if act is not None:
            self._numerics_names["act"] = list(act)

    @property
    def numerics_enabled(self):
        return self.enabled and self.config.numerics_enabled

    @property
    def memory_enabled(self):
        return self.enabled and self.config.memory_enabled

    def set_memory_plan(self, plan):
        """Attach a per-component ZeRO memory plan ({component: bytes
        per device}; `ZeroShardingPolicy.memory_plan`): every later
        `memory` event and trace export carries plan-vs-measured
        deltas (`bin/ds_trace summary` prints them)."""
        self.ledger.set_plan(plan)
        if self.trace_export is not None:
            self.trace_export.set_meta(
                memory_plan={k: int(v) for k, v in (plan or {}).items()})

    def _reconcile_memory(self, step):
        """Fence-aligned ledger reconciliation: pure host arithmetic
        over shape metadata + one allocator-stats read — zero
        host<->device syncs (guard-tested). Updates the flight
        recorder's sticky peak context so an OOM dump names what was
        alive at the watermark even after the ring rolled."""
        from deepspeed_tpu.utils.timer import device_memory_stats
        # device_memory_stats already embeds host_rss_bytes; reconcile
        # falls back to it — one /proc read per fence, not two
        payload = self.ledger.reconcile(
            device_memory_stats(),
            step=step, top_n=self.config.memory_top_buffers)
        self._last_memory = payload
        if self.flight is not None and payload.get("peak"):
            self.flight.set_context(memory_peak=payload["peak"])
        return payload

    def _emit_memory_event(self, step):
        payload = self._reconcile_memory(step)
        event = base_event("memory", step)
        event.update(payload)
        self._emit(event)
        if self.trace_export is not None:
            # per-category counter tracks: Perfetto stacks the args of
            # one counter series, so the HBM timeline reads as a
            # stacked-by-category area with the residual on top
            for space in ("hbm", "host"):
                cats = payload[space]["categories"]
                live = {c: cats[c] for c in memory_mod.CATEGORIES
                        if cats.get(c)}
                # one explicit 0 for categories that just vanished
                # (e.g. a released ckpt snapshot), then they drop out
                vals = dict(live)
                for gone in self._mem_counter_keys[space] - set(live):
                    vals[gone] = 0
                self._mem_counter_keys[space] = set(live)
                res = payload[space]["residual_bytes"]
                if res is not None:
                    vals["residual"] = max(res, 0)
                if vals:
                    self.trace_export.counter(
                        "memory", f"{space}_bytes", vals)
        return event

    # ------------------------------------------------------------------
    # hot path
    # ------------------------------------------------------------------
    def on_step(self, loss=None, grad_norm=None, loss_scale=None,
                overflow=None, tokens=0, wire_stats=None, health=None,
                router=None):
        """Fold one step's metrics. Device scalars stay on device (one
        async jitted add); host numbers go to counters; `health`
        (numerics stat arrays, monitor/numerics.py) and `router` (the
        MoE [E+2] router stats vector, deepspeed_tpu/moe/router.py)
        are retained the same way. NO host<->device sync on this
        path — the fence-alignment guard test pins it."""
        if not self.enabled:
            return
        self.registry.fold_step(loss, grad_norm, loss_scale, overflow,
                                tokens, health=health, router=router)
        if wire_stats:
            self.registry.inc("wire/d2h_bytes",
                              wire_stats.get("d2h_bytes", 0))
            self.registry.inc("wire/h2d_bytes",
                              wire_stats.get("h2d_bytes", 0))
        if not self._armed:
            self._armed = True
            if self.watchdog is not None:
                self.watchdog.arm()
            if self.flight is not None:
                # armed = the engine actually trained; an abnormal exit
                # from here on leaves a flight dump
                self.flight.arm()

    # ------------------------------------------------------------------
    # fence drain
    # ------------------------------------------------------------------
    def _wire_dict(self, counters):
        e = self._engine_ref()
        stats = getattr(e, "wire_stats", None) if e else None
        stats = stats or {}
        return {
            "d2h_bytes": int(counters.get("wire/d2h_bytes", 0)),
            "h2d_bytes": int(counters.get("wire/h2d_bytes", 0)),
            "grad_bits": stats.get("grad_bits"),
            "param_bits": stats.get("param_bits"),
        }

    def _checkpoint_dict(self, counters, gauges):
        return {
            "queue_depth": int(gauges.get("checkpoint/queue_depth", 0)),
            "commits": int(counters.get("ckpt/commits", 0)),
            "last_commit_ms": counters.get("ckpt/last_commit_ms"),
        }

    def _throughput_derived(self):
        """tokens/s/chip + MFU once the throughput timer has a warmed
        measurement window (None before that, and MFU None off-TPU
        where no nominal peak applies).  Same convention as bench.py's
        headline: conservative 6·N·tokens/s against the chip's nominal
        bf16 peak — MFU becomes observable IN-LOOP instead of
        bench-only."""
        e = self._engine_ref()
        if e is None:
            return {"tokens_per_sec_per_chip": None, "mfu": None}
        sps = e.tput_timer.avg_samples_per_sec()
        t_per_sample = getattr(e, "_tokens_per_sample", None)
        if not sps or not t_per_sample:
            return {"tokens_per_sec_per_chip": None, "mfu": None}
        import jax
        tps_chip = sps * t_per_sample / max(len(jax.devices()), 1)
        mfu = None
        n = getattr(e, "_n_model_params", 0)
        override = self.config.peak_flops_override
        if n and override:
            # monitor.peak_flops_override: report MFU against the
            # caller's denominator on ANY backend — CPU/virtual-mesh
            # rehearsal runs get a real number instead of None
            mfu = round(6.0 * n * tps_chip / override, 4)
        elif n and jax.devices()[0].platform == "tpu":
            from deepspeed_tpu.profiling.flops_profiler.profiler import \
                device_peak_specs
            peak, _ = device_peak_specs()
            if peak:
                mfu = round(6.0 * n * tps_chip / peak, 4)
        return {"tokens_per_sec_per_chip": round(tps_chip, 1),
                "mfu": mfu}

    def on_fence(self):
        """The ONE telemetry rendezvous: drain the device accumulator
        (a single device_get), sample host gauges, emit a metrics
        event, and tell the watchdog the run is alive. Returns the
        event (or None) so the engine can reuse it for breakdown
        logging."""
        if not self.enabled:
            return None
        if self.watchdog is not None:
            self.watchdog.notify_fence()
        e = self._engine_ref()
        if e is None:
            return None
        window = self.registry.drain_device()
        now = time.perf_counter()
        if window is None:
            self._maybe_flush()
            return None
        numerics = self._summarize_numerics(window)
        self._last = window
        self._cum["steps"] += window["steps"]
        self._cum["overflow_count"] += window["overflow_count"]
        self._cum["tokens"] += window["tokens"]

        counters = self.registry.counters()
        gauges = self.registry.sample_gauges()
        event = base_event("metrics", e._host_steps)
        event.update(
            micro_steps=e.micro_steps,
            # None when no step in the window reported one (e.g.
            # release_loss=True loops) — never a fabricated 0.0
            loss=None if window["loss"] is None
            else round(window["loss"], 6),
            grad_norm=None if window["grad_norm"] is None
            else round(window["grad_norm"], 6),
            loss_scale=window["loss_scale"],
            lr=e._current_lr(),
            window_steps=window["steps"],
            overflow_count=self._cum["overflow_count"],
            tokens=self._cum["tokens"],
            samples_per_sec=round(e.tput_timer.avg_samples_per_sec(), 3),
        )
        event.update(self._throughput_derived())
        if self._last_fence_t is not None and now > self._last_fence_t:
            event["tokens_per_sec"] = round(
                window["tokens"] / (now - self._last_fence_t), 1)
        self._last_fence_t = now
        event["memory"] = {
            k.split("/", 1)[1]: v for k, v in gauges.items()
            if k.startswith("memory/")}
        event["wire"] = self._wire_dict(counters)
        event["checkpoint"] = self._checkpoint_dict(counters, gauges)
        event["prefetch"] = {
            "occupancy": gauges.get("prefetch/occupancy"),
            "depth": gauges.get("prefetch/depth"),
        }
        spans = self.trace.drain()
        if spans:
            event["spans"] = spans
        if self.trace_export is not None:
            # fence marks + counter tracks: loss/throughput ride the
            # Perfetto timeline next to the span and pipeline slices
            vals = {k: event[k] for k in
                    ("loss", "grad_norm", "tokens_per_sec",
                     "samples_per_sec")
                    if isinstance(event.get(k), (int, float))}
            if vals:
                self.trace_export.counter("fences", "metrics", vals)
            self.trace_export.instant(
                "fences", f"fence step {event['step']}",
                args={"window_steps": event.get("window_steps")})
        self._emit(event)
        if numerics is not None:
            num_event = base_event("numerics", e._host_steps)
            num_event.update(numerics)
            self._emit(num_event)
        router = self._summarize_router(window)
        if router is not None:
            r_event = base_event("router", e._host_steps)
            r_event.update(router)
            self._emit(r_event)
        if self.memory_enabled:
            self._emit_memory_event(e._host_steps)
        self._maybe_flush()
        return event

    def _summarize_numerics(self, window):
        """Summarize (and strip) a drained window's raw health data —
        fetched numpy from the fence's single device_get — into the
        `numerics` event fields; updates the flight recorder's sticky
        first-NaN context."""
        health = window.pop("health", None)
        if health is None:
            return None
        from deepspeed_tpu.monitor import numerics as num_mod
        entries, acc = health
        summary = num_mod.summarize_window(
            entries, acc,
            grad_names=self._numerics_names["grad"],
            act_names=self._numerics_names["act"])
        if summary is None:
            return None
        self._last_numerics = summary
        if summary.get("first_nonfinite") and \
                self._first_nonfinite is None:
            # sticky FIRST occurrence: once a NaN poisons the params,
            # every later window blames layer 0 — the forensic answer
            # is the window where it first appeared
            e = self._engine_ref()
            self._first_nonfinite = dict(
                summary["first_nonfinite"],
                step=e._host_steps if e else None)
        if self.flight is not None:
            ctx = {"numerics": summary}
            if self._first_nonfinite is not None:
                ctx["first_nonfinite"] = self._first_nonfinite
            self.flight.set_context(**ctx)
        return summary

    def _summarize_router(self, window):
        """The fence's `router` event fields from the drained window's
        MEAN MoE router-stats vector ([E+2] layout — per-expert load
        fractions, drop fraction, aux loss; deepspeed_tpu/moe/router).
        Returns None (and emits nothing) when the window carried no
        router stats — dense engines never see this event."""
        router = window.pop("router", None)
        if router is None:
            return None
        vec, steps = router
        loads = [round(float(v), 6) for v in vec[:-2]]
        summary = {
            "num_experts": len(loads),
            "expert_load": loads,
            "load_max": round(max(loads), 6) if loads else None,
            "drop_fraction": round(float(vec[-2]), 6),
            "aux_loss": round(float(vec[-1]), 6),
            "window_steps": int(steps),
        }
        self._last_router = summary
        return summary

    # ------------------------------------------------------------------
    # events / sinks
    # ------------------------------------------------------------------
    def _emit(self, event):
        if self.flight is not None:
            # the ring retains what the sinks saw — the dump IS the
            # tail of the event stream
            self.flight.record(event)
        for sink in self.sinks:
            try:
                sink.emit(event)
            except Exception:
                # telemetry must never kill training, but a sink that
                # silently drops every event blinds the run — warn
                # once per sink, with the traceback (duck-typed user
                # sinks may lack .name)
                name = getattr(sink, "name", type(sink).__name__)
                if name not in self._sink_emit_warned:
                    self._sink_emit_warned.add(name)
                    logger.warning(
                        f"monitor sink {name!r} emit failed "
                        "(suppressing further warnings for this sink)",
                        exc_info=True)

    def _emit_kind(self, kind, fields):
        """Thread-safe host-event hook (checkpoint writer, watchdog)."""
        if not self.enabled:
            return
        e = self._engine_ref()
        event = base_event(kind, e._host_steps if e else 0)
        event.update(fields)
        self._emit(event)
        if kind == "ckpt_commit" and self.trace_export is not None:
            # the commit just finished ON the writer thread: a slice of
            # wall_ms ending now on the ckpt-writer track
            wall = float(fields.get("wall_ms") or 0.0) / 1e3
            self.trace_export.complete(
                "ckpt_writer", f"commit {fields.get('tag', '')}",
                time.perf_counter() - wall, wall, cat=CAT_SUBSYSTEM,
                args={"tag": fields.get("tag")})
        if kind in ("stall", "stall_escalated"):
            # the forensic moment: freeze the evidence while the run is
            # still (maybe) wedged — flight dump + trace export. An
            # escalation is terminal for the episode: its dump carries
            # the consecutive-fire diagnostic a recovery post-mortem
            # starts from.
            if self.flight is not None:
                try:
                    self.flight.dump(kind, extra=fields)
                except Exception:
                    logger.warning(f"flight dump on {kind!r} failed",
                                   exc_info=True)
            self._export_trace_safe()

    def event(self, kind, **fields):
        self._emit_kind(kind, fields)

    def on_crash(self, exc):
        """Uncaught exception out of the step loop: record it and dump
        the flight ring + trace before the exception propagates. A
        RESOURCE_EXHAUSTED / out-of-memory failure is classified and
        dumped as reason "oom" with the memory ledger, the top
        buffers, and actionable hints attached — the attribution dies
        with the process otherwise."""
        if not self.enabled:
            return
        extra = {"error": repr(exc)}
        reason = "exception"
        serving = self._serving_ref() if self._serving_ref else None
        if serving is not None:
            try:
                # the in-flight request table: an OOM/crash dump names
                # exactly which requests were being served
                extra["serving"] = serving.snapshot()
            except Exception:  # ds-lint: allow[BROADEXC] crash forensics must not mask the original exception mid-propagation
                serving = None
        if self.memory_enabled and memory_mod.classify_oom(exc):
            reason = "oom"
            try:
                # allocator stats are a host-side read — the failed
                # allocation left the device responsive; still guarded
                # because a post-mortem must never raise
                payload = self._reconcile_memory(
                    self._flight_step() or 0)
            except Exception:  # ds-lint: allow[BROADEXC] an OOM post-mortem must never raise while handling the original failure
                payload = self._last_memory or \
                    self.ledger.reconcile(None, None)
            hints = memory_mod.oom_hints(payload)
            if serving is not None:
                try:
                    from deepspeed_tpu.monitor.serving import \
                        serving_oom_hints
                    # serving-aware ranking FIRST: on a serving engine
                    # the kv_cache / max_slots / prefill_chunk knobs
                    # are the ones the operator can actually turn
                    hints = serving_oom_hints(
                        payload, extra.get("serving")) + hints
                except Exception:  # ds-lint: allow[BROADEXC] an OOM post-mortem must never raise while handling the original failure
                    pass
            extra["oom"] = {
                "hbm": payload.get("hbm"),
                "host": payload.get("host"),
                "peak": payload.get("peak"),
                "top_buffers": payload.get("top_buffers"),
                "hints": hints,
            }
        if self.flight is not None:
            try:
                self.flight.record_exception(exc)
                self.flight.dump(reason, extra=extra)
            except Exception:  # ds-lint: allow[BROADEXC] crash forensics must not mask the original exception mid-propagation
                pass
        self._export_trace_safe()

    # ------------------------------------------------------------------
    # trace export
    # ------------------------------------------------------------------
    def trace_path(self):
        import jax
        rank = jax.process_index()
        if self.config.trace_path:
            # explicit path: rank 0 gets it verbatim; other ranks get a
            # rank-suffixed sibling — every rank writing the SAME file
            # would clobber the shards ds_trace merge needs
            if rank == 0:
                return self.config.trace_path
            stem, ext = os.path.splitext(self.config.trace_path)
            return f"{stem}_rank{rank}{ext or '.json'}"
        return os.path.join(
            getattr(self, "_out_dir", _MONITOR_OUTPUT_DEFAULT),
            f"trace_rank{rank}.json")

    def export_trace(self, path=None):
        """Write the Perfetto trace file (atomic) and return its path;
        None when trace export is off."""
        if self.trace_export is None:
            return None
        return self.trace_export.write(path or self.trace_path())

    def _export_trace_safe(self):
        try:
            self.export_trace()
        except Exception:
            # trace export rides failure paths (stall, crash, close);
            # it must not raise there — but leave the evidence
            logger.warning("trace export failed", exc_info=True)

    def _maybe_flush(self):
        now = time.monotonic()
        if now - self._last_flush_t >= self.config.flush_interval:
            self._last_flush_t = now
            for sink in self.sinks:
                try:
                    sink.flush()
                except Exception:  # ds-lint: allow[BROADEXC] flush is advisory visibility; real sink failures surface at emit (warn-once)
                    pass

    # ------------------------------------------------------------------
    # snapshot API (bench.py shares this schema)
    # ------------------------------------------------------------------
    SNAPSHOT_KEYS = (
        "schema", "enabled", "step", "micro_steps", "loss", "grad_norm",
        "loss_scale", "lr", "overflow_count", "tokens",
        "samples_per_sec", "tokens_per_sec_per_chip", "mfu",
        "memory", "wire", "checkpoint", "prefetch", "numerics",
        "router", "memory_ledger",
    )

    def snapshot(self):
        """In-process telemetry snapshot with a STABLE key set across
        engine modes (bf16 / fp16 / ZeRO-2 / offload) — unknown values
        are None, never missing keys. This is a user-initiated sync
        point (it drains the device accumulator)."""
        e = self._engine_ref()
        window = self.registry.drain_device()
        if window is not None:
            self._summarize_numerics(window)
            self._summarize_router(window)
            self._last = window
            self._cum["steps"] += window["steps"]
            self._cum["overflow_count"] += window["overflow_count"]
            self._cum["tokens"] += window["tokens"]
            # snapshot consumed the token window: the next fence's
            # tokens_per_sec must measure from here, not from the
            # pre-snapshot fence
            self._last_fence_t = time.perf_counter()
        last = self._last
        counters = self.registry.counters()
        gauges = self.registry.sample_gauges()
        snap = {
            "schema": SCHEMA_VERSION,
            "enabled": self.enabled,
            "step": e._host_steps if e else None,
            "micro_steps": e.micro_steps if e else None,
            "loss": last.get("loss"),
            "grad_norm": last.get("grad_norm"),
            "loss_scale": last.get("loss_scale"),
            "lr": e._current_lr() if e else None,
            "overflow_count": self._cum["overflow_count"],
            "tokens": self._cum["tokens"],
            "samples_per_sec":
                round(e.tput_timer.avg_samples_per_sec(), 3) if e
                else None,
            **self._throughput_derived(),
            "memory": {
                k.split("/", 1)[1]: v for k, v in gauges.items()
                if k.startswith("memory/")},
            "wire": self._wire_dict(counters),
            "checkpoint": self._checkpoint_dict(counters, gauges),
            "prefetch": {
                "occupancy": gauges.get("prefetch/occupancy"),
                "depth": gauges.get("prefetch/depth"),
            },
            "numerics": self._last_numerics,
            "router": self._last_router,
            "memory_ledger": self._reconcile_memory(
                e._host_steps if e else 0)
            if self.memory_enabled else None,
        }
        return snap

    # ------------------------------------------------------------------
    def close(self):
        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None
        if self.flight is not None:
            # clean shutdown: no atexit dump for this engine
            self.flight.disarm()
        self._export_trace_safe()
        for sink in self.sinks:
            try:
                sink.flush()
                sink.close()
            except Exception:
                logger.warning(
                    f"monitor sink "
                    f"{getattr(sink, 'name', type(sink).__name__)!r} "
                    "close failed", exc_info=True)
        self.sinks = []
