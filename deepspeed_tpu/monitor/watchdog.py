"""Stall watchdog: a background thread that fires when training stops
making progress.

Progress is defined as the engine's sync fence advancing — the one
point where host and device provably rendezvous (per-step host activity
is NOT progress: under async dispatch the host happily queues steps
against a wedged device until buffer donation blocks it). Subsystems
that can wedge a run (prefetch worker, checkpoint writer, offload step,
pipeline compile) report `heartbeat`s; they don't reset the stall clock
but their ages are included in the diagnostic when the watchdog fires,
pointing at WHICH part of the pipeline went quiet first.

On fire: one warning log with the per-source age table, an optional
`on_stall(diag)` callback, an event into the monitor sinks, and —
with `probe=True` — an `effects_barrier` probe on a separate daemon
thread (if the barrier returns quickly the device is idle and the
stall is host-side; if it never returns the device itself is wedged;
the probe thread is sacrificial so a hung barrier can't wedge the
watchdog too). The watchdog re-arms after each fire, so a run that
stalls, recovers, and stalls again reports both episodes.

Escalation (`escalate_after=N`): a stall that persists keeps firing —
one `on_stall` per further `timeout_sec` of silence — with a
consecutive-fire counter; at the Nth consecutive fire a terminal
`stall_escalated` event is emitted EXACTLY ONCE per episode (sink
event + `on_escalate(diag)` callback; the monitor also dumps the
flight recorder on it), after which the episode goes quiet until a
fence re-arms it. A supervisor (elasticity/runtime.py) uses the
escalated verdict to give up waiting and execute recovery instead.
With escalate_after=0 (the default) behavior is unchanged: one fire
per episode, no terminal event.
"""

import threading
import time

from deepspeed_tpu.utils.logging import logger


class StallWatchdog:
    def __init__(self, timeout_sec, on_stall=None, probe=False,
                 emit=None, poll_interval=None, escalate_after=0,
                 on_escalate=None):
        assert timeout_sec > 0, timeout_sec
        self.timeout_sec = float(timeout_sec)
        self.on_stall = on_stall
        self.probe = probe
        self.escalate_after = int(escalate_after or 0)
        self.on_escalate = on_escalate
        self._emit = emit            # monitor event hook (thread-safe)
        self._poll = poll_interval or min(self.timeout_sec / 4.0, 5.0)
        self._lock = threading.Lock()
        self._last_fence = None      # None = not armed yet
        self._heartbeats = {}
        self._terminal = set()       # finished subsystems (not stalled)
        self._fired_for = None       # fence timestamp already reported
        self._last_fire_t = None     # wall time of the episode's last fire
        self._consecutive = 0        # fires since the last fence
        self._escalated = False      # terminal event sent for this episode
        self.stall_count = 0
        self.escalation_count = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="ds-tpu-watchdog", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    # progress signals
    # ------------------------------------------------------------------
    def notify_fence(self):
        """A sync fence advanced — THE progress signal. Also arms the
        watchdog on first call (an idle engine that never trained must
        not fire)."""
        with self._lock:
            self._last_fence = time.monotonic()
            self._fired_for = None
            self._last_fire_t = None
            self._consecutive = 0
            self._escalated = False

    def arm(self):
        """Start the stall clock without counting progress (called at
        the first train step, so a first fence that never arrives is
        itself detected)."""
        with self._lock:
            if self._last_fence is None:
                self._last_fence = time.monotonic()

    def heartbeat(self, source):
        with self._lock:
            self._heartbeats[source] = time.monotonic()
            # a fresh beat revives a previously-finished subsystem
            # (e.g. a new prefetch loader reusing the name)
            self._terminal.discard(source)

    def mark_terminal(self, source):
        """A subsystem finished CLEANLY (e.g. the prefetch worker after
        its loader exhausted). Its heartbeat age stops counting toward
        a stall verdict — a done worker going quiet is not a wedge —
        but it stays listed as terminal in the diagnostic."""
        with self._lock:
            self._terminal.add(source)

    # ------------------------------------------------------------------
    # the watchdog loop
    # ------------------------------------------------------------------
    def _diagnose(self, now, age):
        with self._lock:
            beats = dict(self._heartbeats)
            terminal = set(self._terminal)
        return {
            "fence_age_sec": round(age, 3),
            "timeout_sec": self.timeout_sec,
            "heartbeat_age_sec": {
                src: round(now - t, 3) for src, t in beats.items()
                if src not in terminal},
            "terminal_subsystems": sorted(terminal),
        }

    def _probe_device(self):
        """Time an effects_barrier on a sacrificial daemon thread."""
        def probe():
            try:
                import jax
                t0 = time.monotonic()
                jax.effects_barrier()
                logger.warning(
                    "stall probe: effects_barrier returned in "
                    f"{time.monotonic() - t0:.3f}s — the device queue is "
                    "drained; the stall is host-side (input pipeline, "
                    "checkpoint barrier, or the loop itself)")
            except Exception:
                logger.warning("stall probe failed", exc_info=True)

        threading.Thread(target=probe, name="ds-tpu-stall-probe",
                         daemon=True).start()

    def _run(self):
        while not self._stop.wait(self._poll):
            with self._lock:
                last = self._last_fence
                fired = self._fired_for
                last_fire = self._last_fire_t
                escalated = self._escalated
            if last is None:
                continue
            if fired == last:
                # already reported this episode: with escalation on,
                # keep re-firing every further timeout_sec of silence
                # (counting consecutive fires) until the terminal
                # verdict; the default keeps one fire per episode
                if self.escalate_after <= 0 or escalated or \
                        last_fire is None or \
                        time.monotonic() - last_fire < self.timeout_sec:
                    continue
            now = time.monotonic()
            age = now - last
            if age < self.timeout_sec:
                continue
            with self._lock:
                self._fired_for = last
                self._last_fire_t = now
                self.stall_count += 1
                self._consecutive += 1
                consecutive = self._consecutive
                escalate = (self.escalate_after > 0 and
                            consecutive >= self.escalate_after and
                            not self._escalated)
                if escalate:
                    self._escalated = True
                    self.escalation_count += 1
            diag = self._diagnose(now, age)
            diag["consecutive_fires"] = consecutive
            term = diag.get("terminal_subsystems") or []
            logger.warning(
                f"STALL: no sync fence for {age:.1f}s "
                f"(stall_timeout_sec={self.timeout_sec}); last subsystem "
                f"heartbeats (sec ago): {diag['heartbeat_age_sec']}"
                + (f"; finished: {term}" if term else ""))
            if self._emit is not None:
                try:
                    self._emit("stall", diag)
                except Exception:
                    # a broken sink must not kill the watchdog thread,
                    # but the evidence of WHY it broke must survive
                    logger.warning("stall event emit failed",
                                   exc_info=True)
            if self.probe:
                self._probe_device()
            if self.on_stall is not None:
                try:
                    self.on_stall(diag)
                except Exception:
                    logger.warning("stall callback raised",
                                   exc_info=True)
            if escalate:
                ediag = dict(diag, escalate_after=self.escalate_after)
                logger.error(
                    f"STALL ESCALATED: {consecutive} consecutive "
                    f"watchdog fires with no progress (escalate_after="
                    f"{self.escalate_after}); this episode is terminal "
                    "— a supervisor should recover, not keep waiting")
                if self._emit is not None:
                    try:
                        self._emit("stall_escalated", ediag)
                    except Exception:
                        logger.warning(
                            "stall_escalated event emit failed",
                            exc_info=True)
                if self.on_escalate is not None:
                    try:
                        self.on_escalate(ediag)
                    except Exception:
                        logger.warning("escalation callback raised",
                                       exc_info=True)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2.0)
