"""Module injection: swap HF-style BERT layers for the fused layer.

Counterpart of `deepspeed/module_inject/replace_module.py:6-193`. In
torch, injection mutates `nn.Module` objects in place; under JAX the
model is (module defn, param tree), so injection is *param-tree
surgery*: convert an HF Flax BERT layer's parameters into the fused
`DeepSpeedTransformerLayer` layout (concatenating q/k/v into one
[H, 3H] qkv kernel, exactly the weight transplant of ref
`replace_module.py:34-56`), and run the fused module in its place.
`revert_transformer_layer` is the inverse (ref `:93`). The generic
`replace_module` walker (ref `:161-193`) applies any policy over a
param tree.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerLayer,
                                           DeepSpeedTransformerConfig)
from deepspeed_tpu.utils.logging import logger


def _is_hf_bert_layer(subtree) -> bool:
    try:
        return "query" in subtree["attention"]["self"] and \
            "dense" in subtree["intermediate"]
    except (KeyError, TypeError):
        return False


def convert_bert_layer_params(hf_layer):
    """HF FlaxBertLayer params -> DeepSpeedTransformerLayer params
    (the q/k/v concat of ref `replace_module.py:34-56`)."""
    attn_self = hf_layer["attention"]["self"]
    attn_out = hf_layer["attention"]["output"]
    qkv_kernel = jnp.concatenate(
        [attn_self["query"]["kernel"], attn_self["key"]["kernel"],
         attn_self["value"]["kernel"]], axis=-1)
    qkv_bias = jnp.concatenate(
        [attn_self["query"]["bias"], attn_self["key"]["bias"],
         attn_self["value"]["bias"]], axis=-1)
    return {"core": {
        "attn_qkvw": {"kernel": qkv_kernel, "bias": qkv_bias},
        "attn_ow": {"kernel": attn_out["dense"]["kernel"],
                    "bias": attn_out["dense"]["bias"]},
        "attn_layer_norm": {"scale": attn_out["LayerNorm"]["scale"],
                            "bias": attn_out["LayerNorm"]["bias"]},
        "inter_w": {"kernel": hf_layer["intermediate"]["dense"]["kernel"],
                    "bias": hf_layer["intermediate"]["dense"]["bias"]},
        "output_w": {"kernel": hf_layer["output"]["dense"]["kernel"],
                     "bias": hf_layer["output"]["dense"]["bias"]},
        "layer_norm": {"scale": hf_layer["output"]["LayerNorm"]["scale"],
                       "bias": hf_layer["output"]["LayerNorm"]["bias"]},
    }}


def revert_bert_layer_params(ds_layer):
    """DeepSpeedTransformerLayer params -> HF FlaxBertLayer params
    (ref `replace_module.py:93`)."""
    core = ds_layer["core"]
    qkv_kernel = core["attn_qkvw"]["kernel"]
    qkv_bias = core["attn_qkvw"]["bias"]
    qk, kk, vk = jnp.split(qkv_kernel, 3, axis=-1)
    qb, kb, vb = jnp.split(qkv_bias, 3, axis=-1)
    return {
        "attention": {
            "self": {
                "query": {"kernel": qk, "bias": qb},
                "key": {"kernel": kk, "bias": kb},
                "value": {"kernel": vk, "bias": vb},
            },
            "output": {
                "dense": {"kernel": core["attn_ow"]["kernel"],
                          "bias": core["attn_ow"]["bias"]},
                "LayerNorm": {"scale": core["attn_layer_norm"]["scale"],
                              "bias": core["attn_layer_norm"]["bias"]},
            },
        },
        "intermediate": {
            "dense": {"kernel": core["inter_w"]["kernel"],
                      "bias": core["inter_w"]["bias"]},
        },
        "output": {
            "dense": {"kernel": core["output_w"]["kernel"],
                      "bias": core["output_w"]["bias"]},
            "LayerNorm": {"scale": core["layer_norm"]["scale"],
                          "bias": core["layer_norm"]["bias"]},
        },
    }


def replace_module(params, policy: Callable[[tuple, Any], Optional[Any]]):
    """Generic recursive walker (ref `replace_module.py:161-193`):
    `policy(path, subtree)` returns a replacement subtree or None to
    recurse. Returns (new_tree, replaced_count)."""
    count = 0

    def walk(path, node):
        nonlocal count
        if isinstance(node, dict):
            replacement = policy(path, node)
            if replacement is not None:
                count += 1
                return replacement
            return {k: walk(path + (k,), v) for k, v in node.items()}
        return node

    return walk((), params), count


def replace_transformer_layer(orig_layer_impl=None, model=None,
                              params=None, config=None,
                              micro_batch_size=-1, bert_config=None,
                              seed=-1, preln=False, fp16=False,
                              training=True):
    """Convert every HF BERT layer in `params` to fused-layer params
    (ref `replace_transformer_layer`, `replace_module.py:6`).

    Returns (transformer_config, new_params, num_replaced). Run the
    converted layers with DeepSpeedTransformerLayer(transformer_config).
    """
    assert params is not None, "pass the HF model's param tree as params="
    hidden = None
    heads = None
    if bert_config is not None:
        hidden = getattr(bert_config, "hidden_size", None)
        heads = getattr(bert_config, "num_attention_heads", None)

    def policy(path, node):
        if _is_hf_bert_layer(node):
            return convert_bert_layer_params(node)
        return None

    converted_kernels = []

    def policy2(path, node):
        out = policy(path, node)
        if out is not None:
            converted_kernels.append(out["core"]["attn_qkvw"]["kernel"])
        return out

    new_params, count = replace_module(params, policy2)
    if count == 0:
        logger.warning("replace_transformer_layer: no BERT layers found")
    if config is None and hidden is None and count > 0:
        # infer geometry from the converted qkv kernel: [hidden, 3*hidden]
        hidden = int(converted_kernels[0].shape[0])
    if config is None and heads is None and hidden is not None:
        # BERT-family models universally use head_dim=64; pass
        # bert_config= to override.
        heads = max(hidden // 64, 1)
        logger.warning(
            f"replace_transformer_layer: num_attention_heads not given; "
            f"assuming head_dim=64 -> heads={heads}")
    ds_config = config or DeepSpeedTransformerConfig(
        hidden_size=hidden if hidden is not None else -1,
        heads=heads if heads is not None else -1,
        pre_layer_norm=preln,
        fp16=fp16,
        training=training)
    return ds_config, new_params, count


def revert_transformer_layer(params):
    """Inverse conversion over a whole tree (ref `replace_module.py:93`)."""
    def policy(path, node):
        if isinstance(node, dict) and "core" in node and \
                isinstance(node.get("core"), dict) and \
                "attn_qkvw" in node["core"]:
            return revert_bert_layer_params(node)
        return None

    new_params, count = replace_module(params, policy)
    return new_params, count
