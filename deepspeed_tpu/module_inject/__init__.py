from deepspeed_tpu.module_inject.replace_module import (
    replace_transformer_layer, revert_transformer_layer, replace_module,
    convert_bert_layer_params, revert_bert_layer_params)

__all__ = ["replace_transformer_layer", "revert_transformer_layer",
           "replace_module", "convert_bert_layer_params",
           "revert_bert_layer_params"]
