"""Config helpers: typed dict access + duplicate-key JSON rejection.

Parity with `deepspeed/runtime/config_utils.py` (get_scalar_param,
dict_raise_error_on_duplicate_keys).
"""

import json


def get_scalar_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Reject duplicate keys while parsing JSON."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, v in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError("Duplicate keys in DeepSpeed config: {}".format(keys))
    return d


def load_config_dict(config):
    """Accept a path to a JSON file or an already-parsed dict."""
    if isinstance(config, dict):
        return config
    with open(config, "r") as f:
        return json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)


class ScientificNotationEncoder(json.JSONEncoder):
    pass
