"""Progressive layer drop (parity with
`deepspeed/runtime/progressive_layer_drop.py:5`).

Keep-probability schedule theta(t) = (1 - theta) * exp(-gamma * t) + theta.
The engine feeds the current theta into the model each step; GPT-2 applies
it as a scan-carried stochastic-depth gate (see `models/gpt2.py`).
"""

import numpy as np

from deepspeed_tpu.utils.logging import log_dist


class ProgressiveLayerDrop:
    def __init__(self, theta=0.5, gamma=0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist("Enabled progressive layer dropping (theta = {})".format(
            self.theta), ranks=[0])

    def get_state(self):
        kwargs = {"progressive_layer_drop": True, "pld_theta": self.get_theta()}
        return kwargs

    def get_theta(self):
        return self.current_theta

    def update_state(self, global_step):
        def _prob(x, gamma, p):
            return (1. - p) * np.exp(-gamma * x) + p

        self.current_theta = _prob(global_step, self.gamma, self.theta)
