"""User-facing activation checkpointing.

TPU-native rebuild of the reference subsystem
(`deepspeed/runtime/activation_checkpointing/checkpointing.py:362,666,747`):
`checkpoint(fn, *args)` reruns the wrapped computation during the
backward pass instead of saving its intermediates, and `configure()`
applies the JSON `activation_checkpointing` block to every subsequent
`checkpoint()` call.

Reference behaviour → JAX mapping:

* `CheckpointFunction` save/recompute (`:362-663`) → `jax.checkpoint`.
  RNG restoration (`:148-263`) is free: the recompute replays the same
  traced program with the same PRNG keys, bit-for-bit.
* `partition_activations` (`:282-312`, all-gather regather in backward)
  → the saved residuals (the checkpointed fn's inputs) carry a
  `with_sharding_constraint` over the `model` mesh axis; XLA inserts
  the backward all-gather exactly where `get_full_inputs` did.
* `cpu_checkpointing` (`PA_TO_CPU`, `:418-437`) → saved residuals are
  staged in `pinned_host` memory via device_put memory kinds; the
  backward recompute fetches them back.
* `contiguous_memory_optimization` / `number_checkpoints` /
  `synchronize_checkpoint_boundary` → accepted no-ops (XLA owns buffer
  packing and stream ordering); kept so configs parse identically.

A `checkpoint_policy` escape hatch (TPU extension) selects any
`jax.checkpoint_policies` entry by name for selective rematerialisation.

Named custom policies (TPU extension): `register_checkpoint_policy`
publishes a policy under a string name that `checkpoint()`, the model
configs' `remat_policy` fields and the `checkpoint_policy` config key
all resolve (`resolve_checkpoint_policy`).  The built-in
`"save_fused_epilogues"` policy is the PER-FUSION remat the fused
epilogue kernels enable (ops/transformer/fused_ops.py): instead of the
per-layer all-or-nothing (save block inputs, recompute everything), it
saves exactly the fused kernels' named outputs —

    attn_out / attn_lse        flash attention (never re-run the fwd
                               kernel; PR 4)
    fused_ln_out/fused_ln_sum  bias+residual+LayerNorm chain
    fused_gelu_sum             bias+GeLU input sum (the 4H-wide GeLU
                               OUTPUT is deliberately recomputed — one
                               transcendental pass vs 4H bytes/token,
                               the roofline's bytes/flops verdict)

so the rematted backward recomputes only the cheap glue (a qkv matmul,
LN stats) instead of the whole block.
"""

import contextlib
import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.runtime.mesh import MODEL_AXIS
from deepspeed_tpu.utils.logging import logger

# ----------------------------------------------------------------------
# module state (mirrors the reference's globals, checkpointing.py:40-56)
# ----------------------------------------------------------------------
PARTITION_ACTIVATIONS = False
CPU_CHECKPOINTING = False
CONTIGUOUS_CHECKPOINTING = False
SYNCHRONIZE = False
PROFILE_TIME = False
num_layers = None

_mesh = None
_policy_name = None
_configured = False
_host_offload_ok = None  # lazily probed


# ----------------------------------------------------------------------
# named checkpoint policies
# ----------------------------------------------------------------------
_NAMED_POLICIES = {}


def register_checkpoint_policy(name, policy):
    """Publish a jax.checkpoint policy under a string name, resolvable
    from every remat_policy/checkpoint_policy config field."""
    _NAMED_POLICIES[name] = policy


def _builtin_policies():
    if "save_fused_epilogues" not in _NAMED_POLICIES:
        from deepspeed_tpu.ops.transformer.fused_ops import \
            FUSED_EPILOGUE_SAVE_NAMES
        register_checkpoint_policy(
            "save_fused_epilogues",
            jax.checkpoint_policies.save_only_these_names(
                "attn_out", "attn_lse", *FUSED_EPILOGUE_SAVE_NAMES))
    return _NAMED_POLICIES


def resolve_checkpoint_policy(name):
    """Policy name -> jax policy: registered custom names first (incl.
    the built-in "save_fused_epilogues"), then the literal
    `"save_only_these_names:a,b"` syntax, then `jax.checkpoint_policies`
    attributes.  None passes through."""
    if name is None or callable(name):
        return name
    policies = _builtin_policies()
    if name in policies:
        return policies[name]
    if name.startswith("save_only_these_names:"):
        names = [n for n in name.split(":", 1)[1].split(",") if n]
        return jax.checkpoint_policies.save_only_these_names(*names)
    try:
        return getattr(jax.checkpoint_policies, name)
    except AttributeError:
        raise ValueError(
            f"unknown checkpoint policy {name!r}: not a registered "
            f"custom policy ({sorted(policies)}), a "
            "save_only_these_names:... spec, or a "
            "jax.checkpoint_policies attribute") from None


def is_configured():
    return _configured


def reset():
    """Reference parity (`checkpointing.py:691`): frees contiguous
    buffers between eval forwards.  XLA owns buffer lifetime, so this
    is a no-op."""


def set_num_layers(nlayers):
    global num_layers
    num_layers = nlayers


def partition_activations_in_checkpoint(partition_activation):
    global PARTITION_ACTIVATIONS
    PARTITION_ACTIVATIONS = partition_activation


def _configure_defaults():
    global PARTITION_ACTIVATIONS, CPU_CHECKPOINTING, \
        CONTIGUOUS_CHECKPOINTING, SYNCHRONIZE, PROFILE_TIME, num_layers, \
        _configured
    PARTITION_ACTIVATIONS = False
    CPU_CHECKPOINTING = False
    CONTIGUOUS_CHECKPOINTING = False
    SYNCHRONIZE = False
    PROFILE_TIME = False
    num_layers = None
    _configured = True


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None,
              mesh=None, checkpoint_policy=None):
    """Configure activation checkpointing (ref `checkpointing.py:747`).

    `deepspeed_config` may be a parsed `DeepSpeedConfig`, a dict, or a
    JSON path; explicit kwargs override its values.  `mesh` supplies the
    device mesh whose `model` axis `partition_activations` shards over
    (the reference gets this from `mpu_`; a Mesh is the TPU equivalent).
    """
    global PARTITION_ACTIVATIONS, CPU_CHECKPOINTING, \
        CONTIGUOUS_CHECKPOINTING, SYNCHRONIZE, PROFILE_TIME, num_layers, \
        _mesh, _policy_name, _configured

    _configure_defaults()
    if deepspeed_config is not None:
        cfg = deepspeed_config
        if isinstance(cfg, (str, dict)):
            from deepspeed_tpu.runtime.config import DeepSpeedConfig
            cfg = DeepSpeedConfig(cfg)
        ac = cfg.activation_checkpointing_config
        PARTITION_ACTIVATIONS = bool(ac.partition_activations)
        CPU_CHECKPOINTING = bool(ac.cpu_checkpointing)
        CONTIGUOUS_CHECKPOINTING = bool(ac.contiguous_memory_optimization)
        SYNCHRONIZE = bool(ac.synchronize_checkpoint_boundary)
        PROFILE_TIME = bool(ac.profile)
        num_layers = ac.number_checkpoints

    if partition_activations is not None:
        PARTITION_ACTIVATIONS = partition_activations
    if contiguous_checkpointing is not None:
        CONTIGUOUS_CHECKPOINTING = contiguous_checkpointing
    if num_checkpoints is not None:
        num_layers = num_checkpoints
    if checkpoint_in_cpu is not None:
        CPU_CHECKPOINTING = checkpoint_in_cpu
    if synchronize is not None:
        SYNCHRONIZE = synchronize
    if profile is not None:
        PROFILE_TIME = profile
    if mesh is not None:
        _mesh = mesh
    elif mpu_ is not None and hasattr(mpu_, "mesh"):
        _mesh = mpu_.mesh
    _policy_name = checkpoint_policy
    _configured = True


def _model_par(mesh):
    try:
        return int(mesh.shape[MODEL_AXIS])
    except (KeyError, TypeError):
        return 1


def _partition_spec(x, mesh):
    """Shard the last dim divisible by the model-axis size (the
    reference flattens and splits evenly, `checkpointing.py:266-281`;
    sharding one dim is the XLA-friendly equivalent).  The last dim is
    preferred because the leading dim is usually the batch dim, already
    sharded over `data` — re-sharding it over `model` would add an
    all-to-all and *replicate* over data, the opposite of the goal."""
    n = _model_par(mesh)
    spec = [None] * x.ndim
    for i in range(x.ndim - 1, -1, -1):
        d = x.shape[i]
        if d % n == 0 and d >= n:
            spec[i] = MODEL_AXIS
            break
    return PartitionSpec(*spec)


def _host_offload_supported():
    global _host_offload_ok
    if _host_offload_ok is None:
        try:
            dev = jax.devices()[0]
            x = jnp.zeros((8,), jnp.float32)

            @jax.jit
            def put_host(v):
                return jax.device_put(
                    v, jax.sharding.SingleDeviceSharding(
                        dev, memory_kind="pinned_host"))
            jax.device_get(put_host(x))
            _host_offload_ok = True
        except Exception as e:  # backend without host memory space
            logger.warning(
                f"cpu_checkpointing requested but the backend does not "
                f"support pinned_host memory ({type(e).__name__}); "
                "falling back to on-device checkpointing",
                exc_info=True)
            _host_offload_ok = False
    return _host_offload_ok


def _is_array(x):
    return isinstance(x, jax.Array) or hasattr(x, "dtype") and \
        hasattr(x, "shape")


def checkpoint(function, *args):
    """Checkpoint a function (ref `checkpointing.py:666`): its
    intermediates are recomputed, not saved, in the backward pass.
    Returns `function(*args)`."""
    policy = resolve_checkpoint_policy(_policy_name)

    partition = PARTITION_ACTIVATIONS and _mesh is not None and \
        _model_par(_mesh) > 1
    offload = CPU_CHECKPOINTING and _host_offload_supported()

    if PROFILE_TIME:
        inner = lambda *a: jax.named_scope("ds_checkpoint")(function)(*a)  # noqa: E731
    else:
        inner = function

    if not partition and not offload:
        return jax.checkpoint(inner, policy=policy)(*args)

    mesh = _mesh

    def _kinded_sharding(x, kind):
        if mesh is not None:
            spec = _partition_spec(x, mesh) if partition else \
                PartitionSpec(*([None] * x.ndim))
            return NamedSharding(mesh, spec, memory_kind=kind)
        # no mesh configured (plain single-device parity usage)
        return jax.sharding.SingleDeviceSharding(
            jax.devices()[0], memory_kind=kind)

    def stage(x):
        """Transform each saved input: shard over the model axis and/or
        park it in host memory until the backward recompute."""
        if not _is_array(x) or x.ndim == 0:
            return x
        if partition:
            x = jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, _partition_spec(x, mesh)))
        if offload:
            x = jax.device_put(x, _kinded_sharding(x, "pinned_host"))
        return x

    def unstage(x):
        if not _is_array(x) or x.ndim == 0:
            return x
        if offload:
            x = jax.device_put(x, _kinded_sharding(x, "device"))
        # partitioned activations are re-gathered by XLA wherever the
        # recompute needs them replicated (ref get_full_inputs,
        # checkpointing.py:282-312)
        return x

    staged = jax.tree_util.tree_map(stage, args)

    def run(*staged_args):
        live = jax.tree_util.tree_map(unstage, staged_args)
        return inner(*live)

    # jax.checkpoint saves only `run`'s inputs — i.e. the staged
    # (sharded / host-resident) tensors — as residuals.
    return jax.checkpoint(run, policy=policy)(*staged)


# ----------------------------------------------------------------------
# RNG stream tracker (API parity with CudaRNGStatesTracker,
# ref checkpointing.py:148-263)
# ----------------------------------------------------------------------
class RNGStatesTracker:
    """Named PRNG streams.  The reference forks/restores CUDA RNG state
    so dropout is reproducible across recompute and distinct across
    model-parallel ranks; in JAX recompute-reproducibility is automatic,
    so this tracker only manages the named streams."""

    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_ = {}

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name, seed):
        if name in self.states_:
            raise Exception(f"rng state {name} already exists")
        self.states_[name] = jax.random.PRNGKey(seed)

    @contextlib.contextmanager
    def fork(self, name="model-parallel-rng"):
        """Yields the stream's current key and advances the stream."""
        if name not in self.states_:
            raise Exception(f"rng state {name} is not added")
        key, nxt = jax.random.split(self.states_[name])
        try:
            yield key
        finally:
            self.states_[name] = nxt


_RNG_TRACKER = RNGStatesTracker()
_MODEL_PARALLEL_RNG = "model-parallel-rng"


def get_rng_tracker():
    return _RNG_TRACKER


def model_parallel_manual_seed(seed, model_parallel_rank=0):
    """Seed the default + model-parallel streams (ref
    `model_parallel_cuda_manual_seed`, checkpointing.py:224-263): the
    model-parallel stream differs per rank, the default stream does not.
    Under SPMD pass `jax.lax.axis_index(MODEL_AXIS)`-derived ranks
    inside shard_map, or a per-process rank outside."""
    _RNG_TRACKER.reset()
    mp_seed = seed + 2718 + int(model_parallel_rank)
    _RNG_TRACKER.add(_MODEL_PARALLEL_RNG, mp_seed)
    return jax.random.PRNGKey(seed)


# torch-API aliases (what reference user code imports)
get_cuda_rng_tracker = get_rng_tracker
model_parallel_cuda_manual_seed = model_parallel_manual_seed
CudaRNGStatesTracker = RNGStatesTracker
