"""Device-mesh management.

TPU-native replacement for the reference's process-group zoo
(`deepspeed/runtime/pipe/topology.py:252-455` builds NCCL groups per axis):
one `jax.sharding.Mesh` with named axes ('pipe', 'data', 'model') covers
every collective the framework issues — XLA lowers them onto ICI/DCN.

Config: {"mesh": {"pipe": 1, "data": -1, "model": 1}}; -1 infers the axis
size from the device count. Defaults to pure data parallelism.
"""

import math
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
MODEL_AXIS = "model"
AXIS_ORDER = (PIPE_AXIS, DATA_AXIS, MODEL_AXIS)


def build_mesh(mesh_config: Optional[dict] = None, devices=None) -> Mesh:
    """Build a 3-axis mesh.  Axis order (pipe, data, model) keeps the
    model axis innermost/fastest-varying — tensor-parallel collectives are
    the most latency-sensitive, so they get the shortest ICI hops."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    cfg = dict(mesh_config or {})
    pipe = int(cfg.get(PIPE_AXIS, 1))
    data = int(cfg.get(DATA_AXIS, -1))
    model = int(cfg.get(MODEL_AXIS, 1))

    known = [s for s in (pipe, data, model) if s != -1]
    n_known = math.prod(known) if known else 1
    n_unknown = sum(1 for s in (pipe, data, model) if s == -1)
    assert n_unknown <= 1, "at most one mesh axis may be -1 (inferred)"
    if n_unknown == 1:
        assert n % n_known == 0, \
            f"device count {n} not divisible by fixed axis product {n_known}"
        inferred = n // n_known
        pipe = inferred if pipe == -1 else pipe
        data = inferred if data == -1 else data
        model = inferred if model == -1 else model
    assert pipe * data * model == n, \
        f"mesh {pipe}x{data}x{model} != device count {n}"

    dev_array = np.asarray(devices).reshape(pipe, data, model)
    return Mesh(dev_array, AXIS_ORDER)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def data_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Batch-dim sharding for input arrays: shard dim 0 over ('pipe','data')
    so the global batch divides across all non-model devices."""
    spec = [None] * ndim
    spec[0] = (PIPE_AXIS, DATA_AXIS) if mesh.shape[PIPE_AXIS] > 1 else DATA_AXIS
    return NamedSharding(mesh, PartitionSpec(*spec))


def batch_sharding_for_tree(mesh: Mesh, tree):
    return jax.tree_util.tree_map(
        lambda x: data_sharding(mesh, np.ndim(x)), tree)


def stacked_batch_pspecs(tree):
    """PartitionSpecs for a microbatch-stacked batch pytree
    [gas, batch, ...]: shard dim 1 (the per-microbatch batch dim) over
    the data axis; scalars/1-D leaves stay replicated. Shared by every
    shard_map entry point that consumes the fused step's stacked batch
    (sparse-grad path, 1-bit Adam compressed path, pipeline executor)."""
    def one(x):
        spec = [None] * np.ndim(x)
        if np.ndim(x) > 1:
            spec[1] = DATA_AXIS
        return PartitionSpec(*spec)
    return jax.tree_util.tree_map(one, tree)
