"""Device-mesh management.

TPU-native replacement for the reference's process-group zoo
(`deepspeed/runtime/pipe/topology.py:252-455` builds NCCL groups per axis):
one `jax.sharding.Mesh` with named axes ('pipe', 'data', 'model') covers
every collective the framework issues — XLA lowers them onto ICI/DCN.

Config: {"mesh": {"pipe": 1, "data": -1, "model": 1}}; -1 infers the axis
size from the device count. Defaults to pure data parallelism.
"""

import math
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.runtime.constants import (MESH_DATA_AXIS,
                                             MESH_MODEL_AXIS,
                                             MESH_PIPE_AXIS)

# axis names shared with the "mesh" config block keys
PIPE_AXIS = MESH_PIPE_AXIS
DATA_AXIS = MESH_DATA_AXIS
MODEL_AXIS = MESH_MODEL_AXIS
AXIS_ORDER = (PIPE_AXIS, DATA_AXIS, MODEL_AXIS)


def build_mesh(mesh_config: Optional[dict] = None, devices=None) -> Mesh:
    """Build a 3-axis mesh.  Axis order (pipe, data, model) keeps the
    model axis innermost/fastest-varying — tensor-parallel collectives are
    the most latency-sensitive, so they get the shortest ICI hops."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    cfg = dict(mesh_config or {})
    pipe = int(cfg.get(PIPE_AXIS, 1))
    data = int(cfg.get(DATA_AXIS, -1))
    model = int(cfg.get(MODEL_AXIS, 1))

    known = [s for s in (pipe, data, model) if s != -1]
    n_known = math.prod(known) if known else 1
    n_unknown = sum(1 for s in (pipe, data, model) if s == -1)
    assert n_unknown <= 1, "at most one mesh axis may be -1 (inferred)"
    if n_unknown == 1:
        assert n % n_known == 0, \
            f"device count {n} not divisible by fixed axis product {n_known}"
        inferred = n // n_known
        pipe = inferred if pipe == -1 else pipe
        data = inferred if data == -1 else data
        model = inferred if model == -1 else model
    assert pipe * data * model == n, \
        f"mesh {pipe}x{data}x{model} != device count {n}"

    dev_array = np.asarray(devices).reshape(pipe, data, model)
    return Mesh(dev_array, AXIS_ORDER)


def host_device_groups(devices=None, num_hosts=1):
    """Split a device list into `num_hosts` contiguous "host" groups —
    the virtual-mesh analog of TPU hosts owning a fixed chip subset
    (on real hardware the grouping comes from device.process_index; on
    the forced-host CPU mesh every device reports process 0, so the
    contiguous split stands in). The elastic supervisor drops whole
    groups when a host is lost."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    # ValueError, not assert: num_hosts comes from user config
    # (elasticity.runtime.hosts) and must fail loudly under python -O
    # too — a stripped divisibility check would silently drop devices
    if not 1 <= num_hosts <= n:
        raise ValueError(
            f"num_hosts must be in [1, {n}], got {num_hosts}")
    if n % num_hosts != 0:
        raise ValueError(
            f"device count {n} not divisible into {num_hosts} "
            "host groups")
    per = n // num_hosts
    return [devices[i * per:(i + 1) * per] for i in range(num_hosts)]


def reform_mesh(devices, mesh_config: Optional[dict] = None) -> Mesh:
    """Re-form a mesh over an EXPLICIT surviving device list (elastic
    recovery after host loss): same axis semantics as build_mesh, with
    the data axis inferred from whatever devices remain unless the
    config pins it. Raises on an empty survivor set rather than
    building a zero-device mesh."""
    devices = list(devices)
    if not devices:
        raise ValueError("cannot re-form a mesh over zero devices")
    cfg = dict(mesh_config or {})
    cfg.setdefault(DATA_AXIS, -1)
    return build_mesh(cfg, devices=devices)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def data_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Batch-dim sharding for input arrays: shard dim 0 over ('pipe','data')
    so the global batch divides across all non-model devices."""
    spec = [None] * ndim
    spec[0] = (PIPE_AXIS, DATA_AXIS) if mesh.shape[PIPE_AXIS] > 1 else DATA_AXIS
    return NamedSharding(mesh, PartitionSpec(*spec))


def batch_sharding_for_tree(mesh: Mesh, tree):
    return jax.tree_util.tree_map(
        lambda x: data_sharding(mesh, np.ndim(x)), tree)


def stacked_batch_pspecs(tree):
    """PartitionSpecs for a microbatch-stacked batch pytree
    [gas, batch, ...]: shard dim 1 (the per-microbatch batch dim) over
    the data axis; scalars/1-D leaves stay replicated. Shared by every
    shard_map entry point that consumes the fused step's stacked batch
    (sparse-grad path, 1-bit Adam compressed path, pipeline executor)."""
    def one(x):
        spec = [None] * np.ndim(x)
        if np.ndim(x) > 1:
            spec[1] = DATA_AXIS
        return PartitionSpec(*spec)
    return jax.tree_util.tree_map(one, tree)
