"""Device-mesh management.

TPU-native replacement for the reference's process-group zoo
(`deepspeed/runtime/pipe/topology.py:252-455` builds NCCL groups per axis):
one `jax.sharding.Mesh` with named axes ('pipe', 'data', 'model') covers
every collective the framework issues — XLA lowers them onto ICI/DCN.

Config: {"mesh": {"pipe": 1, "data": -1, "model": 1}}; -1 infers the axis
size from the device count. Defaults to pure data parallelism.

Expert parallelism (deepspeed_tpu/moe/) adds an OPT-IN fourth axis:
{"mesh": {"expert": E}} builds ('pipe', 'data', 'expert', 'model') —
the axis exists only when the config names it, so every 3-axis caller
sees exactly the historical mesh. Batch data shards over
(pipe, data, expert): expert-parallel devices ARE data-parallel
devices (the DeepSpeed-MoE convention — the dispatch all-to-all runs
inside the data-parallel group), while expert parameters shard their
expert dimension over the axis.
"""

import math
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.runtime.constants import (MESH_DATA_AXIS,
                                             MESH_EXPERT_AXIS,
                                             MESH_MODEL_AXIS,
                                             MESH_PIPE_AXIS)

# axis names shared with the "mesh" config block keys
PIPE_AXIS = MESH_PIPE_AXIS
DATA_AXIS = MESH_DATA_AXIS
MODEL_AXIS = MESH_MODEL_AXIS
EXPERT_AXIS = MESH_EXPERT_AXIS
AXIS_ORDER = (PIPE_AXIS, DATA_AXIS, MODEL_AXIS)
# axis order WITH expert parallelism: expert sits between data and
# model — dispatch all-to-alls are batch-volume collectives (wider
# than tensor-parallel psums, narrower than data-parallel grad
# reductions), so they get the middling ICI locality
AXIS_ORDER_EXPERT = (PIPE_AXIS, DATA_AXIS, EXPERT_AXIS, MODEL_AXIS)


def expert_axis_size(mesh: Mesh) -> int:
    """Size of the expert axis (1 on meshes built without one)."""
    return dict(mesh.shape).get(EXPERT_AXIS, 1)


def build_mesh(mesh_config: Optional[dict] = None, devices=None) -> Mesh:
    """Build a 3-axis mesh (4-axis when the config names `expert`).
    Axis order (pipe, data, [expert,] model) keeps the model axis
    innermost/fastest-varying — tensor-parallel collectives are the
    most latency-sensitive, so they get the shortest ICI hops."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    cfg = dict(mesh_config or {})
    axes = AXIS_ORDER_EXPERT if EXPERT_AXIS in cfg else AXIS_ORDER
    sizes = {PIPE_AXIS: int(cfg.get(PIPE_AXIS, 1)),
             DATA_AXIS: int(cfg.get(DATA_AXIS, -1)),
             MODEL_AXIS: int(cfg.get(MODEL_AXIS, 1))}
    if EXPERT_AXIS in cfg:
        sizes[EXPERT_AXIS] = int(cfg.get(EXPERT_AXIS))

    known = [sizes[a] for a in axes if sizes[a] != -1]
    n_known = math.prod(known) if known else 1
    unknown = [a for a in axes if sizes[a] == -1]
    assert len(unknown) <= 1, \
        "at most one mesh axis may be -1 (inferred)"
    if unknown:
        assert n % n_known == 0, \
            f"device count {n} not divisible by fixed axis product {n_known}"
        sizes[unknown[0]] = n // n_known
    dims = tuple(sizes[a] for a in axes)
    assert math.prod(dims) == n, \
        f"mesh {'x'.join(map(str, dims))} != device count {n}"

    dev_array = np.asarray(devices).reshape(dims)
    return Mesh(dev_array, axes)


def host_device_groups(devices=None, num_hosts=1):
    """Split a device list into `num_hosts` contiguous "host" groups —
    the virtual-mesh analog of TPU hosts owning a fixed chip subset
    (on real hardware the grouping comes from device.process_index; on
    the forced-host CPU mesh every device reports process 0, so the
    contiguous split stands in). The elastic supervisor drops whole
    groups when a host is lost."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    # ValueError, not assert: num_hosts comes from user config
    # (elasticity.runtime.hosts) and must fail loudly under python -O
    # too — a stripped divisibility check would silently drop devices
    if not 1 <= num_hosts <= n:
        raise ValueError(
            f"num_hosts must be in [1, {n}], got {num_hosts}")
    if n % num_hosts != 0:
        raise ValueError(
            f"device count {n} not divisible into {num_hosts} "
            "host groups")
    per = n // num_hosts
    return [devices[i * per:(i + 1) * per] for i in range(num_hosts)]


def reform_mesh(devices, mesh_config: Optional[dict] = None) -> Mesh:
    """Re-form a mesh over an EXPLICIT surviving device list (elastic
    recovery after host loss): same axis semantics as build_mesh, with
    the data axis inferred from whatever devices remain unless the
    config pins it. A pinned `expert` axis survives the re-form — the
    data axis absorbs the loss, so expert state re-plans onto the same
    expert-group count (the survivor count must stay divisible by the
    pinned axes; build_mesh raises otherwise). Raises on an empty
    survivor set rather than building a zero-device mesh."""
    devices = list(devices)
    if not devices:
        raise ValueError("cannot re-form a mesh over zero devices")
    cfg = dict(mesh_config or {})
    cfg.setdefault(DATA_AXIS, -1)
    return build_mesh(cfg, devices=devices)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_axes(mesh: Mesh):
    """The mesh axes the batch dimension shards over: ('pipe',) 'data'
    (, 'expert') — every non-model axis present on this mesh. One
    name, a tuple otherwise (PartitionSpec treats them the same)."""
    shape = dict(mesh.shape)
    axes = [a for a in (PIPE_AXIS, DATA_AXIS, EXPERT_AXIS)
            if shape.get(a, 1) > 1 or a == DATA_AXIS]
    # drop size-1 pipe/expert for spec-literal parity with the
    # historical 3-axis behavior ((pipe, data) only when pipe > 1)
    return tuple(axes) if len(axes) > 1 else axes[0]


def data_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Batch-dim sharding for input arrays: shard dim 0 over every
    non-model axis (('pipe','data','expert') as present) so the global
    batch divides across all non-model devices."""
    spec = [None] * ndim
    spec[0] = batch_axes(mesh)
    return NamedSharding(mesh, PartitionSpec(*spec))


def batch_sharding_for_tree(mesh: Mesh, tree):
    return jax.tree_util.tree_map(
        lambda x: data_sharding(mesh, np.ndim(x)), tree)


def stacked_batch_pspecs(tree, mesh: Optional[Mesh] = None):
    """PartitionSpecs for a microbatch-stacked batch pytree
    [gas, batch, ...]: shard dim 1 (the per-microbatch batch dim) over
    the data axis (plus the expert axis when `mesh` carries one);
    scalars/1-D leaves stay replicated. Shared by every shard_map
    entry point that consumes the fused step's stacked batch
    (sparse-grad path, 1-bit Adam compressed path, pipeline
    executor)."""
    row_axes = DATA_AXIS
    if mesh is not None and expert_axis_size(mesh) > 1:
        row_axes = (DATA_AXIS, EXPERT_AXIS)

    def one(x):
        spec = [None] * np.ndim(x)
        if np.ndim(x) > 1:
            spec[1] = row_axes
        return PartitionSpec(*spec)
    return jax.tree_util.tree_map(one, tree)
