"""Compressed collectives (ref `runtime/custom_collectives.py`: MPI/cupy
igather/allgather helpers for 1-bit Adam). On TPU the compressed
allreduce is a bit-packed `all_gather` over the mesh's data axis —
implemented in `runtime/fp16/onebit_adam.py` and re-exported here for
component parity."""

from deepspeed_tpu.runtime.fp16.onebit_adam import (
    pack_signs, unpack_signs, compress, compressed_allreduce)

__all__ = ["pack_signs", "unpack_signs", "compress",
           "compressed_allreduce"]
