"""Runtime utilities: partitioning math, norms, memory reporting.

Parity with `deepspeed/runtime/utils.py` — the pieces that survive the
move to SPMD: `partition_uniform`/`partition_balanced` (used by pipeline
stage assignment, ref `utils.py:311,377`), global-norm helpers (the
cross-rank overflow vote, ref `utils.py:63`, is free under SPMD: every
device computes the same reduction), and device memory reporting.
`PartitionedTensor` (ref `utils.py:395-505`) has no analogue — a sharded
jax.Array with a NamedSharding *is* a partitioned tensor with meta.
"""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import logger


def ensure_directory_exists(filename):
    import os
    dirname = os.path.dirname(filename)
    if dirname:
        os.makedirs(dirname, exist_ok=True)


class CheckOverflow:
    """Overflow check over a pytree of grads. Under SPMD this is a pure
    function of the (globally consistent) grads — no collective vote."""

    def __init__(self, param_groups=None, mpu=None, zero_reduce_scatter=False):
        self.mpu = mpu
        self.params = param_groups

    @staticmethod
    def has_overflow(grads):
        leaves = jax.tree_util.tree_leaves(grads)
        if not leaves:
            return jnp.asarray(False)
        finite = jnp.stack(
            [jnp.all(jnp.isfinite(g)) for g in leaves])
        return ~jnp.all(finite)

    check = has_overflow


def get_grad_norm(tree, norm_type=2):
    """Global gradient norm in fp32."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    if norm_type == float("inf") or norm_type == "inf":
        return jnp.max(jnp.stack(
            [jnp.max(jnp.abs(l.astype(jnp.float32))) for l in leaves]))
    sq = [jnp.vdot(l.astype(jnp.float32), l.astype(jnp.float32))
          for l in leaves]
    return jnp.sqrt(jnp.sum(jnp.stack(sq)))


get_weight_norm = get_grad_norm


def clip_grad_norm_(tree, max_norm, norm_type=2):
    """Return (clipped_tree, norm). Functional — no in-place mutation."""
    norm = get_grad_norm(tree, norm_type)
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda g: g * factor, tree), norm


def partition_uniform(num_items, num_parts):
    """Evenly spread items over parts; returns part boundaries (len
    num_parts+1), ref `utils.py:311`."""
    parts = [0] * (num_parts + 1)
    chunksize = num_items // num_parts
    for p in range(num_parts):
        parts[p] = min(chunksize * p, num_items)
    parts[num_parts] = num_items
    return parts


def prefix_sum_inc(weights):
    """Inclusive prefix sum."""
    out = list(weights)
    for i in range(1, len(out)):
        out[i] += out[i - 1]
    return out


def _lprobe(weights, num_parts, bottleneck):
    """Greedy probe: can we split `weights` into `num_parts` chunks each
    summing <= bottleneck? Returns (parts, success)."""
    parts = [0]
    total = 0
    for i, w in enumerate(weights):
        if total + w > bottleneck and total > 0:
            parts.append(i)
            total = 0
            if len(parts) > num_parts:
                return parts, False
        total += w
    while len(parts) < num_parts:
        parts.append(len(weights))
    parts.append(len(weights))
    return parts[:num_parts + 1], len(parts) <= num_parts + 1


def partition_balanced(weights, num_parts, eps=1e-3):
    """Binary-search the minimal bottleneck so each contiguous part's
    weight sum <= bottleneck (ref `utils.py:377`). Returns boundaries of
    length num_parts+1."""
    weights = list(weights)
    num_items = len(weights)
    if num_items <= num_parts:
        return partition_uniform(num_items, num_parts)

    lo = max(weights)
    hi = sum(weights)
    while hi - lo > eps * max(1.0, hi):
        mid = (lo + hi) / 2
        _, ok = _lprobe(weights, num_parts, mid)
        if ok:
            hi = mid
        else:
            lo = mid
    parts, ok = _lprobe(weights, num_parts, hi)
    assert ok
    return parts


def see_memory_usage(message, force=False):
    """Log the aggregate device-memory picture. Rides
    `device_memory_stats` (sum of in-use over ALL local devices, max
    peak) — reading only `jax.local_devices()[0]` disagreed with the
    monitor gauge and `env_report` on multi-device meshes. Off-TPU
    (no allocator stats) it reports host RSS instead, so the line
    stays meaningful on CPU/virtual-mesh runs."""
    if not force:
        return
    from deepspeed_tpu.utils.timer import device_memory_stats
    gib = 1024 ** 3
    stats = device_memory_stats()
    if stats["device_count"]:
        logger.info(
            f"{message} | DeviceMem in-use "
            f"{stats['in_use_bytes'] / gib:.2f} GB "
            f"peak {stats['peak_bytes'] / gib:.2f} GB "
            f"(over {stats['device_count']} local devices)")
    elif stats.get("host_rss_bytes"):
        logger.info(f"{message} | device memory stats unavailable; "
                    f"host RSS {stats['host_rss_bytes'] / gib:.2f} GB")
    else:
        logger.info(f"{message} | device memory stats unavailable")


def memory_status(msg, print_rank=-1, reset_max=False):
    see_memory_usage(msg, force=True)


def global_norm_squared(tree):
    return get_grad_norm(tree) ** 2


def call_to_str(base, *args, **kwargs):
    """Construct a string representation of a call (ref `utils.py`)."""
    name = f"{base}("
    if args:
        name += ", ".join(repr(arg) for arg in args)
        if kwargs:
            name += ", "
    if kwargs:
        name += ", ".join(f"{key}={repr(arg)}"
                          for key, arg in kwargs.items())
    name += ")"
    return name


def _zeros_like_f32(tree):
    """fp32 zeros pytree matching `tree`'s shapes (grad accumulators)."""
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros(x.shape, jnp.float32), tree)
