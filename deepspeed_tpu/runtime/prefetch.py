"""Background batch prefetch: double-buffered host→device staging.

The reference hides input latency with pinned-memory DataLoader workers
and async H2D copies on side CUDA streams; the XLA-native analogue is a
worker thread that collates the next `gas` microbatches into one stacked
`[gas, micro_bs, ...]` pytree and `stage_batch`-places it on device
while the current fused step is still in flight. `np.stack`, `device_put`
and the transfer itself all release the GIL, so the copy genuinely
overlaps the step loop's Python.

The queue holds at most `depth` staged batches (classic double buffering
at the default depth=2): the worker blocks once it is `depth` ahead, so
device memory holds a bounded number of staged batches no matter how
slow the consumer is.

Usage::

    loader = engine.prefetch(iter(microbatches))   # or PrefetchLoader(...)
    for _ in range(steps):
        loss = engine.train_batch(data_iter=loader)
    loader.close()

`train_batch` recognizes a PrefetchLoader and takes the pre-staged
stacked batch directly — no host collate on the critical path.
"""

import queue
import threading

import numpy as np


class _Sentinel:
    pass


_DONE = _Sentinel()


class PrefetchLoader:
    """Iterate staged batches prepared by a background worker.

    Args:
      source: iterable yielding microbatch pytrees (numpy-convertible
        leaves), or — with ``stacked=True`` — pre-stacked
        ``[gas, micro_bs, ...]`` batches.
      stage_fn: places a stacked batch on device (the engine's
        ``stage_batch``). May be None to prefetch host-side only.
      gas: microbatches collated per stacked batch (ignored when
        ``stacked=True``).
      depth: max staged batches in flight ahead of the consumer.
      heartbeat: optional zero-arg callable invoked after each staged
        batch (the monitor's stall-watchdog heartbeat — a quiet
        prefetch worker shows up by age in the stall diagnostic).
      finished: optional zero-arg callable invoked once when the worker
        exits (source exhausted, error, or close). The monitor marks
        the heartbeat TERMINAL there: a cleanly-finished worker's
        growing heartbeat age must not read as a stall.
      span: optional callable (t_start, dur_sec) per staged batch — the
        Perfetto "prefetch" track stamp (collate + device staging time
        on the worker thread).
    """

    def __init__(self, source, stage_fn=None, gas=1, depth=2,
                 stacked=False, heartbeat=None, finished=None,
                 span=None):
        self._source = source
        self._stage_fn = stage_fn
        self._gas = max(1, int(gas))
        self._stacked = stacked
        self._heartbeat = heartbeat
        self._finished = finished
        self._span = span
        # bytes of one staged batch (set by the worker after the first
        # stage; shape metadata only) — the memory ledger's dynamic
        # prefetch entry samples occupancy x this
        self.staged_nbytes = 0
        self.depth = max(1, int(depth))
        self._queue = queue.Queue(maxsize=self.depth)
        self._exc = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._worker, name="ds-tpu-prefetch", daemon=True)
        self._thread.start()

    def _next_stacked(self, it):
        if self._stacked:
            return next(it)
        micro = []
        for _ in range(self._gas):
            # a partial tail (< gas microbatches) can't form a step;
            # treat it like the exhausted iterator train_batch would
            # have tripped on
            micro.append(next(it))
        import jax
        return jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]), *micro)

    def _worker(self):
        import time
        try:
            it = iter(self._source)
            while not self._closed:
                t0 = time.perf_counter()
                try:
                    batch = self._next_stacked(it)
                except StopIteration:
                    break
                if self._stage_fn is not None:
                    batch = self._stage_fn(batch)
                if not self.staged_nbytes:
                    try:
                        from deepspeed_tpu.monitor.memory import \
                            tree_nbytes
                        self.staged_nbytes = tree_nbytes(batch)
                    except Exception:  # ds-lint: allow[BROADEXC] best-effort byte gauge for the memory ledger; staging must not fail on it
                        pass
                if self._span is not None:
                    try:
                        self._span(t0, time.perf_counter() - t0)
                    except Exception:  # ds-lint: allow[BROADEXC] telemetry hook; a broken trace exporter must not kill the staging worker
                        pass
                self._put(batch)
                if self._heartbeat is not None:
                    try:
                        self._heartbeat()
                    except Exception:  # ds-lint: allow[BROADEXC] telemetry hook; a broken watchdog must not kill the staging worker
                        pass
        except BaseException as e:  # ds-lint: allow[BROADEXC] stored and re-raised on the consumer side at the next __next__
            self._exc = e
        finally:
            self._put(_DONE)
            if self._finished is not None:
                # the worker is DONE (exhausted/closed/errored): its
                # heartbeat goes terminal — the watchdog must not count
                # a finished subsystem's age toward a stall verdict
                try:
                    self._finished()
                except Exception:  # ds-lint: allow[BROADEXC] telemetry hook; the worker is already exiting
                    pass

    def _put(self, item):
        # bounded put that aborts when the consumer closes mid-wait
        # (otherwise close() could deadlock against a full queue)
        while True:
            try:
                self._queue.put(item, timeout=0.1)
                return
            except queue.Full:
                if self._closed:
                    return

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._closed:
                # close() drains the queue (sentinel included) after the
                # worker exits; an unbounded get() here would hang
                raise StopIteration
            try:
                item = self._queue.get(timeout=0.1)
                break
            except queue.Empty:
                continue
        if isinstance(item, _Sentinel):
            self._queue.put(item)   # keep signalling subsequent calls
            if self._exc is not None:
                exc, self._exc = self._exc, None
                raise exc
            raise StopIteration
        return item

    def occupancy(self):
        """Staged batches currently queued ahead of the consumer (the
        monitor's prefetch gauge: 0 at a fence means the input pipeline
        is the bottleneck; == depth means the step loop is)."""
        return self._queue.qsize()

    def buffer_bytes(self):
        """Device bytes held by queued staged batches right now
        (occupancy x per-batch bytes) — the memory ledger's dynamic
        prefetch entry. Plus one batch for the item the worker holds
        between stage and put would overstate the steady state; the
        queue is the bound that matters."""
        return self._queue.qsize() * self.staged_nbytes

    def close(self):
        """Stop the worker and drop queued batches."""
        self._closed = True
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
