"""Row-sparse (CSR-style) gradient support.

TPU-native rebuild of the reference's sparse embedding-gradient path
(`deepspeed/runtime/csr_tensor.py:11`, `engine.py:1190-1246`): an
embedding gradient is nonzero only in the rows touched by the batch, so
the DP reduction gathers (indices, values) pairs — payload O(K·D) —
instead of allreducing the dense [V, D] gradient.

XLA needs static shapes, so sparsity is *capacity-bounded*: `capacity`
rows are extracted per device (`jnp.where(..., size=capacity)`); a batch
of B·T tokens touches at most B·T rows, making the bound exact for the
embedding case.  The reference's dynamic `all_gather` of varying-length
tensors padded to the max size (`engine.py:1215-1243`) becomes a fixed
`lax.all_gather` of the capacity-padded arrays — the same wire format,
statically shaped.
"""

import jax
import jax.numpy as jnp
import numpy as np


class CSRTensor:
    """API-parity container (ref `csr_tensor.py:11`): row-compressed
    view of a [rows, cols] tensor with static row capacity."""

    def __init__(self, dense_tensor=None, capacity=None):
        self.orig_dense_tensor = dense_tensor
        if dense_tensor is not None:
            rows = dense_tensor.shape[0]
            if capacity is None:
                capacity = rows
            used = jnp.any(dense_tensor != 0, axis=tuple(
                range(1, dense_tensor.ndim)))
            # fill_value=rows marks padding slots (clipped+masked on use)
            (idx,) = jnp.where(used, size=capacity, fill_value=rows)
            safe = jnp.clip(idx, 0, rows - 1)
            vals = dense_tensor[safe] * (idx < rows).astype(
                dense_tensor.dtype)[:, None]
            self.indices = idx
            self.values = vals
            self.dense_size = list(dense_tensor.shape)
        else:
            self.indices = None
            self.values = None
            self.dense_size = None

    @staticmethod
    def type():
        return "deepspeed.CSRTensor"

    def to_dense(self):
        rows = self.dense_size[0]
        valid = (self.indices < rows).astype(self.values.dtype)
        safe = jnp.clip(self.indices, 0, rows - 1)
        dense = jnp.zeros(self.dense_size, self.values.dtype)
        return dense.at[safe].add(self.values * valid[:, None])

    def sparse_size(self):
        index_size = int(np.prod(self.indices.shape))
        value_size = int(np.prod(self.values.shape))
        dense_size = int(np.prod(self.dense_size))
        return index_size + value_size, dense_size

    def add(self, b):
        assert self.dense_size == b.dense_size
        self.indices = jnp.concatenate([self.indices, b.indices])
        self.values = jnp.concatenate([self.values, b.values])

    def __str__(self):
        sparse_size, dense_size = self.sparse_size()
        return (f"deepspeed_tpu.CSRTensor(indices_size="
                f"{list(self.indices.shape)}, values_size="
                f"{list(self.values.shape)}, dense_size={self.dense_size}, "
                f"reduction_factor={dense_size / max(sparse_size, 1):.1f})")

    __repr__ = __str__


def csr_mean_rows(local_grad, axis_name, capacity):
    """Sparse DP mean of a row-sparse gradient, for use inside
    `shard_map`: compress local rows, `all_gather` (indices, values)
    over `axis_name`, scatter-add into dense (the reference gathers then
    densifies too, `engine.py:1192-1196`).  Wire payload per device is
    capacity·(cols+1) elements vs rows·cols for a dense allreduce."""
    rows = local_grad.shape[0]
    world = jax.lax.psum(1, axis_name)
    csr = CSRTensor(local_grad / world, capacity=capacity)

    all_idx = jax.lax.all_gather(csr.indices, axis_name)   # [W, K]
    all_val = jax.lax.all_gather(csr.values, axis_name)    # [W, K, D]

    flat_idx = all_idx.reshape(-1)
    flat_val = all_val.reshape(-1, local_grad.shape[1])
    valid = (flat_idx < rows).astype(flat_val.dtype)
    safe = jnp.clip(flat_idx, 0, rows - 1)
    dense = jnp.zeros_like(local_grad)
    return dense.at[safe].add(flat_val * valid[:, None])
