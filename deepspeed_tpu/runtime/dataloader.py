"""Data loading.

Parity with `deepspeed/runtime/dataloader.py:10,33` (DeepSpeedDataLoader
auto-creating a distributed sampler + RepeatingLoader), torch-free: works
over numpy-array dicts, indexable datasets (incl. torch datasets), or any
iterable. Per-host sharding replaces DistributedSampler — each JAX process
loads only its slice of the global batch (single-controller runs see the
whole batch; the engine then shards it over the mesh on device_put).
"""

import math

import numpy as np


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (ref dataloader.py:10)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        return batch


def _default_collate(samples):
    """Stack a list of samples (dicts of arrays / arrays / tuples)."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples])
                for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(
            np.stack([np.asarray(s[i]) for s in samples])
            for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


class DeepSpeedDataLoader:
    def __init__(self,
                 dataset,
                 batch_size,
                 local_rank=0,
                 tput_timer=None,
                 collate_fn=None,
                 num_local_io_workers=None,
                 data_sampler=None,
                 data_parallel_world_size=1,
                 data_parallel_rank=0,
                 shuffle=False,
                 seed=0,
                 drop_last=True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.tput_timer = tput_timer
        self.collate_fn = collate_fn or _default_collate
        self.dp_world_size = data_parallel_world_size
        self.dp_rank = data_parallel_rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0

        self._length = self._compute_length()

    def _dataset_len(self):
        if hasattr(self.dataset, "__len__"):
            return len(self.dataset)
        raise TypeError("dataset must be sized for DeepSpeedDataLoader")

    def _compute_length(self):
        n = self._dataset_len()
        per_rank = n // self.dp_world_size if self.drop_last else \
            math.ceil(n / self.dp_world_size)
        if self.drop_last:
            return per_rank // self.batch_size
        return math.ceil(per_rank / self.batch_size)

    def __len__(self):
        return self._length

    def set_epoch(self, epoch):
        self.epoch = epoch

    def _indices(self):
        n = self._dataset_len()
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            order = rng.permutation(n)
        else:
            order = np.arange(n)
        # contiguous per-rank shard (each process loads only its slice)
        per_rank = n // self.dp_world_size if self.drop_last else \
            math.ceil(n / self.dp_world_size)
        start = self.dp_rank * per_rank
        return order[start:start + per_rank]

    def __iter__(self):
        indices = self._indices()
        nb = self._length
        for b in range(nb):
            if self.tput_timer:
                self.tput_timer.start()
            idx = indices[b * self.batch_size:(b + 1) * self.batch_size]
            samples = [self.dataset[int(i)] for i in idx]
            yield self.collate_fn(samples)
        self.epoch += 1
