"""16-bit optimizer state + stochastic rounding — the TPU-native
replacement for fp32 master weights.

The reference's mixed-precision recipe (fp16 params + fp32 master copy +
fp32 Adam moments, `deepspeed/runtime/fp16/fused_optimizer.py`) costs
16 bytes/param of optimizer-side state. On a 16 GB-HBM chip that caps
on-chip training at ~0.9B params. The TPU-native alternative keeps
EVERYTHING in bf16 — params, mu, nu (6 bytes/param) — and recovers fp32
master-quality updates two ways:

  * all update MATH runs in fp32 (moments are decoded bf16->fp32,
    updated, re-encoded; bf16's fp32-range exponent means no loss-scale
    machinery is needed), and
  * the param write-back uses STOCHASTIC ROUNDING: fp32 -> bf16 by
    adding 16 uniform random bits below the mantissa cut before
    truncation, so E[round(x)] = x and tiny updates (|u| << ulp(p))
    accumulate in expectation instead of being swallowed. This is the
    established TPU practice for master-less bf16 training.

`bf16 {"enabled": true, "master_weights": false}` selects this mode in
the engine; `tests/test_bf16_sr.py` holds the loss-trajectory parity
test against the fp32-master path.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


class ScaleByAdamBF16State(NamedTuple):
    count: jnp.ndarray
    mu: Any     # state_dtype (bf16) pytree
    nu: Any     # state_dtype (bf16) pytree


def scale_by_adam_bf16(b1=0.9, b2=0.999, eps=1e-8,
                       state_dtype=jnp.bfloat16):
    """optax-style scale_by_adam whose persistent moments live in
    `state_dtype`; the moment recursion and the preconditioned update
    are computed in fp32 every step (decode -> update -> re-encode).

    bf16 carries fp32's exponent, so the nu (second-moment) dynamic
    range is safe; only ~8 mantissa bits of RELATIVE precision are kept,
    which enters the update as a ~0.4% jitter on 1/sqrt(nu) — far below
    gradient noise. (The same trick with fp16 would overflow nu.)"""

    def init_fn(params):
        z = lambda p: jnp.zeros(p.shape, state_dtype)
        return ScaleByAdamBF16State(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree_util.tree_map(z, params),
            nu=jax.tree_util.tree_map(z, params))

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        mu32 = jax.tree_util.tree_map(
            lambda m, g: b1 * m.astype(jnp.float32) +
            (1.0 - b1) * g.astype(jnp.float32), state.mu, updates)
        nu32 = jax.tree_util.tree_map(
            lambda v, g: b2 * v.astype(jnp.float32) +
            (1.0 - b2) * jnp.square(g.astype(jnp.float32)),
            state.nu, updates)
        c = count.astype(jnp.float32)
        bc1 = 1.0 - jnp.power(b1, c)
        bc2 = 1.0 - jnp.power(b2, c)
        precond = jax.tree_util.tree_map(
            lambda m, v: (m / bc1) / (jnp.sqrt(v / bc2) + eps),
            mu32, nu32)
        enc = lambda t: jax.tree_util.tree_map(
            lambda x: x.astype(state_dtype), t)
        return precond, ScaleByAdamBF16State(count=count, mu=enc(mu32),
                                             nu=enc(nu32))

    return optax.GradientTransformation(init_fn, update_fn)


def _adamw_bf16(learning_rate, b1=0.9, b2=0.999, eps=1e-8,
                weight_decay=0.0, state_dtype=jnp.bfloat16):
    inner = scale_by_adam_bf16(b1=b1, b2=b2, eps=eps,
                               state_dtype=state_dtype)

    def init_fn(params):
        return inner.init(params)

    def update_fn(updates, state, params=None):
        precond, new_state = inner.update(updates, state)
        # weight_decay/learning_rate may be inject_hyperparams tracers —
        # apply unconditionally (0.0 is exact)
        precond = jax.tree_util.tree_map(
            lambda u, p: u + weight_decay * p.astype(jnp.float32),
            precond, params)
        scaled = jax.tree_util.tree_map(
            lambda u: -learning_rate * u, precond)
        return scaled, new_state

    return optax.GradientTransformation(init_fn, update_fn)


def adamw_bf16(learning_rate, b1=0.9, b2=0.999, eps=1e-8,
               weight_decay=0.0, state_dtype=jnp.bfloat16):
    """AdamW with 16-bit moments; `learning_rate` rides
    inject_hyperparams so the engine's scheduler plumbing (`_with_lr`)
    works unchanged. Returns fp32 updates — pair with
    `stochastic_round_apply`, NOT optax.apply_updates (a deterministic
    bf16 add would re-swallow small updates)."""
    return optax.inject_hyperparams(
        _adamw_bf16, static_args=("state_dtype",),
        hyperparam_dtype=jnp.float32)(
        learning_rate=learning_rate, b1=b1, b2=b2, eps=eps,
        weight_decay=weight_decay, state_dtype=state_dtype)


def _as_rbg_key(key):
    """Re-wrap any PRNG key as an `unsafe_rbg` key: its bit generation
    lowers to XLA RngBitGenerator — the TPU's hardware RNG — instead of
    a threefry VPU program. At flagship scale the rounding noise covers
    every param (1.5B+ uint16 draws per step); threefry's ~10+ VPU ops
    per word made the noise a first-order optimizer-update cost, while
    dither for rounding needs no cryptographic stream quality."""
    data = jnp.ravel(jax.random.key_data(key))
    data = jnp.concatenate([data, data])[:4] if data.size < 4 else data[:4]
    return jax.random.wrap_key_data(data, impl="unsafe_rbg")


def stochastic_round_bf16(x32, key):
    """fp32 -> bf16 with unbiased stochastic rounding: add uniform
    random bits below the 16-bit truncation point, then truncate.
    Handles ties/carries exactly (integer add propagates into the kept
    mantissa); NaN/inf pass through (their exponent field saturates)."""
    bits = jax.lax.bitcast_convert_type(x32.astype(jnp.float32),
                                        jnp.uint32)
    noise = jax.random.bits(_as_rbg_key(key), x32.shape,
                            jnp.uint32) & jnp.uint32(0xFFFF)
    rounded = (bits + noise) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(rounded,
                                        jnp.float32).astype(jnp.bfloat16)


def stochastic_round_apply(params, updates, key):
    """params (bf16) + updates (fp32) -> new bf16 params via
    stochastic rounding. One independent key per leaf."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.tree_util.tree_unflatten(
        treedef, list(jax.random.split(key, len(leaves))))

    def apply_one(p, u, k):
        return stochastic_round_bf16(
            p.astype(jnp.float32) + u.astype(jnp.float32), k)

    return jax.tree_util.tree_map(apply_one, params, updates, keys)
