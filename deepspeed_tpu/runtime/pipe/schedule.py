"""Pipeline schedules: instruction-stream generators.

Parity with `deepspeed/runtime/pipe/schedule.py:6-474` — the reference's
best architectural idea (schedule = declarative instruction generator,
engine = interpreter) is kept intact. On TPU the *SPMD* execution path
(`pipe/engine.py`) realizes TrainSchedule's dataflow implicitly inside a
single compiled program (scan over ticks + collective-permute), so these
generators serve three roles:

  1. the sequential interpreter path for heterogeneous PipelineModules,
  2. documentation/validation of execution order (tested like ref
     `tests/unit/test_pipe_schedule.py`),
  3. future host-driven multi-controller schedules.
"""

from abc import ABC, abstractmethod


class PipeSchedule(ABC):
    """Directs the execution of a pipe engine by generating sequences of
    PipeInstruction (ref `schedule.py:6-127`).

    Args:
        micro_batches: micro-batches per batch (gradient accumulation).
        stages: number of pipeline stages.
        stage_id: the stage whose instruction stream to generate.
    """

    def __init__(self, micro_batches, stages, stage_id):
        super().__init__()
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = self.stage_id - 1
        self.next_stage = self.stage_id + 1

    @abstractmethod
    def steps(self):
        """Yield a list of PipeInstruction per step."""
        raise NotImplementedError()

    def num_pipe_buffers(self):
        """Upper bound on simultaneously-live pipeline buffers."""
        return self.micro_batches

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, micro_batch_id):
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        self.it = None
        return self

    def __next__(self):
        if self.it is None:
            self.it = self.steps()
        return next(self.it)


class InferenceSchedule(PipeSchedule):
    """Forward-only schedule (ref `schedule.py:129-180`)."""

    def steps(self):
        prev_micro_batch_id = -1
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            micro_batch_id = step_id - self.stage_id
            cmds = []
            # Alternate send/recv ordering by stage parity to avoid
            # deadlocks in a host-driven runtime (ref `schedule.py:145-168`)
            if _is_even(self.stage_id):
                if self._valid_stage(self.next_stage) and \
                        self._valid_micro_batch(prev_micro_batch_id):
                    cmds.append(SendActivation(
                        self._buffer_idx(prev_micro_batch_id)))
                if self._valid_stage(self.prev_stage) and \
                        self._valid_micro_batch(micro_batch_id):
                    cmds.append(RecvActivation(
                        self._buffer_idx(micro_batch_id)))
            else:
                if self._valid_stage(self.prev_stage) and \
                        self._valid_micro_batch(micro_batch_id):
                    cmds.append(RecvActivation(
                        self._buffer_idx(micro_batch_id)))
                if self._valid_stage(self.next_stage) and \
                        self._valid_micro_batch(prev_micro_batch_id):
                    cmds.append(SendActivation(
                        self._buffer_idx(prev_micro_batch_id)))

            if self._valid_micro_batch(micro_batch_id):
                if self.is_first_stage or self.is_last_stage:
                    cmds.append(LoadMicroBatch(
                        self._buffer_idx(micro_batch_id)))
                cmds.append(ForwardPass(self._buffer_idx(micro_batch_id)))
            prev_micro_batch_id = micro_batch_id
            yield cmds

    def num_pipe_buffers(self):
        """Inference needs only two alternating buffers
        (ref `schedule.py:174-180`)."""
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B train schedule: warmup forwards, steady-state alternating
    backward/forward, drain backwards (ref `schedule.py:182-289` uses an
    equivalent even/odd-step interleaving). Live activations per stage
    are bounded by `num_pipe_buffers`, the property the reference's
    interleaving exists to achieve."""

    def steps(self):
        m = self.micro_batches
        warmup = min(self.stages - self.stage_id, m)

        def fwd_cmds(mb):
            cmds = []
            if self._valid_stage(self.prev_stage):
                cmds.append(RecvActivation(self._buffer_idx(mb)))
            if self.is_first_stage or self.is_last_stage:
                cmds.append(LoadMicroBatch(self._buffer_idx(mb)))
            cmds.append(ForwardPass(self._buffer_idx(mb)))
            if self._valid_stage(self.next_stage):
                cmds.append(SendActivation(self._buffer_idx(mb)))
            return cmds

        def bwd_cmds(mb):
            cmds = []
            if self._valid_stage(self.next_stage):
                cmds.append(RecvGrad(self._buffer_idx(mb)))
            cmds.append(BackwardPass(self._buffer_idx(mb)))
            if self._valid_stage(self.prev_stage):
                cmds.append(SendGrad(self._buffer_idx(mb)))
            return cmds

        # warmup: forwards fill the pipeline
        for mb in range(warmup):
            yield fwd_cmds(mb)
        # steady state: one backward then one forward per step
        for i in range(m - warmup):
            yield bwd_cmds(i)
            yield fwd_cmds(warmup + i)
        # drain: remaining backwards; batch-end reductions ride the last
        for i in range(m - warmup, m):
            cmds = bwd_cmds(i)
            if i == m - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())
            yield cmds

    def num_pipe_buffers(self):
        """min(stages - stage_id + 1, micro_batches), >= 2
        (ref `schedule.py:243-247`)."""
        buffers = min(self.stages - self.stage_id + 1, self.micro_batches)
        return max(2, buffers)


class DataParallelSchedule(PipeSchedule):
    """Pure-DP schedule through the pipeline machinery
    (ref `schedule.py:292-314`)."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [
                LoadMicroBatch(buffer_id=0),
                ForwardPass(buffer_id=0),
                BackwardPass(buffer_id=0),
            ]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1


class PipeInstruction:
    """Base instruction (ref `schedule.py:317-341`)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for key, val in kwargs.items():
            setattr(self, key, val)

    def __repr__(self):
        from deepspeed_tpu.runtime.utils import call_to_str
        return call_to_str(self.name, **self.kwargs)

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


def _is_even(x):
    return x % 2 == 0


def _is_odd(x):
    return x % 2 != 0
