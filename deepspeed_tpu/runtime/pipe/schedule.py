"""Pipeline schedules: instruction-stream generators.

Parity with `deepspeed/runtime/pipe/schedule.py:6-474` — the reference's
best architectural idea (schedule = declarative instruction generator,
engine = interpreter) is kept intact. On TPU the *SPMD* execution path
(`pipe/engine.py`) realizes TrainSchedule's dataflow implicitly inside a
single compiled program (scan over ticks + collective-permute), so these
generators serve three roles:

  1. the sequential interpreter path for heterogeneous PipelineModules,
  2. documentation/validation of execution order (tested like ref
     `tests/unit/test_pipe_schedule.py`),
  3. future host-driven multi-controller schedules.
"""

from abc import ABC, abstractmethod


class PipeSchedule(ABC):
    """Directs the execution of a pipe engine by generating sequences of
    PipeInstruction (ref `schedule.py:6-127`).

    Args:
        micro_batches: micro-batches per batch (gradient accumulation).
        stages: number of pipeline stages.
        stage_id: the stage whose instruction stream to generate.
    """

    def __init__(self, micro_batches, stages, stage_id):
        super().__init__()
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = self.stage_id - 1
        self.next_stage = self.stage_id + 1

    @abstractmethod
    def steps(self):
        """Yield a list of PipeInstruction per step."""
        raise NotImplementedError()

    def num_pipe_buffers(self):
        """Upper bound on simultaneously-live pipeline buffers."""
        return self.micro_batches

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, micro_batch_id):
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        self.it = None
        return self

    def __next__(self):
        if self.it is None:
            self.it = self.steps()
        return next(self.it)


class InferenceSchedule(PipeSchedule):
    """Forward-only schedule (ref `schedule.py:129-180`)."""

    def steps(self):
        prev_micro_batch_id = -1
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            micro_batch_id = step_id - self.stage_id
            cmds = []
            # Alternate send/recv ordering by stage parity to avoid
            # deadlocks in a host-driven runtime (ref `schedule.py:145-168`)
            if _is_even(self.stage_id):
                if self._valid_stage(self.next_stage) and \
                        self._valid_micro_batch(prev_micro_batch_id):
                    cmds.append(SendActivation(
                        self._buffer_idx(prev_micro_batch_id)))
                if self._valid_stage(self.prev_stage) and \
                        self._valid_micro_batch(micro_batch_id):
                    cmds.append(RecvActivation(
                        self._buffer_idx(micro_batch_id)))
            else:
                if self._valid_stage(self.prev_stage) and \
                        self._valid_micro_batch(micro_batch_id):
                    cmds.append(RecvActivation(
                        self._buffer_idx(micro_batch_id)))
                if self._valid_stage(self.next_stage) and \
                        self._valid_micro_batch(prev_micro_batch_id):
                    cmds.append(SendActivation(
                        self._buffer_idx(prev_micro_batch_id)))

            if self._valid_micro_batch(micro_batch_id):
                if self.is_first_stage or self.is_last_stage:
                    cmds.append(LoadMicroBatch(
                        self._buffer_idx(micro_batch_id)))
                cmds.append(ForwardPass(self._buffer_idx(micro_batch_id)))
            prev_micro_batch_id = micro_batch_id
            yield cmds

    def num_pipe_buffers(self):
        """Inference needs only two alternating buffers
        (ref `schedule.py:174-180`)."""
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B train schedule: warmup forwards, steady-state alternating
    backward/forward, drain backwards (ref `schedule.py:182-289` uses an
    equivalent even/odd-step interleaving). Live activations per stage
    are bounded by `num_pipe_buffers`, the property the reference's
    interleaving exists to achieve."""

    def steps(self):
        m = self.micro_batches
        warmup = min(self.stages - self.stage_id, m)

        def fwd_cmds(mb):
            cmds = []
            if self._valid_stage(self.prev_stage):
                cmds.append(RecvActivation(self._buffer_idx(mb)))
            if self.is_first_stage or self.is_last_stage:
                cmds.append(LoadMicroBatch(self._buffer_idx(mb)))
            cmds.append(ForwardPass(self._buffer_idx(mb)))
            if self._valid_stage(self.next_stage):
                cmds.append(SendActivation(self._buffer_idx(mb)))
            return cmds

        def bwd_cmds(mb):
            cmds = []
            if self._valid_stage(self.next_stage):
                cmds.append(RecvGrad(self._buffer_idx(mb)))
            cmds.append(BackwardPass(self._buffer_idx(mb)))
            if self._valid_stage(self.prev_stage):
                cmds.append(SendGrad(self._buffer_idx(mb)))
            return cmds

        # warmup: forwards fill the pipeline
        for mb in range(warmup):
            yield fwd_cmds(mb)
        # steady state: one backward then one forward per step
        for i in range(m - warmup):
            yield bwd_cmds(i)
            yield fwd_cmds(warmup + i)
        # drain: remaining backwards; batch-end reductions ride the last
        for i in range(m - warmup, m):
            cmds = bwd_cmds(i)
            if i == m - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())
            yield cmds

    def num_pipe_buffers(self):
        """min(stages - stage_id + 1, micro_batches), >= 2
        (ref `schedule.py:243-247`)."""
        buffers = min(self.stages - self.stage_id + 1, self.micro_batches)
        return max(2, buffers)


def interleaved_fwd_cmds(stage, stages, num_chunks, vidx, mb, buf):
    """Forward command emission for one interleaved (chunk, microbatch)
    op — the ONE source of truth for the dataflow (recv when not the
    first chunk, load data/labels on the first/last chunk, send when
    not the last), shared by InterleavedTrainSchedule.steps() and the
    fwd-only eval streams (interp._inference_streams)."""
    q = vidx * stages + stage
    cmds = []
    if q > 0:
        cmds.append(RecvActivation(buf, chunk=vidx))
    if q == 0 or q == num_chunks - 1:
        cmds.append(LoadMicroBatch(buf, chunk=vidx, mb=mb))
    cmds.append(ForwardPass(buf, chunk=vidx, mb=mb))
    if q < num_chunks - 1:
        cmds.append(SendActivation(buf, chunk=vidx))
    return cmds


class InterleavedTrainSchedule(PipeSchedule):
    """Interleaved (virtual-stage) 1F1B — the Megatron-LM schedule that
    cuts the pipeline bubble from (p-1)/(m+p-1) stage-times toward
    (p-1)/(v·m+p-1): every physical stage hosts `v` model chunks
    assigned ROUND-ROBIN (global chunk q lives on stage q % p as its
    q // p-th virtual stage), so the fill/drain ramp advances in
    chunk-times (1/v of a stage-time) instead of stage-times.

    Microbatches are processed in groups of p: the i-th forward op of a
    stage runs chunk (i % (p·v)) // p on microbatch
    (i // (p·v))·p + i % p; backwards mirror the order with chunks
    reversed.  Warmup depth is the Megatron formula
    2·(p - stage - 1) + (v - 1)·p, then strict 1F1B alternation, then
    the backward drain.  Requires micro_batches % stages == 0 (the
    group structure).

    Instruction streams carry a `chunk` kwarg (the LOCAL virtual index)
    on Forward/BackwardPass; communication is a RING — the last stage's
    non-final chunks send activations to stage 0 (and stage 0's
    non-first chunks send gradients to the last stage).  The compiled
    executor (`pipe/interp.py`) lowers these streams exactly like
    TrainSchedule's, with the ppermute ring closed.

    The known cost: more in-flight activations per stage (a chunk can
    have up to ~m forwards outstanding at m = 2p) and a larger compiled
    program (v× the schedule ticks, each 1/v the work) — the standard
    Megatron memory/bubble trade.
    """

    def __init__(self, micro_batches, stages, stage_id,
                 num_virtual_stages=2):
        super().__init__(micro_batches, stages, stage_id)
        self.num_virtual_stages = int(num_virtual_stages)
        if self.num_virtual_stages < 1:
            raise ValueError(
                f"num_virtual_stages must be >= 1, got "
                f"{num_virtual_stages}")
        if micro_batches % stages:
            raise ValueError(
                f"interleaved 1F1B requires micro_batches divisible by "
                f"stages (microbatch groups of p): got m={micro_batches}"
                f", p={stages}")
        # cached: _buffer_of consults this per op and the scan is
        # O(total ops) — recomputing it per call made steps() quadratic
        self._per_chunk_buffers = None

    # -- op ordering (Megatron get_forward_backward_func) --------------
    def _fwd_cm(self, i):
        p, v = self.stages, self.num_virtual_stages
        group, within = divmod(i, p * v)
        vidx, off = divmod(within, p)
        return vidx, group * p + off

    def _bwd_cm(self, j):
        p, v = self.stages, self.num_virtual_stages
        group, within = divmod(j, p * v)
        vidx = v - 1 - within // p
        return vidx, group * p + within % p

    def _ops(self):
        p, v, s = self.stages, self.num_virtual_stages, self.stage_id
        total = self.micro_batches * v
        warmup = min((p - s - 1) * 2 + (v - 1) * p, total)
        ops = [("F", i) for i in range(warmup)]
        for j in range(total - warmup):
            ops.append(("F", warmup + j))
            ops.append(("B", j))
        for j in range(total - warmup, total):
            ops.append(("B", j))
        return ops

    def per_chunk_buffers(self):
        """Max in-flight forwards of any one chunk on this stage (the
        saved-input buffer bound per virtual stage); computed once."""
        if self._per_chunk_buffers is None:
            live = [0] * self.num_virtual_stages
            peak = 1
            for kind, i in self._ops():
                vidx, _ = self._fwd_cm(i) if kind == "F" \
                    else self._bwd_cm(i)
                live[vidx] += 1 if kind == "F" else -1
                peak = max(peak, live[vidx])
            self._per_chunk_buffers = peak
        return self._per_chunk_buffers

    def num_pipe_buffers(self):
        return self.num_virtual_stages * self.per_chunk_buffers()

    def _buffer_of(self, vidx, mb):
        # per-chunk in-flight microbatches form a contiguous window of
        # at most per_chunk_buffers(), so mb mod the bound never
        # collides
        return vidx * self.per_chunk_buffers() + \
            mb % self.per_chunk_buffers()

    def steps(self):
        p, v, s = self.stages, self.num_virtual_stages, self.stage_id
        n_chunks = p * v
        ops = self._ops()
        for n, (kind, i) in enumerate(ops):
            if kind == "F":
                vidx, mb = self._fwd_cm(i)
                cmds = interleaved_fwd_cmds(s, p, n_chunks, vidx, mb,
                                            self._buffer_of(vidx, mb))
            else:
                cmds = []
                vidx, mb = self._bwd_cm(i)
                q = vidx * p + s
                buf = self._buffer_of(vidx, mb)
                if q < n_chunks - 1:
                    cmds.append(RecvGrad(buf, chunk=vidx))
                cmds.append(BackwardPass(buf, chunk=vidx, mb=mb))
                if q > 0:
                    cmds.append(SendGrad(buf, chunk=vidx))
            if n == len(ops) - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())
            yield cmds


class DataParallelSchedule(PipeSchedule):
    """Pure-DP schedule through the pipeline machinery
    (ref `schedule.py:292-314`)."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [
                LoadMicroBatch(buffer_id=0),
                ForwardPass(buffer_id=0),
                BackwardPass(buffer_id=0),
            ]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1


class PipeInstruction:
    """Base instruction (ref `schedule.py:317-341`)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for key, val in kwargs.items():
            setattr(self, key, val)

    def __repr__(self):
        from deepspeed_tpu.runtime.utils import call_to_str
        return call_to_str(self.name, **self.kwargs)

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


def _is_even(x):
    return x % 2 == 0


def _is_odd(x):
    return x % 2 != 0
