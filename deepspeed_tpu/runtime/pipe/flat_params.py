"""Per-stage flat parameter storage for the compiled 1F1B executor.

The reference's pipeline builds only each stage's local layers on each
process (`deepspeed/runtime/pipe/module.py:197-249`), so pipeline
parallelism divides parameter/gradient/optimizer memory by the stage
count. Under single-controller SPMD the same partitioning is expressed
as a STORAGE LAYOUT: every stage-exclusive parameter leaf is raveled
into its stage's flat segment, segments are padded to the widest
stage and stacked into one `[S, F]` buffer per dtype, and that buffer
is sharded over the `pipe` mesh axis — each pipe shard's local slice
IS its stage's parameters, no gather needed. Gradients, fp32 masters,
and optimizer moments inherit the layout (they are elementwise images
of the params), so the FULL training state divides by the stage count.

Tied leaves (TiedLayerSpec) are used by several stages; they stay in
their original tree form, replicated over the pipe axis, with their
gradients psum-reduced — the compiled form of the reference's
tied-grad allreduce (`module.py:405-409`), unchanged from before.

The engine stores `{"flat": {dtype: [S, F]}, "tied": <tree>}` as its
parameter pytree; `unflatten_stage` (static stage id, used inside each
stage's lax.switch branch) and `unflatten` (full tree, used for
checkpoint/eval) are exact inverses of `flatten` — ravel/reshape only,
no value change.
"""

import numpy as np
import jax
import jax.numpy as jnp


def _dt_key(dtype):
    return np.dtype(dtype).name


class StageFlatLayout:
    """Static description of the per-stage flat layout.

    Built once from the module's partitioning and an example param
    structure (`{"layers": {idx: tree}, "tied": {key: tree}}` from
    `PipelineModule.init_params`). All offsets/shapes are recorded at
    build time; flatten/unflatten are pure reshape/concat programs that
    work identically on host numpy and inside jit.
    """

    def __init__(self, module, params_example, align=1,
                 stage_layers=None):
        """align: round the per-dtype buffer width F up to a multiple —
        the engine passes model*data so the [S, F] buffers divide evenly
        over the model axis (interp in_specs) and the composed
        (model, data) master sharding (zero/partition.py).

        stage_layers: optional explicit per-stage layer-index lists
        (len = physical stage count).  Interleaved 1F1B passes the
        round-robin chunk assignment here — stage s stores chunks
        {s, s+S, ...}, a NON-contiguous layer set the default
        module.parts ranges cannot express."""
        if stage_layers is None:
            parts = module.parts
            stage_layers = [list(range(parts[s], parts[s + 1]))
                            for s in range(module.num_stages)]
        self.S = len(stage_layers)
        self._stage_treedefs = []
        self._stage_meta = []      # per stage: list of (dt_key, offset, shape)
        sizes = {}                 # dt_key -> per-stage sizes
        for s in range(self.S):
            sub = {str(i): params_example["layers"][str(i)]
                   for i in stage_layers[s]
                   if str(i) in params_example.get("layers", {})}
            leaves, treedef = jax.tree_util.tree_flatten(sub)
            self._stage_treedefs.append(treedef)
            meta = []
            offsets = {}
            for leaf in leaves:
                dt = _dt_key(leaf.dtype)
                off = offsets.get(dt, 0)
                shape = tuple(np.shape(leaf))
                meta.append((dt, off, shape))
                offsets[dt] = off + int(np.prod(shape))
            self._stage_meta.append(meta)
            for dt, end in offsets.items():
                sizes.setdefault(dt, [0] * self.S)[s] = end
        # padded width per dtype buffer = widest stage, rounded to align
        self.F = {dt: -(-max(per_stage) // align) * align
                  for dt, per_stage in sizes.items()}

    def num_params(self, stored):
        """True parameter count (per-stage padding excluded)."""
        n = sum(int(np.prod(shape)) for meta in self._stage_meta
                for _, _, shape in meta)
        n += sum(int(np.prod(np.shape(l))) for l in
                 jax.tree_util.tree_leaves(stored.get("tied", {})))
        return n

    # -- stage-level ----------------------------------------------------
    def flatten_stage(self, s, stage_tree):
        """Stage subtree -> {dt: [F_dt]} padded flat vectors."""
        leaves = jax.tree_util.tree_leaves(stage_tree)
        segs = {dt: [] for dt in self.F}
        for (dt, _, shape), leaf in zip(self._stage_meta[s], leaves):
            segs[dt].append(jnp.ravel(leaf))
        out = {}
        for dt in self.F:
            flat = (jnp.concatenate(segs[dt]) if segs[dt]
                    else jnp.zeros((0,), dt))
            out[dt] = jnp.pad(flat, (0, self.F[dt] - flat.shape[0]))
        return out

    def unflatten_stage(self, s, flat):
        """{dt: [F_dt]} -> stage subtree (leaves take each buffer's
        current dtype — the engine casts buffers wholesale, exactly as
        it casts whole param trees in tree form)."""
        leaves = []
        for dt, off, shape in self._stage_meta[s]:
            n = int(np.prod(shape))
            leaves.append(flat[dt][off:off + n].reshape(shape))
        return jax.tree_util.tree_unflatten(self._stage_treedefs[s],
                                            leaves)

    # -- full-tree ------------------------------------------------------
    def flatten(self, params):
        """Full `{"layers", "tied"}` structure -> stored layout
        `{"flat": {dt: [S, F_dt]}, "tied": tree}`."""
        bufs = {dt: [] for dt in self.F}
        for s in range(self.S):
            stage_flat = self.flatten_stage(
                s, self._stage_subtree(params, s))
            for dt in self.F:
                bufs[dt].append(stage_flat[dt])
        return {"flat": {dt: jnp.stack(bufs[dt]) for dt in self.F},
                "tied": params.get("tied", {})}

    def _stage_subtree(self, params, s):
        # the stage treedef was built from {idx_str: layer_tree}, so
        # top-level keys identify the stage's layers in the live dict
        td = self._stage_treedefs[s]
        example = td.unflatten([0] * td.num_leaves)
        return {idx_str: params["layers"][idx_str] for idx_str in example}

    def unflatten(self, stored):
        """Stored layout -> full `{"layers", "tied"}` structure."""
        layers = {}
        for s in range(self.S):
            flat_s = {dt: stored["flat"][dt][s] for dt in self.F}
            sub = self.unflatten_stage(s, flat_s)
            layers.update(sub)
        return {"layers": layers, "tied": stored.get("tied", {})}

    def template(self, stored):
        """Abstract full-tree template (ShapeDtypeStructs) matching what
        `unflatten(stored)` would produce — for checkpoint loaders."""
        return jax.eval_shape(self.unflatten, stored)
