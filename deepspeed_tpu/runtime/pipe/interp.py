"""Compiled 1F1B executor for heterogeneous PipelineModules.

Counterpart of the reference's schedule interpreter
(`deepspeed/runtime/pipe/engine.py:1135-1161`: `_exec_schedule` walking
`_INSTRUCTION_MAP` with blocking p2p). The TPU-native form compiles the
SAME TrainSchedule instruction streams into one SPMD program:

  1. `build_clock_tables` interprets every stage's TrainSchedule stream
     with a FIFO one-slot channel model (send at tick t is receivable
     from tick t+1 — the compiled analogue of blocking p2p) into
     globally clock-aligned numpy tables: which stage runs which
     microbatch's forward/backward at every tick.
  2. `build_pipeline_step` lowers those tables to a `lax.scan` over
     ticks inside `shard_map` over the `pipe` mesh axis. Each pipe
     shard executes ITS stage's work via `lax.switch` (per-device
     divergent control flow — heterogeneous layers and activation
     shapes are handled by padding inter-stage activations to one flat
     f32 buffer), activations ride `ppermute(+1)` and cotangents
     `ppermute(-1)`.

Backward uses per-(microbatch, stage) recompute from the saved stage
INPUT activation (`jax.vjp` inside the backward branch), so the live
activation memory per stage is the schedule's buffer bound —
`TrainSchedule.num_pipe_buffers() = min(stages - stage + 1, m)` saved
inputs (ref `schedule.py:243-247`) — instead of GPipe's `m` full
per-layer residual sets. Stages genuinely overlap: at any steady-state
tick every pipe shard is executing a different microbatch.

Tied layers (TiedLayerSpec) appear in several stages; each shard
contributes its stage's grads and the final `psum` over the pipe axis
IS ReduceTiedGrads (ref `module.py:405-409`).

MEMORY: stage-exclusive parameters are stored in the per-stage flat
layout (`pipe/flat_params.py`) — one `[S, F]` buffer per dtype sharded
over the pipe axis, so each shard holds only its stage's params, grads
and optimizer state (the SPMD form of the reference building only
local layers per process, ref `module.py:197-249`); tied leaves stay
replicated with psum'd grads. Together with the schedule's
`num_pipe_buffers()` activation bound, pipe>1 divides both parameter
and activation memory by the stage count.

MODEL-AXIS COMPOSITION: with model>1 the [S, F] buffers shard over the
model axis too (each (pipe, model) shard stores F/model of its stage,
masters/moments compose (model, data) on top), the stage compute
all-gathers its stage over the model axis per tick and keeps only its
own grad segment — parameter/optimizer memory divides by pipe*model
(*data for masters), the storage composition of the reference's
pipe×model grid (`topology.py:246-249`). The gather is the ZeRO-3
pattern riding the shortest ICI hops (model is the innermost mesh
axis); split-matmul tensor parallelism inside a stage needs TP-aware
layers, which the homogeneous stacked-stage SPMD protocol provides.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from deepspeed_tpu.runtime.compat import shard_map
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.runtime.mesh import (DATA_AXIS, MODEL_AXIS, PIPE_AXIS,
                                        stacked_batch_pspecs)
from deepspeed_tpu.runtime.pipe.schedule import (
    TrainSchedule, InterleavedTrainSchedule, ForwardPass, BackwardPass,
    SendActivation, RecvActivation, SendGrad, RecvGrad, LoadMicroBatch,
    interleaved_fwd_cmds)


# ----------------------------------------------------------------------
# schedule -> clock tables
# ----------------------------------------------------------------------
def _inference_streams(m, S, v=1):
    """Canonical fwd-only streams with InferenceSchedule's dataflow
    (`schedule.py:86-127`). The literal InferenceSchedule emits
    SendActivation one step AFTER the producing ForwardPass (a
    host-runtime buffering detail); the compiled executor's send
    register holds exactly one tick, so the send is folded into the
    producing step — same dependency structure, same 2-buffer bound.

    v > 1: the interleaved forward order (microbatch groups of S,
    chunks round-robin — InterleavedTrainSchedule's fwd stream) with
    two alternating buffers per chunk."""
    n_chunks = S * v
    streams = []
    for s in range(S):
        steps = []
        if v == 1:
            order = [(0, mb) for mb in range(m)]
        else:
            sched = InterleavedTrainSchedule(m, S, s, v)
            order = [sched._fwd_cm(i) for i in range(m * v)]
        for vidx, mb in order:
            # two alternating eval buffers per chunk; the dataflow
            # itself comes from the schedule's single source of truth
            steps.append(interleaved_fwd_cmds(
                s, S, n_chunks, vidx, mb, vidx * 2 + mb % 2))
        streams.append(steps)
    return streams


def build_clock_tables(micro_batches, stages, train=True,
                       num_virtual_stages=1):
    """Align the per-stage schedule streams on a global clock
    (TrainSchedule / InterleavedTrainSchedule for num_virtual_stages>1,
    or the fwd-only InferenceSchedule dataflow when train=False).

    Each stage executes at most one schedule step per tick; a step is
    eligible when every RecvActivation/RecvGrad it contains pairs with
    a Send* completed at an EARLIER tick (k-th recv on a channel pairs
    with the k-th send — FIFO), and any Send* it contains has a free
    channel slot. Returns int/bool arrays indexed [tick, stage].

    Channels form a RING when interleaving (round-robin chunk q sends
    forward to stage (q+1) mod S — the last stage's non-final chunks
    wrap to stage 0); with one virtual stage the wrap channels are
    never used and the tables are bit-identical to before.  The
    fwd/bwd chunk rows carry the GLOBAL chunk id (vidx·S + s) the
    executor's lax.switch dispatches on, and the sent_act/sent_grad
    rows gate the executor's send registers so an op that does not
    send (e.g. the loss chunk on the last stage) cannot clobber an
    undelivered value."""
    m, S, v = micro_batches, stages, int(num_virtual_stages)
    if train:
        if v > 1:
            streams = [list(InterleavedTrainSchedule(m, S, s, v).steps())
                       for s in range(S)]
        else:
            streams = [list(TrainSchedule(m, S, s).steps())
                       for s in range(S)]
    else:
        streams = _inference_streams(m, S, v)

    # one-slot channels deadlock the interleaved ring (every stage's
    # warmup wants recv+fwd+send atomically while every channel holds
    # an undelivered value); depth-2 rings break the cycle for every
    # (m, S, v) swept — retry upward as a safety margin. v == 1 keeps
    # the single slot: tables (and the compiled program) stay identical
    # to the pre-interleaving executor.
    caps = (1,) if v == 1 else (2, 3, 4, 2 * v * S)
    tables = None
    for cap in caps:
        tables = _align_streams(streams, S, cap,
                                max_ticks=4 * (m * v + S) + 16)
        if tables is not None:
            break
    assert tables is not None, "clock alignment did not converge"
    return tables


def _align_streams(streams, S, cap, max_ticks):
    """Greedy clock alignment of per-stage instruction streams with
    `cap`-deep FIFO delivery rings per channel.  Returns the tick
    tables, or None if the streams deadlock at this capacity."""
    fwd_mb = []
    fwd_buf = []
    fwd_ch = []
    bwd_mb = []
    bwd_buf = []
    bwd_ch = []
    sent_act = []
    sent_grad = []
    recv_act_slot = []
    recv_grad_slot = []

    send_act_count = [0] * S
    recv_act_count = [0] * S
    send_grad_count = [0] * S
    recv_grad_count = [0] * S
    fwd_count = [0] * S
    bwd_count = [0] * S
    ptr = [0] * S
    t = 0
    while any(ptr[s] < len(streams[s]) for s in range(S)):
        if t >= max_ticks:
            return None
        f_row = [-1] * S
        fb_row = [0] * S
        fc_row = [0] * S
        b_row = [-1] * S
        bb_row = [0] * S
        bc_row = [0] * S
        sa_row = [False] * S
        sg_row = [False] * S
        ras_row = [-1] * S
        rgs_row = [-1] * S
        snap_sa = list(send_act_count)
        snap_sg = list(send_grad_count)
        snap_ra = list(recv_act_count)
        snap_rg = list(recv_grad_count)
        progressed = False
        for s in range(S):
            if ptr[s] >= len(streams[s]):
                continue
            cmds = streams[s][ptr[s]]
            ok = True
            for c in cmds:
                if isinstance(c, RecvActivation):
                    # k-th recv pairs with the k-th send (FIFO), which
                    # must have completed at an EARLIER tick
                    ok &= recv_act_count[s] < snap_sa[(s - 1) % S]
                elif isinstance(c, RecvGrad):
                    ok &= recv_grad_count[s] < snap_sg[(s + 1) % S]
                elif isinstance(c, SendActivation):
                    # ring depth: at most `cap` sends in flight
                    # (delivered-but-unconsumed) per channel
                    ok &= send_act_count[s] - snap_ra[(s + 1) % S] < cap
                elif isinstance(c, SendGrad):
                    ok &= send_grad_count[s] - snap_rg[(s - 1) % S] < cap
            if not ok:
                continue
            progressed = True
            for c in cmds:
                if isinstance(c, RecvActivation):
                    ras_row[s] = recv_act_count[s] % cap
                    recv_act_count[s] += 1
                elif isinstance(c, RecvGrad):
                    rgs_row[s] = recv_grad_count[s] % cap
                    recv_grad_count[s] += 1
                elif isinstance(c, SendActivation):
                    send_act_count[s] += 1
                    sa_row[s] = True
                elif isinstance(c, SendGrad):
                    send_grad_count[s] += 1
                    sg_row[s] = True
                elif isinstance(c, ForwardPass):
                    # the executor needs the MICROBATCH id (what the
                    # first/last chunks index the stacked batch with);
                    # plain schedules execute microbatches in order so
                    # the fwd ordinal doubles as the id, interleaved
                    # ops carry it explicitly
                    f_row[s] = getattr(c, "mb", fwd_count[s])
                    fb_row[s] = c.buffer_id
                    fc_row[s] = getattr(c, "chunk", 0) * S + s
                    fwd_count[s] += 1
                elif isinstance(c, BackwardPass):
                    b_row[s] = getattr(c, "mb", bwd_count[s])
                    bb_row[s] = c.buffer_id
                    bc_row[s] = getattr(c, "chunk", 0) * S + s
                    bwd_count[s] += 1
            ptr[s] += 1
        fwd_mb.append(f_row)
        fwd_buf.append(fb_row)
        fwd_ch.append(fc_row)
        bwd_mb.append(b_row)
        bwd_buf.append(bb_row)
        bwd_ch.append(bc_row)
        sent_act.append(sa_row)
        sent_grad.append(sg_row)
        recv_act_slot.append(ras_row)
        recv_grad_slot.append(rgs_row)
        t += 1
        if not progressed:
            return None

    T = t
    sent_act = np.asarray(sent_act, bool)
    sent_grad = np.asarray(sent_grad, bool)
    # delivery at tick t = what the ring neighbor sent at tick t-1
    # (acts travel +1 mod S, grads -1 mod S; the wrap columns are
    # all-False when v == 1).  The k-th delivery lands in ring slot
    # k % cap — the slot the k-th recv reads.
    deliver_act = np.zeros((T, S), bool)
    deliver_act[1:] = np.roll(sent_act[:-1], 1, axis=1)
    deliver_grad = np.zeros((T, S), bool)
    deliver_grad[1:] = np.roll(sent_grad[:-1], -1, axis=1)
    deliver_act_slot = np.full((T, S), -1, np.int32)
    deliver_grad_slot = np.full((T, S), -1, np.int32)
    dcount_a = np.zeros(S, np.int64)
    dcount_g = np.zeros(S, np.int64)
    for tick in range(T):
        for s in range(S):
            if deliver_act[tick, s]:
                deliver_act_slot[tick, s] = dcount_a[s] % cap
                dcount_a[s] += 1
            if deliver_grad[tick, s]:
                deliver_grad_slot[tick, s] = dcount_g[s] % cap
                dcount_g[s] += 1
    return {
        "fwd_mb": np.asarray(fwd_mb, np.int32),
        "fwd_buf": np.asarray(fwd_buf, np.int32),
        "fwd_chunk": np.asarray(fwd_ch, np.int32),
        "bwd_mb": np.asarray(bwd_mb, np.int32),
        "bwd_buf": np.asarray(bwd_buf, np.int32),
        "bwd_chunk": np.asarray(bwd_ch, np.int32),
        "sent_act": sent_act,
        "sent_grad": sent_grad,
        "deliver_act": deliver_act,
        "deliver_grad": deliver_grad,
        "deliver_act_slot": deliver_act_slot,
        "deliver_grad_slot": deliver_grad_slot,
        "recv_act_slot": np.asarray(recv_act_slot, np.int32),
        "recv_grad_slot": np.asarray(recv_grad_slot, np.int32),
        "channel_depth": cap,
        "num_ticks": T,
    }


def num_pipe_buffers(micro_batches, stages, num_virtual_stages=1):
    """Global buffer-array bound: the worst stage's
    num_pipe_buffers() (plain 1F1B stage 0: min(stages+1, m))."""
    if num_virtual_stages > 1:
        return max(InterleavedTrainSchedule(
            micro_batches, stages, s, num_virtual_stages)
            .num_pipe_buffers() for s in range(stages))
    return max(TrainSchedule(micro_batches, stages, s).num_pipe_buffers()
               for s in range(stages))


# ----------------------------------------------------------------------
# stage function construction
# ----------------------------------------------------------------------
def _microbatch(tree, mb):
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_index_in_dim(a, mb, 0, keepdims=False),
        tree)


def build_pipeline_step(module, mesh, micro_batches, params_example,
                        batch_example, split_batch, det_accepting,
                        train=True, layout=None, num_virtual_stages=1,
                        chunk_parts=None):
    """Compile-time construction of the pipelined step function:
    `(params, stacked_batch, rng, loss_scale) -> (loss, grads)` for
    train=True (1F1B), or `... -> loss` for train=False (the fwd-only
    InferenceSchedule dataflow — no saved buffers, no backward).

    params_example/batch_example: concrete or ShapeDtypeStruct pytrees
    used only for shape inference (batch_example is ONE microbatch).
    split_batch: callable batch -> (inputs, labels).

    layout (StageFlatLayout): when given, `params` is the per-stage
    flat storage `{"flat": {dt: [S, F]}, "tied": tree}` sharded over
    the pipe axis — each shard slices ITS stage's params out of its
    local [1, F] view (the SPMD form of the reference building only
    local layers per process, ref module.py:197-249), and gradients
    come back in the same layout (flat [S, F] per dtype + replicated
    tied tree). Without it, params are a replicated full tree.

    num_virtual_stages > 1 compiles the INTERLEAVED 1F1B schedule
    (InterleavedTrainSchedule): the model is split into S·v chunks
    (`chunk_parts`, a parts list of length S·v+1) assigned round-robin
    (chunk q on stage q mod S), every tick's lax.switch dispatches on
    the GLOBAL chunk id, activations/cotangents ride a closed ppermute
    ring with depth-`channel_depth` FIFO delivery slots, and the
    fill/drain bubble shrinks from (S-1)/(m+S-1) stage-times toward
    (S-1)/(v·m+S-1).  v == 1 compiles the exact pre-interleaving
    program (chain permutes, single delivery slot)."""
    S = mesh.shape[PIPE_AXIS]
    M = mesh.shape[MODEL_AXIS]
    m = micro_batches
    v = int(num_virtual_stages)
    n_chunks = S * v
    tables = dict(build_clock_tables(m, S, train=train,
                                     num_virtual_stages=v))
    # kept (numpy) for the trace exporter: the compiled program's
    # EXACT per-tick (stage, microbatch, chunk) placement, stamped
    # with host dispatch windows by pipe/engine.py
    export_tables = dict(tables)
    C = int(tables.pop("channel_depth"))
    B = num_pipe_buffers(m, S, v) if train else 2 * v
    parts = list(module.parts) if chunk_parts is None else \
        list(chunk_parts)
    assert len(parts) == n_chunks + 1, (
        f"chunk parts length {len(parts)} != stages*virtual+1 = "
        f"{n_chunks + 1}")

    inputs_ex, labels_ex = split_batch(batch_example)

    def run_chunk(q, params, x, rng, deterministic):
        start, stop = parts[q], parts[q + 1]
        for idx in range(start, stop):
            kw = {}
            if idx in det_accepting:
                kw["deterministic"] = deterministic
            x = module.apply_layer(
                idx, module.layer_params(params, idx), x,
                rngs={"dropout": rng} if rng is not None else None, **kw)
        return x

    # -- param carrier: what the backward differentiates against ------
    # legacy: the (replicated) full tree itself.  flat layout: the
    # shard-local flat buffers + the tied tree; `params_of` rebuilds a
    # stage-sufficient {"layers", "tied"} dict from either.  A chunk's
    # layers live in its OWNER stage's segment (round-robin: stage
    # q mod S), which is exactly the local shard wherever the chunk's
    # switch branch actually executes.
    if layout is None:
        def carrier_of(params):
            return params

        def params_of(s, carrier):
            return carrier

        def local_grads(dcarrier):
            return dcarrier
    else:
        for dt in layout.F:
            assert layout.F[dt] % M == 0, (
                f"flat buffer width {layout.F[dt]} ({dt}) not divisible "
                f"by model={M}; build StageFlatLayout with "
                "align=model*data (the engine's setting — model alone "
                "satisfies this assert but leaves masters unshardable "
                "over data)")

        def carrier_of(params):
            # model>1 divides stage parameter STORAGE over the model
            # axis (each (pipe, model) shard holds F/model of its
            # stage); the stage compute gathers the full stage and runs
            # replicated within each TP group — the storage composition
            # of the reference's pipe×model grid (ref topology.py:
            # 246-249; true split-matmul TP needs TP-aware layers, which
            # the stacked-stage SPMD protocol provides).
            return ({dt: jax.lax.all_gather(
                        params["flat"][dt][0], MODEL_AXIS,
                        axis=0, tiled=True)
                     for dt in layout.F},
                    params.get("tied", {}))

        def params_of(s, carrier):
            flat_local, tied = carrier
            return {"layers": layout.unflatten_stage(s, flat_local),
                    "tied": tied}

        def local_grads(dcarrier):
            # the gathered-carrier cotangent is the FULL stage grad,
            # identical on every model shard (replicated compute, same
            # data shard) — each shard keeps only its own segment so the
            # accumulated grads come back already model-partitioned
            dflat, dtied = dcarrier
            i = jax.lax.axis_index(MODEL_AXIS)
            dflat = {dt: jax.lax.dynamic_slice_in_dim(
                         dflat[dt], i * (layout.F[dt] // M),
                         layout.F[dt] // M)
                     for dt in layout.F}
            return dflat, dtied

    # boundary avals: activation entering chunk q (q >= 1); shape
    # inference runs on the logical full tree regardless of storage
    full_example = params_example if layout is None else \
        jax.eval_shape(layout.unflatten, params_example)
    bnd = []
    x_aval = jax.eval_shape(lambda x: x, inputs_ex)
    for q in range(n_chunks):
        x_aval = jax.eval_shape(
            functools.partial(run_chunk, q, deterministic=True, rng=None),
            full_example, x_aval)
        bnd.append(x_aval)
    # bnd[q] = output of chunk q = input of chunk q+1
    in_avals = [jax.eval_shape(lambda x: x, inputs_ex)] + bnd[:-1]
    flat_sizes = [
        sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(a))
        for a in bnd[:-1]]
    A = max(flat_sizes) if flat_sizes else 1
    # transport dtype for the flat activation/cotangent buffers: the
    # boundaries' common float dtype (bf16 models move half the pipe
    # bytes); any non-float leaf (e.g. ids threaded through) forces f32
    bleaves = [l for a in bnd[:-1] for l in jax.tree_util.tree_leaves(a)]
    if bleaves and all(jnp.issubdtype(l.dtype, jnp.floating)
                       for l in bleaves):
        tdt = jnp.result_type(*[l.dtype for l in bleaves])
    else:
        tdt = jnp.float32

    def to_flat(tree):
        leaves = [l.reshape(-1).astype(tdt)
                  for l in jax.tree_util.tree_leaves(tree)]
        flat = jnp.concatenate(leaves) if leaves else jnp.zeros((0,), tdt)
        return jnp.pad(flat, (0, A - flat.shape[0]))

    def from_flat(flat, aval):
        out = []
        off = 0
        leaves, treedef = jax.tree_util.tree_flatten(aval)
        for l in leaves:
            n = int(np.prod(l.shape))
            out.append(flat[off:off + n].reshape(l.shape).astype(l.dtype))
            off += n
        return jax.tree_util.tree_unflatten(treedef, out)

    def chunk_input(q, flat, batch, mb):
        if q == 0:
            inputs, _ = split_batch(batch)
            return _microbatch(inputs, mb)
        return from_flat(flat, in_avals[q])

    def fwd_fn(q):
        def fn(params, act_in, batch, mb, rng, loss_scale):
            x = chunk_input(q, act_in, batch, mb)
            r = jax.random.fold_in(jax.random.fold_in(rng, mb), q)
            y = run_chunk(q, params_of(q % S, carrier_of(params)), x, r,
                          deterministic=not train)
            if q == n_chunks - 1:
                _, labels = split_batch(batch)
                loss = module.loss_fn(y, _microbatch(labels, mb)) \
                    if module.loss_fn is not None else y
                return jnp.zeros((A,), tdt), \
                    loss.astype(jnp.float32)
            return to_flat(y), jnp.float32(0.0)
        return fn

    def _grads_f32(dcarrier):
        return jax.tree_util.tree_map(
            lambda g_: g_.astype(jnp.float32), dcarrier)

    def bwd_fn(q):
        def fn(params, x_saved_flat, grad_in, batch, mb, rng,
               loss_scale):
            x = chunk_input(q, x_saved_flat, batch, mb)
            r = jax.random.fold_in(jax.random.fold_in(rng, mb), q)
            carrier = carrier_of(params)

            if q == n_chunks - 1:
                def g(c, xx):
                    y = run_chunk(q, params_of(q % S, c), xx, r,
                                  deterministic=False)
                    _, labels = split_batch(batch)
                    loss = module.loss_fn(y, _microbatch(labels, mb)) \
                        if module.loss_fn is not None else y
                    return loss.astype(jnp.float32)
                cot = loss_scale / m
            else:
                def g(c, xx):
                    return run_chunk(q, params_of(q % S, c), xx, r,
                                     deterministic=False)
                cot = from_flat(grad_in, bnd[q])

            if q == 0:
                _, vjp = jax.vjp(lambda c: g(c, x), carrier)
                (dcarrier,) = vjp(cot)
                dx_flat = jnp.zeros((A,), tdt)
            else:
                _, vjp = jax.vjp(g, carrier, x)
                dcarrier, dx = vjp(cot)
                dx_flat = to_flat(dx)
            return dx_flat, _grads_f32(dcarrier)
        return fn

    fwd_fns = [fwd_fn(q) for q in range(n_chunks)]
    bwd_fns = [bwd_fn(q) for q in range(n_chunks)] if train else []

    # acts travel +1, cotangents -1; interleaving closes the ring (the
    # last stage's non-final chunks feed stage 0)
    if v > 1:
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [((i + 1) % S, i) for i in range(S)]
    else:
        fwd_perm = [(i, i + 1) for i in range(S - 1)]
        bwd_perm = [(i + 1, i) for i in range(S - 1)]

    rows = {k: jnp.asarray(val) for k, val in tables.items()
            if k != "num_ticks"}

    def _ring_write(ring, value, slot):
        upd = jax.lax.dynamic_update_index_in_dim(
            ring, value, jnp.maximum(slot, 0), 0)
        return jnp.where(slot >= 0, upd, ring)

    def _ring_read(ring, slot):
        return jax.lax.dynamic_index_in_dim(
            ring, jnp.maximum(slot, 0), 0, keepdims=False)

    def local_step(params, stacked_batch, rng, loss_scale):
        s = jax.lax.axis_index(PIPE_AXIS)
        dp = mesh.shape[DATA_AXIS]
        # decorrelate dropout across data shards (chunk folding happens
        # per-branch in fwd_fn/bwd_fn; fwd and recompute share the key)
        rng = jax.random.fold_in(rng, jax.lax.axis_index(DATA_AXIS))

        if not train:
            # minimal carry: no grads tree, no backward registers or
            # saved-input buffers, no backward ppermute per tick
            def tick_eval(carry, row):
                act_ring, fwd_out, loss_sum = carry
                perm_act = jax.lax.ppermute(fwd_out, PIPE_AXIS, fwd_perm)
                act_ring = _ring_write(act_ring, perm_act,
                                       row["deliver_act_slot"][s])
                my_fwd = row["fwd_mb"][s]
                my_chunk = row["fwd_chunk"][s]
                x_in = _ring_read(act_ring, row["recv_act_slot"][s])

                def do_fwd(_):
                    return jax.lax.switch(
                        my_chunk, fwd_fns, params, x_in, stacked_batch,
                        my_fwd, rng, loss_scale)

                def no_fwd(_):
                    return fwd_out, jnp.float32(0.0)

                new_fwd_out, loss_inc = jax.lax.cond(
                    my_fwd >= 0, do_fwd, no_fwd, None)
                # only a sending op may occupy the send register (the
                # loss chunk's output must not clobber an undelivered
                # value riding the same register)
                fwd_next = jnp.where(row["sent_act"][s], new_fwd_out,
                                     fwd_out)
                return (act_ring, fwd_next, loss_sum + loss_inc), None

            carry, _ = jax.lax.scan(
                tick_eval,
                (jnp.zeros((C, A), tdt),
                 jnp.zeros((A,), tdt), jnp.float32(0.0)),
                rows)
            loss = jax.lax.psum(carry[2], PIPE_AXIS) / m
            if dp > 1:
                loss = jax.lax.pmean(loss, DATA_AXIS)
            return loss

        # grads carry mirrors the ACCUMULATED layout: full tree (legacy)
        # or (model-sliced flat buffers, tied tree) under the flat
        # layout (shapes only — the gather/slice chain is dead code XLA
        # eliminates)
        zeros_grads = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32),
            local_grads(carrier_of(params)))

        def tick(carry, row):
            (act_ring, grad_ring, fwd_out, grad_out, bufs, loss_sum,
             grads_acc) = carry
            # communication phase: deliver last tick's sends into their
            # FIFO ring slots
            perm_act = jax.lax.ppermute(fwd_out, PIPE_AXIS, fwd_perm)
            perm_grad = jax.lax.ppermute(grad_out, PIPE_AXIS, bwd_perm)
            act_ring = _ring_write(act_ring, perm_act,
                                   row["deliver_act_slot"][s])
            grad_ring = _ring_write(grad_ring, perm_grad,
                                    row["deliver_grad_slot"][s])

            my_fwd = row["fwd_mb"][s]
            my_fbuf = row["fwd_buf"][s]
            my_fchunk = row["fwd_chunk"][s]
            my_bwd = row["bwd_mb"][s]
            my_bbuf = row["bwd_buf"][s]
            my_bchunk = row["bwd_chunk"][s]
            x_in = _ring_read(act_ring, row["recv_act_slot"][s])

            def do_fwd(_):
                out, loss = jax.lax.switch(
                    my_fchunk, fwd_fns, params, x_in, stacked_batch,
                    my_fwd, rng, loss_scale)
                return out, loss

            def no_fwd(_):
                return fwd_out, jnp.float32(0.0)

            new_fwd_out, loss_inc = jax.lax.cond(my_fwd >= 0, do_fwd,
                                                 no_fwd, None)
            loss_sum = loss_sum + loss_inc
            # only a sending op occupies the send register — an op with
            # no SendActivation (the loss chunk) must not clobber a
            # value still riding toward its delivery
            fwd_next = jnp.where(row["sent_act"][s], new_fwd_out,
                                 fwd_out)
            # save the chunk-INPUT activation for backward recompute
            bufs = jnp.where(
                my_fwd >= 0,
                jax.lax.dynamic_update_index_in_dim(
                    bufs, x_in, my_fbuf, 0),
                bufs)

            def do_bwd(_):
                x_saved = jax.lax.dynamic_index_in_dim(
                    bufs, my_bbuf, 0, keepdims=False)
                g_in = _ring_read(grad_ring, row["recv_grad_slot"][s])
                dx, dparams = jax.lax.switch(
                    my_bchunk, bwd_fns, params, x_saved, g_in,
                    stacked_batch, my_bwd, rng, loss_scale)
                return dx, local_grads(dparams)

            def no_bwd(_):
                return grad_out, zeros_grads

            new_grad_out, dparams = jax.lax.cond(my_bwd >= 0, do_bwd,
                                                 no_bwd, None)
            grad_next = jnp.where(row["sent_grad"][s], new_grad_out,
                                  grad_out)
            grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc,
                                               dparams)
            return (act_ring, grad_ring, fwd_next, grad_next,
                    bufs, loss_sum, grads_acc), None

        init = (jnp.zeros((C, A), tdt),  # act delivery ring
                jnp.zeros((C, A), tdt),  # grad delivery ring
                jnp.zeros((A,), tdt),    # fwd_out (send register)
                jnp.zeros((A,), tdt),    # grad_out (send register)
                jnp.zeros((B, A), tdt),  # saved chunk inputs
                jnp.float32(0.0), zeros_grads)
        carry, _ = jax.lax.scan(tick, init, rows)
        loss_sum = carry[5]
        loss = jax.lax.psum(loss_sum, PIPE_AXIS) / m
        if dp > 1:
            loss = jax.lax.pmean(loss, DATA_AXIS)
        if layout is None:
            # ReduceGrads + ReduceTiedGrads: stage-disjoint leaves psum
            # to their single producer's value; tied leaves SUM across
            # stages
            grads = jax.tree_util.tree_map(
                lambda g_: jax.lax.psum(g_, PIPE_AXIS), carry[6])
            if dp > 1:
                grads = jax.tree_util.tree_map(
                    lambda g_: jax.lax.pmean(g_, DATA_AXIS), grads)
        else:
            # flat grads STAY stage-partitioned (each shard produced
            # only its stage's segment — no psum, the stacked [S, F]
            # output is the partitioned gradient store); tied grads SUM
            # across their user stages (ReduceTiedGrads)
            flat_g, tied_g = carry[6]
            tied_g = jax.tree_util.tree_map(
                lambda g_: jax.lax.psum(g_, PIPE_AXIS), tied_g)
            if dp > 1:
                flat_g = jax.tree_util.tree_map(
                    lambda g_: jax.lax.pmean(g_, DATA_AXIS), flat_g)
                tied_g = jax.tree_util.tree_map(
                    lambda g_: jax.lax.pmean(g_, DATA_AXIS), tied_g)
            grads = {"flat": {dt: flat_g[dt][None] for dt in layout.F},
                     "tied": tied_g}
        return loss, grads

    if layout is None:
        params_spec = P()
        grads_out_spec = P()
    else:
        # dim 1 over the model axis (size-1 model: identical to the
        # pipe-only spec); each (pipe, model) shard enters with its
        # [1, F/model] slice and leaves its own grad segment
        params_spec = {"flat": {dt: P(PIPE_AXIS, MODEL_AXIS)
                                for dt in layout.F},
                       "tied": P()}
        grads_out_spec = {"flat": {dt: P(PIPE_AXIS, MODEL_AXIS)
                                   for dt in layout.F},
                          "tied": P()}

    def step(params, stacked_batch, rng, loss_scale):
        b_specs = stacked_batch_pspecs(stacked_batch)
        return shard_map(
            local_step, mesh=mesh,
            in_specs=(params_spec, b_specs, P(), P()),
            out_specs=(P(), grads_out_spec) if train else P(),
            check_vma=False)(params, stacked_batch, rng, loss_scale)

    # forensics: the schedule this program executes (trace_export lays
    # these ticks over each dispatch's wall window)
    step.clock_tables = export_tables
    step.pipe_meta = {"stages": S, "micro_batches": m,
                      "num_virtual_stages": v, "train": train}
    # memory-ledger accounting of the executor's persistent per-stage
    # carry: saved-input recompute buffers [B, A] + the two depth-C
    # delivery rings + the fwd/bwd send registers, all in the flat
    # transport dtype. Per DEVICE (each pipe shard carries its own).
    _itemsize = jnp.dtype(tdt).itemsize
    step.buffer_meta = {
        "saved_input_buffers": int(B),
        "channel_depth": int(C),
        "flat_width": int(A),
        "transport_dtype": str(jnp.dtype(tdt).name),
        "bytes_per_stage": int(
            (B + 2 * C + 2 if train else C + 1) * A * _itemsize),
    }
    return step
