"""Compiled 1F1B executor for heterogeneous PipelineModules.

Counterpart of the reference's schedule interpreter
(`deepspeed/runtime/pipe/engine.py:1135-1161`: `_exec_schedule` walking
`_INSTRUCTION_MAP` with blocking p2p). The TPU-native form compiles the
SAME TrainSchedule instruction streams into one SPMD program:

  1. `build_clock_tables` interprets every stage's TrainSchedule stream
     with a FIFO one-slot channel model (send at tick t is receivable
     from tick t+1 — the compiled analogue of blocking p2p) into
     globally clock-aligned numpy tables: which stage runs which
     microbatch's forward/backward at every tick.
  2. `build_pipeline_step` lowers those tables to a `lax.scan` over
     ticks inside `shard_map` over the `pipe` mesh axis. Each pipe
     shard executes ITS stage's work via `lax.switch` (per-device
     divergent control flow — heterogeneous layers and activation
     shapes are handled by padding inter-stage activations to one flat
     f32 buffer), activations ride `ppermute(+1)` and cotangents
     `ppermute(-1)`.

Backward uses per-(microbatch, stage) recompute from the saved stage
INPUT activation (`jax.vjp` inside the backward branch), so the live
activation memory per stage is the schedule's buffer bound —
`TrainSchedule.num_pipe_buffers() = min(stages - stage + 1, m)` saved
inputs (ref `schedule.py:243-247`) — instead of GPipe's `m` full
per-layer residual sets. Stages genuinely overlap: at any steady-state
tick every pipe shard is executing a different microbatch.

Tied layers (TiedLayerSpec) appear in several stages; each shard
contributes its stage's grads and the final `psum` over the pipe axis
IS ReduceTiedGrads (ref `module.py:405-409`).

MEMORY: stage-exclusive parameters are stored in the per-stage flat
layout (`pipe/flat_params.py`) — one `[S, F]` buffer per dtype sharded
over the pipe axis, so each shard holds only its stage's params, grads
and optimizer state (the SPMD form of the reference building only
local layers per process, ref `module.py:197-249`); tied leaves stay
replicated with psum'd grads. Together with the schedule's
`num_pipe_buffers()` activation bound, pipe>1 divides both parameter
and activation memory by the stage count.

MODEL-AXIS COMPOSITION: with model>1 the [S, F] buffers shard over the
model axis too (each (pipe, model) shard stores F/model of its stage,
masters/moments compose (model, data) on top), the stage compute
all-gathers its stage over the model axis per tick and keeps only its
own grad segment — parameter/optimizer memory divides by pipe*model
(*data for masters), the storage composition of the reference's
pipe×model grid (`topology.py:246-249`). The gather is the ZeRO-3
pattern riding the shortest ICI hops (model is the innermost mesh
axis); split-matmul tensor parallelism inside a stage needs TP-aware
layers, which the homogeneous stacked-stage SPMD protocol provides.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from deepspeed_tpu.runtime.compat import shard_map
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.runtime.mesh import (DATA_AXIS, MODEL_AXIS, PIPE_AXIS,
                                        stacked_batch_pspecs)
from deepspeed_tpu.runtime.pipe.schedule import (
    TrainSchedule, ForwardPass, BackwardPass, SendActivation,
    RecvActivation, SendGrad, RecvGrad, LoadMicroBatch)


# ----------------------------------------------------------------------
# schedule -> clock tables
# ----------------------------------------------------------------------
def _inference_streams(m, S):
    """Canonical fwd-only streams with InferenceSchedule's dataflow
    (`schedule.py:86-127`). The literal InferenceSchedule emits
    SendActivation one step AFTER the producing ForwardPass (a
    host-runtime buffering detail); the compiled executor's send
    register holds exactly one tick, so the send is folded into the
    producing step — same dependency structure, same 2-buffer bound."""
    streams = []
    for s in range(S):
        steps = []
        for mb in range(m):
            cmds = []
            if s > 0:
                cmds.append(RecvActivation(mb % 2))
            if s == 0 or s == S - 1:
                cmds.append(LoadMicroBatch(mb % 2))
            cmds.append(ForwardPass(mb % 2))
            if s < S - 1:
                cmds.append(SendActivation(mb % 2))
            steps.append(cmds)
        streams.append(steps)
    return streams


def build_clock_tables(micro_batches, stages, train=True):
    """Align the per-stage schedule streams on a global clock
    (TrainSchedule, or the fwd-only InferenceSchedule dataflow when
    train=False).

    Each stage executes at most one schedule step per tick; a step is
    eligible when every RecvActivation/RecvGrad it contains pairs with
    a Send* completed at an EARLIER tick (k-th recv on a channel pairs
    with the k-th send — FIFO), and any Send* it contains has a free
    channel slot. Returns int/bool arrays indexed [tick, stage]."""
    m, S = micro_batches, stages
    if train:
        streams = [list(TrainSchedule(m, S, s).steps()) for s in range(S)]
    else:
        streams = _inference_streams(m, S)

    fwd_mb = []
    fwd_buf = []
    bwd_mb = []
    bwd_buf = []
    sent_act = []
    sent_grad = []

    send_act_ticks = [[] for _ in range(S)]
    recv_act_count = [0] * S
    send_grad_ticks = [[] for _ in range(S)]
    recv_grad_count = [0] * S
    fwd_count = [0] * S
    bwd_count = [0] * S
    ptr = [0] * S
    t = 0
    max_ticks = 4 * (m + S) + 8
    while any(ptr[s] < len(streams[s]) for s in range(S)):
        assert t < max_ticks, "clock alignment did not converge"
        f_row = [-1] * S
        fb_row = [0] * S
        b_row = [-1] * S
        bb_row = [0] * S
        sa_row = [False] * S
        sg_row = [False] * S
        snap_sa = [len(x) for x in send_act_ticks]
        snap_sg = [len(x) for x in send_grad_ticks]
        snap_ra = list(recv_act_count)
        snap_rg = list(recv_grad_count)
        for s in range(S):
            if ptr[s] >= len(streams[s]):
                continue
            cmds = streams[s][ptr[s]]
            ok = True
            for c in cmds:
                if isinstance(c, RecvActivation):
                    k = recv_act_count[s]
                    ok &= k < snap_sa[s - 1]
                elif isinstance(c, RecvGrad):
                    k = recv_grad_count[s]
                    ok &= k < snap_sg[s + 1]
                elif isinstance(c, SendActivation):
                    # one-slot channel: previous send must be consumed
                    ok &= len(send_act_ticks[s]) <= snap_ra[s + 1]
                elif isinstance(c, SendGrad):
                    ok &= len(send_grad_ticks[s]) <= snap_rg[s - 1]
            if not ok:
                continue
            for c in cmds:
                if isinstance(c, RecvActivation):
                    recv_act_count[s] += 1
                elif isinstance(c, RecvGrad):
                    recv_grad_count[s] += 1
                elif isinstance(c, SendActivation):
                    send_act_ticks[s].append(t)
                    sa_row[s] = True
                elif isinstance(c, SendGrad):
                    send_grad_ticks[s].append(t)
                    sg_row[s] = True
                elif isinstance(c, ForwardPass):
                    f_row[s] = fwd_count[s]
                    fb_row[s] = c.buffer_id
                    fwd_count[s] += 1
                elif isinstance(c, BackwardPass):
                    b_row[s] = bwd_count[s]
                    bb_row[s] = c.buffer_id
                    bwd_count[s] += 1
            ptr[s] += 1
        fwd_mb.append(f_row)
        fwd_buf.append(fb_row)
        bwd_mb.append(b_row)
        bwd_buf.append(bb_row)
        sent_act.append(sa_row)
        sent_grad.append(sg_row)
        t += 1

    T = t
    sent_act = np.asarray(sent_act, bool)
    sent_grad = np.asarray(sent_grad, bool)
    # delivery at tick t = what the neighbor sent at tick t-1
    deliver_act = np.zeros((T, S), bool)
    deliver_act[1:, 1:] = sent_act[:-1, :-1]
    deliver_grad = np.zeros((T, S), bool)
    deliver_grad[1:, :-1] = sent_grad[:-1, 1:]
    return {
        "fwd_mb": np.asarray(fwd_mb, np.int32),
        "fwd_buf": np.asarray(fwd_buf, np.int32),
        "bwd_mb": np.asarray(bwd_mb, np.int32),
        "bwd_buf": np.asarray(bwd_buf, np.int32),
        "deliver_act": deliver_act,
        "deliver_grad": deliver_grad,
        "num_ticks": T,
    }


def num_pipe_buffers(micro_batches, stages):
    """Global buffer-array bound: the worst stage's
    TrainSchedule.num_pipe_buffers() (stage 0: min(stages+1, m))."""
    return max(TrainSchedule(micro_batches, stages, s).num_pipe_buffers()
               for s in range(stages))


# ----------------------------------------------------------------------
# stage function construction
# ----------------------------------------------------------------------
def _microbatch(tree, mb):
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_index_in_dim(a, mb, 0, keepdims=False),
        tree)


def build_pipeline_step(module, mesh, micro_batches, params_example,
                        batch_example, split_batch, det_accepting,
                        train=True, layout=None):
    """Compile-time construction of the pipelined step function:
    `(params, stacked_batch, rng, loss_scale) -> (loss, grads)` for
    train=True (1F1B), or `... -> loss` for train=False (the fwd-only
    InferenceSchedule dataflow — no saved buffers, no backward).

    params_example/batch_example: concrete or ShapeDtypeStruct pytrees
    used only for shape inference (batch_example is ONE microbatch).
    split_batch: callable batch -> (inputs, labels).

    layout (StageFlatLayout): when given, `params` is the per-stage
    flat storage `{"flat": {dt: [S, F]}, "tied": tree}` sharded over
    the pipe axis — each shard slices ITS stage's params out of its
    local [1, F] view (the SPMD form of the reference building only
    local layers per process, ref module.py:197-249), and gradients
    come back in the same layout (flat [S, F] per dtype + replicated
    tied tree). Without it, params are a replicated full tree."""
    S = mesh.shape[PIPE_AXIS]
    M = mesh.shape[MODEL_AXIS]
    m = micro_batches
    tables = build_clock_tables(m, S, train=train)
    B = num_pipe_buffers(m, S) if train else 1
    parts = module.parts

    inputs_ex, labels_ex = split_batch(batch_example)

    def run_stage(s, params, x, rng, deterministic):
        start, stop = parts[s], parts[s + 1]
        for idx in range(start, stop):
            kw = {}
            if idx in det_accepting:
                kw["deterministic"] = deterministic
            x = module.apply_layer(
                idx, module.layer_params(params, idx), x,
                rngs={"dropout": rng} if rng is not None else None, **kw)
        return x

    # -- param carrier: what the backward differentiates against ------
    # legacy: the (replicated) full tree itself.  flat layout: the
    # shard-local flat buffers + the tied tree; `params_of` rebuilds a
    # stage-sufficient {"layers", "tied"} dict from either.
    if layout is None:
        def carrier_of(params):
            return params

        def params_of(s, carrier):
            return carrier

        def local_grads(dcarrier):
            return dcarrier
    else:
        for dt in layout.F:
            assert layout.F[dt] % M == 0, (
                f"flat buffer width {layout.F[dt]} ({dt}) not divisible "
                f"by model={M}; build StageFlatLayout with "
                "align=model*data (the engine's setting — model alone "
                "satisfies this assert but leaves masters unshardable "
                "over data)")

        def carrier_of(params):
            # model>1 divides stage parameter STORAGE over the model
            # axis (each (pipe, model) shard holds F/model of its
            # stage); the stage compute gathers the full stage and runs
            # replicated within each TP group — the storage composition
            # of the reference's pipe×model grid (ref topology.py:
            # 246-249; true split-matmul TP needs TP-aware layers, which
            # the stacked-stage SPMD protocol provides).
            return ({dt: jax.lax.all_gather(
                        params["flat"][dt][0], MODEL_AXIS,
                        axis=0, tiled=True)
                     for dt in layout.F},
                    params.get("tied", {}))

        def params_of(s, carrier):
            flat_local, tied = carrier
            return {"layers": layout.unflatten_stage(s, flat_local),
                    "tied": tied}

        def local_grads(dcarrier):
            # the gathered-carrier cotangent is the FULL stage grad,
            # identical on every model shard (replicated compute, same
            # data shard) — each shard keeps only its own segment so the
            # accumulated grads come back already model-partitioned
            dflat, dtied = dcarrier
            i = jax.lax.axis_index(MODEL_AXIS)
            dflat = {dt: jax.lax.dynamic_slice_in_dim(
                         dflat[dt], i * (layout.F[dt] // M),
                         layout.F[dt] // M)
                     for dt in layout.F}
            return dflat, dtied

    # boundary avals: activation entering stage s (s >= 1); shape
    # inference runs on the logical full tree regardless of storage
    full_example = params_example if layout is None else \
        jax.eval_shape(layout.unflatten, params_example)
    bnd = []
    x_aval = jax.eval_shape(lambda x: x, inputs_ex)
    for s in range(S):
        x_aval = jax.eval_shape(
            functools.partial(run_stage, s, deterministic=True, rng=None),
            full_example, x_aval)
        bnd.append(x_aval)
    # bnd[s] = output of stage s = input of stage s+1
    in_avals = [jax.eval_shape(lambda x: x, inputs_ex)] + bnd[:-1]
    flat_sizes = [
        sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(a))
        for a in bnd[:-1]]
    A = max(flat_sizes) if flat_sizes else 1
    # transport dtype for the flat activation/cotangent buffers: the
    # boundaries' common float dtype (bf16 models move half the pipe
    # bytes); any non-float leaf (e.g. ids threaded through) forces f32
    bleaves = [l for a in bnd[:-1] for l in jax.tree_util.tree_leaves(a)]
    if bleaves and all(jnp.issubdtype(l.dtype, jnp.floating)
                       for l in bleaves):
        tdt = jnp.result_type(*[l.dtype for l in bleaves])
    else:
        tdt = jnp.float32

    def to_flat(tree):
        leaves = [l.reshape(-1).astype(tdt)
                  for l in jax.tree_util.tree_leaves(tree)]
        flat = jnp.concatenate(leaves) if leaves else jnp.zeros((0,), tdt)
        return jnp.pad(flat, (0, A - flat.shape[0]))

    def from_flat(flat, aval):
        out = []
        off = 0
        leaves, treedef = jax.tree_util.tree_flatten(aval)
        for l in leaves:
            n = int(np.prod(l.shape))
            out.append(flat[off:off + n].reshape(l.shape).astype(l.dtype))
            off += n
        return jax.tree_util.tree_unflatten(treedef, out)

    def stage_input(s, flat, batch, mb):
        if s == 0:
            inputs, _ = split_batch(batch)
            return _microbatch(inputs, mb)
        return from_flat(flat, in_avals[s])

    def fwd_fn(s):
        def fn(params, act_hold, batch, mb, rng, loss_scale):
            x = stage_input(s, act_hold, batch, mb)
            r = jax.random.fold_in(jax.random.fold_in(rng, mb), s)
            y = run_stage(s, params_of(s, carrier_of(params)), x, r,
                          deterministic=not train)
            if s == S - 1:
                _, labels = split_batch(batch)
                loss = module.loss_fn(y, _microbatch(labels, mb)) \
                    if module.loss_fn is not None else y
                return jnp.zeros((A,), tdt), \
                    loss.astype(jnp.float32)
            return to_flat(y), jnp.float32(0.0)
        return fn

    def _grads_f32(dcarrier):
        return jax.tree_util.tree_map(
            lambda g_: g_.astype(jnp.float32), dcarrier)

    def bwd_fn(s):
        def fn(params, x_saved_flat, grad_hold, batch, mb, rng,
               loss_scale):
            x = stage_input(s, x_saved_flat, batch, mb)
            r = jax.random.fold_in(jax.random.fold_in(rng, mb), s)
            carrier = carrier_of(params)

            if s == S - 1:
                def g(c, xx):
                    y = run_stage(s, params_of(s, c), xx, r,
                                  deterministic=False)
                    _, labels = split_batch(batch)
                    loss = module.loss_fn(y, _microbatch(labels, mb)) \
                        if module.loss_fn is not None else y
                    return loss.astype(jnp.float32)
                cot = loss_scale / m
            else:
                def g(c, xx):
                    return run_stage(s, params_of(s, c), xx, r,
                                     deterministic=False)
                cot = from_flat(grad_hold, bnd[s])

            if s == 0:
                _, vjp = jax.vjp(lambda c: g(c, x), carrier)
                (dcarrier,) = vjp(cot)
                dx_flat = jnp.zeros((A,), tdt)
            else:
                _, vjp = jax.vjp(g, carrier, x)
                dcarrier, dx = vjp(cot)
                dx_flat = to_flat(dx)
            return dx_flat, _grads_f32(dcarrier)
        return fn

    fwd_fns = [fwd_fn(s) for s in range(S)]
    bwd_fns = [bwd_fn(s) for s in range(S)] if train else []

    fwd_perm = [(i, i + 1) for i in range(S - 1)]
    bwd_perm = [(i + 1, i) for i in range(S - 1)]

    rows = {k: jnp.asarray(v) for k, v in tables.items()
            if k != "num_ticks"}

    def local_step(params, stacked_batch, rng, loss_scale):
        s = jax.lax.axis_index(PIPE_AXIS)
        dp = mesh.shape[DATA_AXIS]
        # decorrelate dropout across data shards (stage folding happens
        # per-branch in fwd_fn/bwd_fn; fwd and recompute share the key)
        rng = jax.random.fold_in(rng, jax.lax.axis_index(DATA_AXIS))

        if not train:
            # minimal carry: no grads tree, no backward registers or
            # saved-input buffers, no backward ppermute per tick
            def tick_eval(carry, row):
                act_hold, fwd_out, loss_sum = carry
                perm_act = jax.lax.ppermute(fwd_out, PIPE_AXIS, fwd_perm)
                act_hold = jnp.where(row["deliver_act"][s], perm_act,
                                     act_hold)
                my_fwd = row["fwd_mb"][s]

                def do_fwd(_):
                    return jax.lax.switch(
                        s, fwd_fns, params, act_hold, stacked_batch,
                        my_fwd, rng, loss_scale)

                def no_fwd(_):
                    return fwd_out, jnp.float32(0.0)

                new_fwd_out, loss_inc = jax.lax.cond(
                    my_fwd >= 0, do_fwd, no_fwd, None)
                return (act_hold, new_fwd_out, loss_sum + loss_inc), None

            carry, _ = jax.lax.scan(
                tick_eval,
                (jnp.zeros((A,), tdt),
                 jnp.zeros((A,), tdt), jnp.float32(0.0)),
                rows)
            loss = jax.lax.psum(carry[2], PIPE_AXIS) / m
            if dp > 1:
                loss = jax.lax.pmean(loss, DATA_AXIS)
            return loss

        # grads carry mirrors the ACCUMULATED layout: full tree (legacy)
        # or (model-sliced flat buffers, tied tree) under the flat
        # layout (shapes only — the gather/slice chain is dead code XLA
        # eliminates)
        zeros_grads = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32),
            local_grads(carrier_of(params)))

        def tick(carry, row):
            (act_hold, grad_hold, fwd_out, grad_out, bufs, loss_sum,
             grads_acc) = carry
            # communication phase: deliver last tick's sends
            perm_act = jax.lax.ppermute(fwd_out, PIPE_AXIS, fwd_perm)
            perm_grad = jax.lax.ppermute(grad_out, PIPE_AXIS, bwd_perm)
            act_hold = jnp.where(row["deliver_act"][s], perm_act,
                                 act_hold)
            grad_hold = jnp.where(row["deliver_grad"][s], perm_grad,
                                  grad_hold)

            my_fwd = row["fwd_mb"][s]
            my_fbuf = row["fwd_buf"][s]
            my_bwd = row["bwd_mb"][s]
            my_bbuf = row["bwd_buf"][s]

            def do_fwd(_):
                out, loss = jax.lax.switch(
                    s, fwd_fns, params, act_hold, stacked_batch,
                    my_fwd, rng, loss_scale)
                return out, loss

            def no_fwd(_):
                return fwd_out, jnp.float32(0.0)

            new_fwd_out, loss_inc = jax.lax.cond(my_fwd >= 0, do_fwd,
                                                 no_fwd, None)
            loss_sum = loss_sum + loss_inc
            # save the stage-INPUT activation for backward recompute
            bufs = jnp.where(
                my_fwd >= 0,
                jax.lax.dynamic_update_index_in_dim(
                    bufs, act_hold, my_fbuf, 0),
                bufs)

            def do_bwd(_):
                x_saved = jax.lax.dynamic_index_in_dim(
                    bufs, my_bbuf, 0, keepdims=False)
                dx, dparams = jax.lax.switch(
                    s, bwd_fns, params, x_saved, grad_hold,
                    stacked_batch, my_bwd, rng, loss_scale)
                return dx, local_grads(dparams)

            def no_bwd(_):
                return grad_out, zeros_grads

            new_grad_out, dparams = jax.lax.cond(my_bwd >= 0, do_bwd,
                                                 no_bwd, None)
            grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc,
                                               dparams)
            return (act_hold, grad_hold, new_fwd_out, new_grad_out,
                    bufs, loss_sum, grads_acc), None

        init = (jnp.zeros((A,), tdt),    # act_hold
                jnp.zeros((A,), tdt),    # grad_hold
                jnp.zeros((A,), tdt),    # fwd_out
                jnp.zeros((A,), tdt),    # grad_out
                jnp.zeros((B, A), tdt),  # saved stage inputs
                jnp.float32(0.0), zeros_grads)
        carry, _ = jax.lax.scan(tick, init, rows)
        loss_sum = carry[5]
        loss = jax.lax.psum(loss_sum, PIPE_AXIS) / m
        if dp > 1:
            loss = jax.lax.pmean(loss, DATA_AXIS)
        if layout is None:
            # ReduceGrads + ReduceTiedGrads: stage-disjoint leaves psum
            # to their single producer's value; tied leaves SUM across
            # stages
            grads = jax.tree_util.tree_map(
                lambda g_: jax.lax.psum(g_, PIPE_AXIS), carry[6])
            if dp > 1:
                grads = jax.tree_util.tree_map(
                    lambda g_: jax.lax.pmean(g_, DATA_AXIS), grads)
        else:
            # flat grads STAY stage-partitioned (each shard produced
            # only its stage's segment — no psum, the stacked [S, F]
            # output is the partitioned gradient store); tied grads SUM
            # across their user stages (ReduceTiedGrads)
            flat_g, tied_g = carry[6]
            tied_g = jax.tree_util.tree_map(
                lambda g_: jax.lax.psum(g_, PIPE_AXIS), tied_g)
            if dp > 1:
                flat_g = jax.tree_util.tree_map(
                    lambda g_: jax.lax.pmean(g_, DATA_AXIS), flat_g)
                tied_g = jax.tree_util.tree_map(
                    lambda g_: jax.lax.pmean(g_, DATA_AXIS), tied_g)
            grads = {"flat": {dt: flat_g[dt][None] for dt in layout.F},
                     "tied": tied_g}
        return loss, grads

    if layout is None:
        params_spec = P()
        grads_out_spec = P()
    else:
        # dim 1 over the model axis (size-1 model: identical to the
        # pipe-only spec); each (pipe, model) shard enters with its
        # [1, F/model] slice and leaves its own grad segment
        params_spec = {"flat": {dt: P(PIPE_AXIS, MODEL_AXIS)
                                for dt in layout.F},
                       "tied": P()}
        grads_out_spec = {"flat": {dt: P(PIPE_AXIS, MODEL_AXIS)
                                   for dt in layout.F},
                          "tied": P()}

    def step(params, stacked_batch, rng, loss_scale):
        b_specs = stacked_batch_pspecs(stacked_batch)
        return shard_map(
            local_step, mesh=mesh,
            in_specs=(params_spec, b_specs, P(), P()),
            out_specs=(P(), grads_out_spec) if train else P(),
            check_vma=False)(params, stacked_batch, rng, loss_scale)

    return step
