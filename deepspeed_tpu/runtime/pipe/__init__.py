from deepspeed_tpu.runtime.pipe.module import (PipelineModule, LayerSpec,
                                               TiedLayerSpec)
from deepspeed_tpu.runtime.pipe.topology import (
    ProcessTopology, PipeDataParallelTopology, PipeModelDataParallelTopology,
    PipelineParallelGrid, topology_from_mesh)
