"""Pipeline model description: LayerSpec / TiedLayerSpec / PipelineModule.

Parity with `deepspeed/runtime/pipe/module.py:23,71,85`, TPU-native: a
PipelineModule is a *description* (list of layer specs + a partitioning)
that the PipelineEngine lowers to an SPMD program — stage-stacked
parameters sharded over the `pipe` mesh axis, microbatch activations
rotated with `ppermute`. Deferred construction (LayerSpec) is kept: on a
pod only the stage owner ever materializes a layer's params (memory
parity with ref `module.py:197-249`), which under GSPMD means: params are
created host-side per layer and device_put with a pipe-axis sharding.

Partition methods (ref `module.py:348-403`): 'parameters' (balance param
counts), 'uniform' (balance layer counts), 'type:regex' (balance layers
whose class name matches the regex).
"""

import re
from typing import Any, Callable, List, Optional

import jax
import numpy as np

from deepspeed_tpu.runtime.utils import (partition_balanced,
                                         partition_uniform)
from deepspeed_tpu.utils.logging import logger


class LayerSpec:
    """Deferred layer construction (ref `module.py:23-68`).

    typename: a flax Module class or any callable factory; building is
    delayed until `build()` so a 100-layer model doesn't materialize
    anything until stages are assigned.
    """

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not callable(typename):
            raise RuntimeError("LayerSpec typename must be callable "
                               "(flax Module class or factory)")

    def __repr__(self):
        from deepspeed_tpu.runtime.utils import call_to_str
        return call_to_str(getattr(self.typename, "__name__",
                                   str(self.typename)),
                           *self.module_args, **self.module_kwargs)

    def build(self, log=False):
        if log:
            logger.info(f"building {repr(self)}")
        return self.typename(*self.module_args, **self.module_kwargs)


class TiedLayerSpec(LayerSpec):
    """Weight tying across stages (ref `module.py:71-82`): layers sharing
    `key` share one parameter tree; the engine keeps tied params replicated
    over the pipe axis and sums their grads (the SPMD form of the tied-grad
    allreduce at ref `module.py:405-409`)."""

    def __init__(self, key, typename, *module_args, forward_fn=None,
                 tied_weight_attr="embedding", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


class PipelineModule:
    """Layer-list model for pipeline execution (ref `module.py:85`)."""

    def __init__(self,
                 layers,
                 num_stages=None,
                 topology=None,
                 loss_fn=None,
                 seed_layers=False,
                 seed_fn=None,
                 base_seed=1234,
                 partition_method="parameters",
                 activation_checkpoint_interval=0,
                 activation_checkpoint_func=None):
        self._layer_specs = list(layers)
        self.loss_fn = loss_fn
        self.seed_layers = seed_layers
        self.base_seed = base_seed
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.topology = topology

        if num_stages is None and topology is not None:
            num_stages = topology.get_dim("pipe")
        self.num_stages = num_stages or 1

        self._build_layers()
        self.parts = self._partition_layers()

    # -- construction ----------------------------------------------------
    def _build_layers(self):
        self.forward_funcs: List[Any] = []
        self.tied_modules = {}
        self.tied_weight_attrs = {}
        self.layers = []
        # layer idx -> tied key; tied occurrences share ONE param tree in
        # the params structure (so autodiff sums their gradients — the
        # SPMD form of the tied-grad allreduce, ref `module.py:405-409`)
        self.tied_layer_keys = {}
        for idx, spec in enumerate(self._layer_specs):
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in self.tied_modules:
                    self.tied_modules[spec.key] = spec.build()
                    self.tied_weight_attrs[spec.key] = spec.tied_weight_attr
                layer = self.tied_modules[spec.key]
                fn = spec.forward_fn or layer
                self.layers.append(layer)
                self.forward_funcs.append(fn)
                self.tied_layer_keys[idx] = spec.key
            elif isinstance(spec, LayerSpec):
                layer = spec.build()
                self.layers.append(layer)
                self.forward_funcs.append(layer)
            elif callable(spec):
                self.layers.append(spec)
                self.forward_funcs.append(spec)
            else:
                raise TypeError(f"layer {idx} is neither LayerSpec nor "
                                f"callable: {type(spec)}")

    def __len__(self):
        return len(self._layer_specs)

    # -- partitioning (ref module.py:348-403) ---------------------------
    def _count_layer_params(self):
        """Estimated parameter count per layer for balancing, without
        materializing device arrays."""
        counts = []
        for layer in self.layers:
            n = 0
            if hasattr(layer, "param_count"):
                n = int(layer.param_count())
            elif hasattr(layer, "num_params"):
                n = int(layer.num_params)
            counts.append(max(n, 1))
        return counts

    def partition(self, num_parts):
        """Partition the layer list into `num_parts` contiguous parts
        with the module's partition_method; returns the parts offsets
        (length num_parts+1).  The engine uses this with
        num_parts = stages * num_virtual_stages to build the
        round-robin chunk assignment of interleaved 1F1B."""
        method_orig = self.partition_method or "parameters"
        method = method_orig.lower()
        num_layers = len(self._layer_specs)
        if method == "uniform":
            return partition_uniform(num_layers, num_parts)
        if method in ("parameters", "params"):
            weights = self._count_layer_params()
            return partition_balanced(weights, num_parts)
        if method.startswith("type:"):
            # keep original case: the regex matches class names
            layertype = method_orig.split(":", 1)[1]
            binary_weights = [0] * num_layers
            for idx, layer in enumerate(self.layers):
                name = type(layer).__name__ if not isinstance(
                    layer, type) else layer.__name__
                if regex_matches(layertype, name):
                    binary_weights[idx] = 1
            return partition_balanced(binary_weights, num_parts)
        if method == "profile":
            raise NotImplementedError(
                "profile-based partitioning not implemented")
        raise NotImplementedError(
            f"Partitioning method {method} not implemented")

    def _partition_layers(self):
        parts = self.partition(self.num_stages)
        for stage in range(self.num_stages):
            start, stop = parts[stage], parts[stage + 1]
            logger.info(f"pipeline stage={stage} layers={stop - start} "
                        f"[{start}..{stop})")
        return parts

    def stage_layer_range(self, stage_id):
        return self.parts[stage_id], self.parts[stage_id + 1]

    def stage_layers(self, stage_id):
        start, stop = self.stage_layer_range(stage_id)
        return self.forward_funcs[start:stop]

    def mpu(self):
        return self.topology

    # -- functional init/apply (used by the pipeline engine) -------------
    def init_params(self, rng, example_input):
        """Initialize the param structure: {"layers": {idx: tree},
        "tied": {key: tree}}. A tied key appears ONCE no matter how many
        layers reference it — the weight-sharing contract of
        TiedLayerSpec (ref `module.py:71-82`)."""
        layer_params = {}
        tied_params = {}
        x = example_input
        for idx, layer in enumerate(self.layers):
            rng, sub = jax.random.split(rng)
            tied_key = self.tied_layer_keys.get(idx)
            if tied_key is not None and tied_key in tied_params:
                p = tied_params[tied_key]
            elif hasattr(layer, "init"):
                variables = layer.init({"params": sub, "dropout": sub}, x)
                p = variables.get("params", variables)
            else:
                p = {}
            if tied_key is not None:
                tied_params[tied_key] = p
            else:
                layer_params[str(idx)] = p
            x = self.apply_layer(idx, p, x)
        return {"layers": layer_params, "tied": tied_params}

    def layer_params(self, params, idx):
        """Fetch layer idx's params from the shared structure (list
        inputs from older callers still work)."""
        if isinstance(params, (list, tuple)):
            return params[idx]
        tied_key = self.tied_layer_keys.get(idx)
        if tied_key is not None:
            return params["tied"][tied_key]
        return params["layers"][str(idx)]

    def apply_layer(self, idx, params, x, rngs=None, **kwargs):
        fn = self.forward_funcs[idx]
        layer = self.layers[idx]
        if fn is not layer and not hasattr(fn, "apply"):
            # TiedLayerSpec.forward_fn: custom use of the shared params
            # (e.g. embedding transpose as LM head)
            return fn(params, x)
        if hasattr(layer, "apply"):
            return layer.apply({"params": params}, x, rngs=rngs, **kwargs)
        return layer(x)

    # -- per-layer checkpoint files (ref module.py:510-567) ---------------
    def ckpt_layer_path(self, ckpt_dir, layer_idx):
        """`layer_NN-model_states` file for one layer — written per layer
        index, never per stage, so a checkpoint reloads onto any stage
        partitioning (ref `module.py:536-567`, tested by the reference
        at `test_checkpointing.py:633`)."""
        import os
        return os.path.join(ckpt_dir,
                            f"layer_{layer_idx:02d}-model_states.npz")

    def _tied_path(self, ckpt_dir, key):
        import os
        return os.path.join(ckpt_dir, f"tied_{key}-model_states.npz")

    def save_state_dict(self, ckpt_dir, params):
        """Write one file per layer (plus one per tied-param group).
        `params` is the engine param structure from `init_params`.

        ALL processes must call this (multi-host shardings require a
        collective gather per layer — bounded host memory: one layer at
        a time, like the reference's per-layer files); only process 0
        writes."""
        import os
        from deepspeed_tpu.runtime.checkpoint import tree_to_entries
        if jax.process_index() == 0:
            os.makedirs(ckpt_dir, exist_ok=True)

        def host(leaf):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                from jax.experimental import multihost_utils
                return np.asarray(
                    multihost_utils.process_allgather(leaf, tiled=True))
            return np.asarray(jax.device_get(leaf))

        def write(path, tree):
            arrays = {key: host(leaf)
                      for key, leaf in tree_to_entries(tree)}
            if jax.process_index() == 0:
                np.savez(path, **arrays)

        for idx_str, tree in params.get("layers", {}).items():
            write(self.ckpt_layer_path(ckpt_dir, int(idx_str)), tree)
        for key, tree in params.get("tied", {}).items():
            write(self._tied_path(ckpt_dir, key), tree)

    def load_state_dir(self, ckpt_dir, params_template, strict=True):
        """Rebuild the param structure from per-layer files.  The
        current partitioning (num_stages/parts) plays no role: files are
        keyed by global layer index."""
        import os
        from deepspeed_tpu.runtime.checkpoint import (entries_to_tree,
                                                      tree_to_entries)

        def read(path, template):
            if not os.path.exists(path):
                if strict:
                    raise FileNotFoundError(path)
                return template
            with np.load(path) as data:
                flat = {k: data[k] for k in data.files}
            return entries_to_tree(template, flat)

        out = {"layers": {}, "tied": {}}
        for idx_str, tree in params_template.get("layers", {}).items():
            out["layers"][idx_str] = read(
                self.ckpt_layer_path(ckpt_dir, int(idx_str)), tree)
        for key, tree in params_template.get("tied", {}).items():
            out["tied"][key] = read(self._tied_path(ckpt_dir, key), tree)
        return out


def regex_matches(pattern, name):
    return re.search(pattern, name) is not None
