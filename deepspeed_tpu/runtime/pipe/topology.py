"""Process topology: N-D cartesian rank <-> coordinate mapping.

Parity with `deepspeed/runtime/pipe/topology.py:12-455`. The rank math is
backend-agnostic and ports directly; what changes on TPU is what the
topology *produces*: instead of building NCCL process groups per axis
(`topology.py:299-364`), `PipelineParallelGrid` wraps a
`jax.sharding.Mesh` — each named axis IS the communicator, and XLA lowers
collectives onto ICI. The grid still implements the Megatron-style `mpu`
interface (`get_model_parallel_rank` etc., ref `topology.py:365-455`)
so user code written against an mpu keeps working.
"""

from collections import namedtuple
from itertools import product


class ProcessTopology:
    """Cartesian product topology over named axes (ref `topology.py:12`).

    axes: list of axis names, ordered major (outer) to minor (inner).
    dims: per-axis sizes, same order.
    """

    def __init__(self, axes, dims):
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self.mapping = {}
        ranges = [range(d) for d in self.dims]
        for global_rank, coord in enumerate(product(*ranges)):
            key = dict(zip(self.axes, coord))
            self.mapping[self.ProcessCoord(**key)] = global_rank

    def get_rank(self, **coord_kwargs):
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(f"get_rank() needs all axes {self.axes}, "
                             f"got {list(coord_kwargs)}")
        key = self.ProcessCoord(**coord_kwargs)
        assert key in self.mapping, f"coord {coord_kwargs} not in topology"
        return self.mapping[key]

    def get_axis_names(self):
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("data", "pipe"),
                      inner_sep="_", outer_sep="-"):
        """String like 'model_00' naming a rank's non-omitted coords
        (used for checkpoint filenames, ref `topology.py:54-81`)."""
        omit_axes = list(omit_axes)
        axes = [a for a in self.get_axis_names() if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis):
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank):
        for coord, r in self.mapping.items():
            if r == rank:
                return coord
        raise ValueError(f"rank {rank} not in topology")

    def get_axis_comm_lists(self, axis):
        """Lists of ranks that vary only along `axis` (the reference
        builds one process group per list, ref `topology.py:130-166`)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for other_coord in product(*ranges):
            fixed = dict(zip(other_axes, other_coord))
            ranks = [self.get_rank(**{axis: i, **fixed})
                     for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs):
        """Ranks whose coords match all kwargs (ref `topology.py:168-190`)."""
        def _filter_helper(x):
            for key, val in filter_kwargs.items():
                if getattr(x, key) != val:
                    return False
            return True
        coords = filter(_filter_helper, self.mapping.keys())
        return [self.mapping[coord] for coord in coords]

    def get_axis_list(self, axis, idx):
        """Ranks with coord[axis] == idx, sorted (ref `topology.py:192`)."""
        axis_num = self.axes.index(axis)
        ranks = [self.mapping[k] for k in self.mapping.keys()
                 if k[axis_num] == idx]
        return sorted(ranks)

    def world_size(self):
        size = 1
        for d in self.dims:
            size *= d
        return size

    def __str__(self):
        return str(self.mapping)


def _prime_factors(N):
    """Prime factorization, ascending (ref `topology.py:228`)."""
    if N <= 0:
        raise ValueError("Factorize only positive integers")
    primes = []
    while N != 1:
        for candidate in range(2, N + 1):
            if N % candidate == 0:
                primes.append(candidate)
                N //= candidate
                break
    return primes


class PipeDataParallelTopology(ProcessTopology):
    """Hybrid pipeline+data topology; adjacent pipe stages land on
    neighboring device-mesh coordinates (ref `topology.py:235-244`)."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """3D topology: pipeline / model (tensor) / data
    (ref `topology.py:246-249`)."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])


def topology_from_mesh(mesh):
    """ProcessTopology over ALL of a jax Mesh's named axes, in the
    mesh's own (major -> minor) order. Extensible by construction: a
    4-axis mesh with an `expert` axis (deepspeed_tpu/moe/) produces an
    expert coordinate in every rank repr and comm-group computation —
    hardcoding the historical ["pipe", "data", "model"] set here would
    silently drop the axis from rank math (and break
    `_is_grid_valid`, since the axis product must equal the device
    count)."""
    shape = dict(mesh.shape)
    return ProcessTopology(axes=list(shape.keys()),
                           dims=list(shape.values()))


class PipelineParallelGrid:
    """Megatron-compatible `mpu` facade over a topology / jax Mesh
    (ref `topology.py:252-455`).

    On TPU there are no process groups to construct: the mesh axes are
    the communicators. This class supplies rank arithmetic for
    checkpoint naming, data sharding, and mpu-consuming user code.
    `global_rank` is `jax.process_index()`-based when running
    multi-controller, else 0 (single-controller SPMD drives all devices).
    """

    def __init__(self, topology=None, process_group=None, mesh=None,
                 global_rank=0):
        if topology is None:
            assert mesh is not None, "need a topology or a mesh"
            # ALL mesh axes, not a hardcoded 3-axis set: a mesh with
            # an `expert` axis keeps it in rank reprs and group math
            topology = topology_from_mesh(mesh)
        self._topo = topology
        self.mesh = mesh
        self.global_rank = global_rank
        self.world_size = topology.world_size()

        self.data_parallel_size = max(topology.get_dim("data"), 1)
        self.pipe_parallel_size = max(topology.get_dim("pipe"), 1)
        self.model_parallel_size = max(topology.get_dim("model"), 1)
        self.expert_parallel_size = max(topology.get_dim("expert"), 1)
        self.slice_parallel_size = self.model_parallel_size
        assert self._is_grid_valid(), "Invalid Grid"

        self.stage_id = self.get_stage_id()
        self.data_parallel_id = self.get_data_parallel_id()

        # Rank lists per pipeline stage (parity with `self.p2p_groups` /
        # stage_to_global bookkeeping, ref `topology.py:287-330`).
        self.pp_group = []
        self.dp_group = []
        for dp in range(self.data_parallel_size):
            ranks = sorted(self._topo.filter_match(data=dp)) \
                if "data" in self._topo.get_axis_names() else []
            self.pp_group.append(ranks)
        for stage in range(self.pipe_parallel_size):
            if "pipe" in self._topo.get_axis_names():
                self.dp_group.append(
                    sorted(self._topo.filter_match(pipe=stage)))

    def _is_grid_valid(self):
        ranks = 1
        for ax in self._topo.get_axis_names():
            ranks *= self._topo.get_dim(ax)
        return ranks == self.world_size

    # -- stage / pipe ----------------------------------------------------
    def get_stage_id(self):
        if "pipe" not in self._topo.get_axis_names():
            return 0
        return getattr(self._topo.get_coord(rank=self.global_rank), "pipe")

    def get_pipe_parallel_rank(self):
        return self.get_stage_id()

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def stage_to_global(self, stage_id, **kwargs):
        me = self._topo.get_coord(self.global_rank)
        transform = me._replace(pipe=stage_id, **kwargs)._asdict()
        return self._topo.get_rank(**transform)

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self.pipe_parallel_size - 1

    # -- data parallel ---------------------------------------------------
    def get_data_parallel_id(self):
        if "data" not in self._topo.get_axis_names():
            return 0
        return getattr(self._topo.get_coord(rank=self.global_rank), "data")

    def get_data_parallel_rank(self):
        return self.get_data_parallel_id()

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    # -- expert parallel (deepspeed_tpu/moe/) ---------------------------
    def get_expert_parallel_rank(self):
        if "expert" not in self._topo.get_axis_names():
            return 0
        return getattr(self._topo.get_coord(rank=self.global_rank),
                       "expert")

    def get_expert_parallel_world_size(self):
        return self.expert_parallel_size

    # -- model (tensor) parallel ----------------------------------------
    def get_model_parallel_rank(self):
        if "model" not in self._topo.get_axis_names():
            return 0
        return getattr(self._topo.get_coord(rank=self.global_rank), "model")

    get_slice_parallel_rank = get_model_parallel_rank

    def get_model_parallel_world_size(self):
        return self.model_parallel_size

    get_slice_parallel_world_size = get_model_parallel_world_size

    def get_global_rank(self):
        return self.global_rank

    def get_topology(self):
        return self._topo
