"""PipelineEngine — pipeline-parallel training on the SPMD substrate.

Counterpart of `deepspeed/runtime/pipe/engine.py:45` (1169 LoC). The
reference interprets an instruction stream per stage process
(`_INSTRUCTION_MAP`, ref `engine.py:1135-1161`) with p2p sends/recvs and
ring buffers. Under single-controller SPMD both the schedule and the
communication are *compiled*:

  * arbitrary PipelineModules (heterogeneous layers/shapes) on a
    pipe>1 mesh execute the compiled 1F1B interpreter
    (`pipe/interp.py`): the TrainSchedule instruction streams are
    clock-aligned at build time and lowered to a shard_map scan whose
    pipe shards each run THEIR stage via lax.switch, with ppermute
    activation/cotangent flow, recompute-based backward bounded by
    `num_pipe_buffers()` saved stage inputs, and per-stage parameter
    memory partitioning (`pipe/flat_params.py`). This is the
    RECOMMENDED substrate: 1F1B's activation bound beats GPipe's m
    residual sets, parameters divide by the stage count, and it
    measures faster end-to-end on the same model (bench
    `pipe_interp_vs_spmd`: 1918 ms vs 2758 ms — on the serialized
    virtual test mesh the scan's fill/drain bubble executes as real
    garbage compute, an overhead factor of 1 + (S-1)/m = 1.375x,
    matching the measured 1.44x;
    on parallel hardware both paths pay the bubble as idle stages, so
    the gap is EXPECTED to narrow without inverting — an analytic
    claim; no multi-chip pipe hardware exists in this environment to
    measure it). On a pipe=1 mesh the layer chain runs sequentially
    inside the fused step (pure microbatching semantics, no overlap to
    be had).
  * homogeneous-stage models (the PipelinedGPT2 protocol: stacked
    [S, ...] stage params + shape-preserving stage body) execute the
    GPipe fill/steady/drain timeline inside ONE jitted step —
    `lax.scan` over ticks, vmapped stage body partitioned over the
    `pipe` mesh axis, activation rotation lowered to collective-permute
    (see `models/gpt2_pipe.py`). Backward-pipeline scheduling falls
    out of autodiff — the simplest template for fully-regular stacks
    and the one that composes with Megatron TP on the `model` axis.

The train_batch/eval_batch API and loss aggregation semantics
(ref `engine.py:244,320,388-418`) are preserved.
"""

import functools
import inspect

import jax
import numpy as np

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.mesh import DATA_AXIS, MODEL_AXIS, PIPE_AXIS
from deepspeed_tpu.runtime.pipe.module import PipelineModule
from deepspeed_tpu.runtime.pipe.topology import PipelineParallelGrid
from deepspeed_tpu.runtime.pipe.schedule import TrainSchedule
from deepspeed_tpu.utils.logging import log_dist


def is_pipelined_model(model):
    """True for models implementing the stacked-stage SPMD pipeline
    protocol (PipelinedGPT2 and friends): stage_module + loss_fn."""
    return hasattr(model, "stage_module") and hasattr(model, "loss_fn")


class PipelineEngine(DeepSpeedEngine):
    """Training engine for pipelined models (ref `pipe/engine.py:45`)."""

    # Bound on distinct compiled eval-1F1B programs kept alive (one per
    # eval batch shape); LRU beyond this.
    _EVAL_INTERP_CACHE_MAX = 4

    def __init__(self, *args, **kwargs):
        model = kwargs.get("model")
        self._is_pipe_module = isinstance(model, PipelineModule)
        self._pipelined_protocol = is_pipelined_model(model)
        super().__init__(*args, **kwargs)

        # Under single-controller SPMD every process drives the whole
        # device mesh, so each process logically holds ALL stages —
        # global_rank 0 keeps the mpu predicates true everywhere (a
        # per-stage multi-controller runtime would pass its real rank).
        self.grid = PipelineParallelGrid(mesh=self.mesh, global_rank=0)
        self.num_stages = self.mesh.shape[PIPE_AXIS]
        self.stage_id = self.grid.get_stage_id()
        self.micro_batches = self.gradient_accumulation_steps()

        if self.elasticity_enabled():
            raise RuntimeError(
                "Elasticity is not currently supported with pipeline "
                "parallelism.")  # parity: ref pipe/engine.py:57
        if self._is_pipe_module and self.pld_enabled():
            from deepspeed_tpu.utils.logging import logger
            if getattr(self, "_pipe_flat_mode", False):
                logger.warning(
                    "progressive_layer_drop has no effect under the "
                    "compiled 1F1B executor: stochastic depth makes the "
                    "per-stage clock tables data-dependent (documented "
                    "exclusion, docs/tutorials/progressive-layer-drop.md)"
                )
            elif not getattr(self, "_pld_accepting_layers", None):
                logger.warning(
                    "progressive_layer_drop is enabled but no pipeline "
                    "layer accepts a layer_keep_prob kwarg — theta(t) "
                    "will be computed but unused")

        mode = ("spmd" if self._pipelined_protocol else
                "1f1b" if getattr(self, "_use_1f1b", False) else
                "sequential")
        log_dist(
            f"PipelineEngine: stages={self.num_stages}, "
            f"micro_batches={self.micro_batches}, mode={mode}",
            ranks=[0])

    def _virtual_stages_config(self):
        """pipeline.num_virtual_stages from the config block (validated
        int >= 1 by get_pipeline_config)."""
        return int((self._config.pipeline or {}).get(
            C.PIPELINE_NUM_VIRTUAL_STAGES,
            C.PIPELINE_NUM_VIRTUAL_STAGES_DEFAULT))

    # ------------------------------------------------------------------
    # model resolution: chain PipelineModule layers into one loss fn
    # ------------------------------------------------------------------
    def _resolve_model(self, model, model_parameters):
        if isinstance(model, PipelineModule):
            self.module = model
            det_accepting = _layers_accepting_deterministic(model)
            assert model_parameters is not None, (
                "PipelineModule requires explicit model_parameters "
                "(pass model_parameters=module.init_params(rng, example))")

            # Per-stage flat parameter storage (pipe/flat_params.py):
            # active exactly when the compiled 1F1B interpreter will run.
            # Parameters/grads/optimizer state then divide by the stage
            # count (ref module.py:197-249 builds only local layers per
            # process) — and by the model axis on top (the storage
            # composition of the reference's pipe×model grid, ref
            # topology.py:246-249); ZeRO param sharding (stage 3) is
            # capped at 2 — the pipe axis already partitions the
            # parameters.
            if self.mesh.shape[PIPE_AXIS] > 1 and \
                    self.gradient_accumulation_steps() == 1:
                # A 1-microbatch "pipeline" has no overlap and no 1F1B
                # memory partitioning — every pipe device would hold the
                # full model and idle (S-1)/S of the time. Refuse
                # loudly rather than degrade silently (VERDICT r4 #5).
                raise ValueError(
                    f"pipe={self.mesh.shape[PIPE_AXIS]} requires "
                    "gradient_accumulation_steps > 1: pipeline "
                    "parallelism overlaps MICROBATCHES across stages "
                    "(ref pipe/engine.py:59 train_batch consumes "
                    "micro_batches per step). Set "
                    '"gradient_accumulation_steps" >= the stage count '
                    "(2x stages recommended) in the config")
            self._pipe_flat_mode = (
                self.mesh.shape[PIPE_AXIS] > 1 and
                self.gradient_accumulation_steps() > 1)
            # the sequential (pipe=1) chain applies layers one at a
            # time — exactly the seam the ZeRO-3 gather scheduler
            # needs; flat 1F1B mode caps the stage at 2 instead (the
            # pipe axis already partitions parameters)
            self._zero3_chain_capable = not self._pipe_flat_mode
            self._pipe_virtual_stages = 1
            self._chunk_parts = None
            v_cfg = self._virtual_stages_config()
            if not self._pipe_flat_mode and v_cfg > 1:
                # refuse loudly rather than silently train uninterleaved
                # (the other interleave misconfigurations all raise) —
                # naming WHICH precondition failed
                raise ValueError(
                    f"pipeline.num_virtual_stages={v_cfg} requires the "
                    "compiled 1F1B executor, which needs a pipe mesh "
                    f"axis > 1 (got {self.mesh.shape[PIPE_AXIS]}) AND "
                    "gradient_accumulation_steps > 1 (got "
                    f"{self.gradient_accumulation_steps()}) — "
                    "interleaving has nothing to overlap on a "
                    "sequential layer chain")
            if self._pipe_flat_mode:
                assert model.num_stages == self.mesh.shape[PIPE_AXIS], (
                    f"PipelineModule was partitioned for "
                    f"{model.num_stages} stages but the mesh has "
                    f"pipe={self.mesh.shape[PIPE_AXIS]}; build the "
                    "module with num_stages matching the pipe axis")
                from jax.sharding import PartitionSpec
                from deepspeed_tpu.runtime.pipe.flat_params import \
                    StageFlatLayout
                # interleaved (virtual-stage) 1F1B: pipeline block's
                # num_virtual_stages splits the model into S*v chunks
                # assigned round-robin (chunk q on stage q % S), cutting
                # the fill/drain bubble toward 1/v (pipe/schedule.py
                # InterleavedTrainSchedule)
                S = self.mesh.shape[PIPE_AXIS]
                v = v_cfg
                stage_layers = None
                if v > 1:
                    gas = self.gradient_accumulation_steps()
                    if gas % S:
                        raise ValueError(
                            f"num_virtual_stages={v} requires "
                            f"gradient_accumulation_steps divisible by "
                            f"the stage count (microbatch groups of "
                            f"p): got gas={gas}, pipe={S}")
                    if len(model.layers) < S * v:
                        raise ValueError(
                            f"num_virtual_stages={v} needs at least "
                            f"stages*virtual = {S * v} layers to form "
                            f"chunks; the module has "
                            f"{len(model.layers)}")
                    self._pipe_virtual_stages = v
                    self._chunk_parts = model.partition(S * v)
                    # stage s stores chunks {s, s+S, ...}: the
                    # round-robin, non-contiguous layer set
                    stage_layers = [
                        [idx for j in range(v)
                         for idx in range(
                             self._chunk_parts[j * S + s],
                             self._chunk_parts[j * S + s + 1])]
                        for s in range(S)]
                # align so [S, F] divides over model (interp in_specs)
                # and the composed (model, data) master sharding
                self._pipe_layout = StageFlatLayout(
                    model, model_parameters,
                    align=self.mesh.shape[MODEL_AXIS] *
                    self.mesh.shape[DATA_AXIS],
                    stage_layers=stage_layers)
                model_parameters = self._pipe_layout.flatten(
                    model_parameters)
                self._zero_stage_cap = 2

                def _pipe_specs(params_f32):
                    flat, td = jax.tree_util.tree_flatten_with_path(
                        params_f32)
                    specs = [
                        PartitionSpec(PIPE_AXIS, MODEL_AXIS)
                        if jax.tree_util.keystr(path).startswith("['flat']")
                        else PartitionSpec()
                        for path, _ in flat]
                    return jax.tree_util.tree_unflatten(td, specs)

                self._param_specs_override = _pipe_specs

            kp_accepting = _layers_accepting(model, "layer_keep_prob")
            self._pld_accepting_layers = kp_accepting

            def _chained(params, batch, rngs, deterministic,
                         layer_keep_prob, collect):
                if getattr(self, "_pipe_flat_mode", False) and \
                        isinstance(params, dict) and "flat" in params:
                    params = self._pipe_layout.unflatten(params)
                inputs, labels = _split_batch(batch)
                x = inputs
                stats = [] if collect else None
                # ZeRO-3 runtime on the unrolled chain: each layer's
                # sharded params all-gather through the scheduler, with
                # the shared overlap fence (ops/overlap.py) tying layer
                # idx's gather to the activation entering layer
                # idx - prefetch_layers —
                # without the fence XLA may hoist every gather to the
                # top of the program (the naive up-front pattern);
                # backward reduce-scatters each layer's grad into its
                # owning shard via the gather's custom VJP
                sched = getattr(self, "zero3_scheduler", None)
                acts = [x]
                chain_bytes = []
                for idx in range(len(model.layers)):
                    kw = {}
                    if idx in det_accepting:
                        kw["deterministic"] = deterministic
                    if idx in kp_accepting and layer_keep_prob is not None:
                        # PLD θ(t): forwarded exactly as the base engine
                        # forwards it to monolithic models (ref
                        # engine.py:809-810 inherits through the pipe
                        # engine's forward)
                        kw["layer_keep_prob"] = layer_keep_prob
                    lp = model.layer_params(params, idx)
                    if sched is not None:
                        chain_bytes.append(sched.tree_gathered_nbytes(lp))
                        dep = acts[max(0, idx - sched.prefetch_layers)] \
                            if sched.release_after_use else None

                        def layer_call(lp_sharded, x, *, _idx=idx,
                                       _dep=dep, _kw=kw):
                            full = sched.gather(lp_sharded, depend=_dep)
                            return model.apply_layer(_idx, full, x,
                                                     rngs=rngs, **_kw)
                        if sched.release_after_use:
                            # remat the gather INSIDE the layer: the
                            # gathered copy would otherwise be an
                            # autodiff residual held from forward use
                            # until this layer's backward — O(L) live
                            # layers, not the window. Rematted, the
                            # residual is the SHARDED lp; backward
                            # re-gathers in reverse order, same as
                            # apply_layers' hand-written scan.
                            layer_call = jax.checkpoint(
                                layer_call, prevent_cse=False)
                        x = layer_call(lp, x)
                        acts.append(x)
                    else:
                        x = model.apply_layer(idx, lp, x, rngs=rngs,
                                              **kw)
                    if collect:
                        # numerics health: boundary stats AFTER layer
                        # idx — a finite input with a nonfinite output
                        # names the first-NaN layer
                        from deepspeed_tpu.monitor import numerics as nm
                        stats.append(nm.tensor_stats(x))
                if sched is not None:
                    sched.account_chain("pipe_chain", chain_bytes)
                if model.loss_fn is not None:
                    x = model.loss_fn(x, labels)
                if collect:
                    from deepspeed_tpu.monitor import numerics as nm
                    return x, nm.stack_act_stats(stats)
                return x

            def chained_loss(params, batch, rngs=None,
                             deterministic=False, layer_keep_prob=None,
                             **_):
                return _chained(params, batch, rngs, deterministic,
                                layer_keep_prob, collect=False)

            def chained_loss_health(params, batch, rngs=None,
                                    deterministic=False,
                                    layer_keep_prob=None, **_):
                return _chained(params, batch, rngs, deterministic,
                                layer_keep_prob, collect=True)

            self._loss_fn = chained_loss
            if self._numerics_on:
                self._loss_and_health_fn = chained_loss_health
                self._act_layer_names = [
                    f"layer{idx}:{type(layer).__name__}"
                    for idx, layer in enumerate(model.layers)]
            self._initial_params = model_parameters
            return

        if self._pipelined_protocol:
            if self._virtual_stages_config() > 1:
                raise ValueError(
                    "pipeline.num_virtual_stages applies to the "
                    "compiled 1F1B executor (PipelineModule); the "
                    "stacked-stage SPMD protocol (PipelinedGPT2) has "
                    "no virtual-stage schedule")
            # PipelinedGPT2-style protocol: bind the mesh into the loss
            # so activation buffers carry pipe shardings (the mesh is
            # built before model resolution in the base __init__).
            self.module = model
            self._loss_fn = functools.partial(model.loss_fn, mesh=self.mesh)
            if model_parameters is None and hasattr(model, "params"):
                model_parameters = model.params
            assert model_parameters is not None, \
                "model_parameters required for pipelined models"
            self._initial_params = model_parameters
            return

        super()._resolve_model(model, model_parameters)

    def _jit_gas(self):
        # the SPMD pipeline microbatches inside the compiled loss
        return 1 if self._pipelined_protocol else \
            self.gradient_accumulation_steps()

    def _microbatches_per_step(self):
        # samples/throughput accounting: the SPMD path consumes all
        # micro_batches in its single jitted step
        return self.micro_batches if self._pipelined_protocol else \
            super()._microbatches_per_step()

    # ------------------------------------------------------------------
    # compiled 1F1B execution for heterogeneous PipelineModules
    # ------------------------------------------------------------------
    def _build_step_fns(self):
        super()._build_step_fns()
        self._use_1f1b = self._is_pipe_module and \
            getattr(self, "_pipe_flat_mode", False)
        self._interp_fn = None
        if not self._use_1f1b:
            return

        def pipe_step(state, stacked_batch, rng, lr, keep_prob):
            lr = self._resolve_step_lr(state, lr)
            loss, grads = self._interp_fn(
                state.params, stacked_batch, rng, state.scale.loss_scale)
            # join the padded layout when ZeRO pads odd leaves (same as
            # _micro_grad's exit path)
            grads = self.zero_policy.encode(grads, self._zero_pad_plan)
            new_state, overflow, grad_norm, hgrad = \
                self._unscale_clip_and_update(state, lr, grads=grads)
            health = {"grad": hgrad, "act": None} \
                if self._numerics_on else None
            # arity parity with the base _fused_step_jit (no MoE
            # router stats on the 1F1B pipeline path)
            return new_state, loss, overflow, grad_norm, health, None

        # the base train_batch dispatches whatever _fused_step_jit is;
        # the 1F1B program replaces the sequential-chain scan
        self._fused_step_jit = jax.jit(pipe_step, donate_argnums=(0,))

    def _interp_example_mb(self, stacked_batch):
        dp = self.mesh.shape[DATA_AXIS]
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                (np.asarray(x).shape[1] // dp,) + np.asarray(x).shape[2:],
                np.asarray(x).dtype),
            stacked_batch)

    @staticmethod
    def _batch_sig(stacked_batch):
        return tuple(sorted(
            (jax.tree_util.keystr(p), np.asarray(l).shape,
             str(np.asarray(l).dtype))
            for p, l in jax.tree_util.tree_flatten_with_path(
                stacked_batch)[0]))

    def _ensure_interp(self, stacked_batch):
        """Lazy-build the compiled 1F1B step: boundary shapes come from
        the first batch (one LOCAL microbatch as seen inside shard_map:
        the per-microbatch batch dim divides over the data axis)."""
        if self._interp_fn is not None:
            # the compiled program bakes the boundary avals of the
            # first batch; silently padding a different shape would
            # corrupt the flat activation transport
            if self._batch_sig(stacked_batch) != self._interp_sig:
                raise ValueError(
                    "1F1B train batches must keep one shape; got "
                    f"{self._batch_sig(stacked_batch)} after compiling "
                    f"for {self._interp_sig}")
            return
        self._interp_sig = self._batch_sig(stacked_batch)
        # a multi-minute 1F1B compile is indistinguishable from a hang
        # without this: the stall diagnostic shows a fresh "compile"
        # heartbeat instead of a dead engine (interleaving multiplies
        # the schedule ticks by ~v and the lax.switch branch count by
        # v, so its compile is correspondingly longer — the same
        # warning applies, amplified)
        self.monitor.heartbeat("compile")
        from deepspeed_tpu.runtime.pipe.interp import build_pipeline_step
        v = getattr(self, "_pipe_virtual_stages", 1)
        self._interp_fn = build_pipeline_step(
            module=self.module, mesh=self.mesh,
            micro_batches=self.micro_batches,
            params_example=self.state.params,
            batch_example=self._interp_example_mb(stacked_batch),
            split_batch=_split_batch,
            det_accepting=_layers_accepting_deterministic(self.module),
            layout=getattr(self, "_pipe_layout", None),
            num_virtual_stages=v,
            chunk_parts=getattr(self, "_chunk_parts", None))
        bm = getattr(self._interp_fn, "buffer_meta", None)
        if bm:
            # memory ledger: the executor's persistent per-stage carry
            # (saved-input recompute buffers + delivery rings) — the
            # 1F1B activation bound, attributed so an OOM dump can tell
            # schedule memory from model state
            from deepspeed_tpu.monitor import memory as _mem
            self.monitor.ledger.register(
                _mem.CAT_PIPE, "pipe.1f1b_buffers",
                bm["bytes_per_stage"],
                meta={k: bm[k] for k in
                      ("saved_input_buffers", "channel_depth",
                       "flat_width", "transport_dtype")})
        log_dist(
            f"PipelineEngine: compiled "
            f"{'interleaved ' if v > 1 else ''}1F1B schedule over "
            f"{self.num_stages} stages"
            + (f" x {v} virtual" if v > 1 else "")
            + f", {self.micro_batches} microbatches (clock-aligned "
            f"{'InterleavedTrainSchedule' if v > 1 else 'TrainSchedule'}"
            ")", ranks=[0])

    def _ensure_eval_interp(self, stacked_batch):
        """Forward-only pipelined eval (the InferenceSchedule dataflow,
        ref schedule.py:86-127): overlapped stage execution with the
        2-buffer bound and no backward. Compiled per batch-shape (eval
        batches commonly vary, e.g. a final partial batch)."""
        sig = self._batch_sig(stacked_batch)
        cache = getattr(self, "_eval_interp_cache", None)
        if cache is None:
            cache = self._eval_interp_cache = {}
        if sig in cache:
            self._eval_interp_jit = cache.pop(sig)
            cache[sig] = self._eval_interp_jit  # LRU: re-insert as newest
            return
        # Bounded LRU: eval loops with varying trailing partial batches
        # would otherwise accumulate one full compiled 1F1B program per
        # distinct shape.
        while len(cache) >= self._EVAL_INTERP_CACHE_MAX:
            cache.pop(next(iter(cache)))
        from deepspeed_tpu.runtime.pipe.interp import build_pipeline_step
        eval_fn = build_pipeline_step(
            module=self.module, mesh=self.mesh,
            micro_batches=self.micro_batches,
            params_example=self.state.params,
            batch_example=self._interp_example_mb(stacked_batch),
            split_batch=_split_batch,
            det_accepting=_layers_accepting_deterministic(self.module),
            train=False, layout=getattr(self, "_pipe_layout", None),
            num_virtual_stages=getattr(self, "_pipe_virtual_stages", 1),
            chunk_parts=getattr(self, "_chunk_parts", None))
        self._eval_interp_jit = cache[sig] = jax.jit(eval_fn)

    # ------------------------------------------------------------------
    # batch API (ref pipe/engine.py:244,320)
    # ------------------------------------------------------------------
    def _collect_full_batch(self, data_iter=None, batch=None):
        """One global batch = micro_batches microbatches concatenated."""
        if batch is None:
            assert data_iter is not None
            micro = [next(data_iter) for _ in range(self.micro_batches)]
            batch = jax.tree_util.tree_map(
                lambda *xs: np.concatenate(
                    [np.asarray(x) for x in xs]), *micro)
        return batch

    def _train_batch_impl(self, data_iter=None, batch=None):
        """SPMD path: the microbatch axis folds *inside* the compiled
        loss, so the step sees one [1, full_batch, ...] stack.
        Sequential path: the full batch splits into [gas, micro_bs, ...]
        and the base engine's fused scan provides the microbatch loop.
        (The public train_batch is the base class's crash-guarded
        wrapper — an exception anywhere in here still dumps the flight
        recorder.)"""
        m = self.micro_batches
        batch = self._collect_full_batch(data_iter, batch)
        if self._pipelined_protocol:
            full = _to_dict_batch(batch)
            stacked = jax.tree_util.tree_map(lambda x: x[None], full)
        else:
            stacked = jax.tree_util.tree_map(
                lambda x: np.asarray(x).reshape(
                    (m, np.asarray(x).shape[0] // m) +
                    np.asarray(x).shape[1:]), batch)
            if getattr(self, "_use_1f1b", False):
                stacked = _to_dict_batch(stacked)
                self._ensure_interp(stacked)
        te = self.monitor.trace_export
        if te is not None and getattr(self, "_use_1f1b", False) and \
                self._interp_fn is not None:
            # per-microbatch pipeline timeline: the compiled schedule's
            # clock tables laid over this dispatch's REAL host wall
            # window (under async dispatch: enqueue time — the tick
            # layout, concurrency and bubble come from the tables, the
            # absolute placement from the host clock)
            import time as _time
            t0 = _time.perf_counter()
            loss = super()._train_batch_impl(batch=stacked)
            te.add_pipeline_step(
                self._interp_fn.clock_tables, self._interp_fn.pipe_meta,
                t0, _time.perf_counter(), step=self._host_steps)
            return loss
        return super()._train_batch_impl(batch=stacked)

    def eval_batch(self, data_iter=None, batch=None):
        # the SPMD pipelined loss consumes a full batch of micro_batches
        # microbatches — same collection as train_batch
        if self._pipelined_protocol:
            batch = self._collect_full_batch(data_iter, batch)
        elif getattr(self, "_use_1f1b", False):
            m = self.micro_batches
            batch = self._collect_full_batch(data_iter, batch)
            stacked = jax.tree_util.tree_map(
                lambda x: np.asarray(x).reshape(
                    (m, np.asarray(x).shape[0] // m) +
                    np.asarray(x).shape[1:]), _to_dict_batch(batch))
            self._ensure_eval_interp(stacked)
            return self._eval_interp_jit(
                self.state.params,
                jax.tree_util.tree_map(np.asarray, stacked),
                jax.random.PRNGKey(0), np.float32(1.0))
        elif batch is None and data_iter is not None:
            batch = next(data_iter)
        batch = _to_dict_batch(batch)
        return super().eval_batch(batch)

    # ------------------------------------------------------------------
    # stage predicates (ref pipe/engine.py; used by user code)
    # ------------------------------------------------------------------
    def is_first_stage(self):
        return self.grid.is_first_stage()

    def is_last_stage(self):
        return self.grid.is_last_stage()

    def is_gradient_accumulation_boundary(self):
        return True

    def set_dataiterator(self, iterator):
        self.data_iterator = iterator

    # -- stored-layout <-> logical-tree translation ---------------------
    @property
    def module_params(self):
        """Compute-dtype parameters as the module's LOGICAL tree
        (`{"layers", "tied"}`), regardless of the engine's stored
        layout (the flat-stage layout is an internal storage format)."""
        p = self.state.params
        if getattr(self, "_pipe_flat_mode", False):
            p = self._pipe_layout.unflatten(p)
        return p

    @property
    def fp32_params(self):
        p = DeepSpeedEngine.fp32_params.fget(self)
        if getattr(self, "_pipe_flat_mode", False):
            p = self._pipe_layout.unflatten(p)
        return p

    def _module_ckpt_template(self):
        if getattr(self, "_pipe_flat_mode", False):
            return self._pipe_layout.template(self.state.params)
        return super()._module_ckpt_template()

    def _logical_module_tree(self, stored):
        """Checkpoint-snapshot hook: the flat-stage layout unflattens
        into per-layer trees by slicing the SNAPSHOT buffers (async
        device ops — the save path stays sync-free), so the per-layer
        writer rides the same snapshot protocol as tree engines."""
        if getattr(self, "_pipe_flat_mode", False) and \
                isinstance(stored, dict) and "flat" in stored:
            return self._pipe_layout.unflatten(stored)
        return stored

    def _module_from_ckpt(self, tree):
        if getattr(self, "_pipe_flat_mode", False):
            return self._pipe_layout.flatten(tree)
        return tree

    def _count_model_params(self, tree):
        if getattr(self, "_pipe_flat_mode", False) and \
                isinstance(tree, dict) and "flat" in tree:
            return self._pipe_layout.num_params(tree)
        return super()._count_model_params(tree)

    def forward(self, *args, **kwargs):
        raise RuntimeError(
            "Only train_batch() / eval_batch() are accessible on the "
            "pipeline engine (ref pipe/engine.py:328-338)")

    def backward(self, *args, **kwargs):
        raise RuntimeError(
            "Only train_batch() / eval_batch() are accessible on the "
            "pipeline engine")

    def step(self, *args, **kwargs):
        raise RuntimeError(
            "Only train_batch() / eval_batch() are accessible on the "
            "pipeline engine")

    # schedule introspection (testing / multi-controller)
    def train_schedule(self):
        return TrainSchedule(micro_batches=self.micro_batches,
                             stages=self.num_stages,
                             stage_id=self.stage_id)


def _layers_accepting(model, kwarg):
    """Indices of layers whose __call__ takes the given kwarg."""
    accepting = set()
    for idx, layer in enumerate(model.layers):
        target = getattr(type(layer), "__call__", None) \
            if hasattr(layer, "apply") else layer
        try:
            if kwarg in inspect.signature(target).parameters:
                accepting.add(idx)
        except (TypeError, ValueError):
            pass
    return accepting


def _layers_accepting_deterministic(model):
    return _layers_accepting(model, "deterministic")


def _split_batch(batch):
    if isinstance(batch, (tuple, list)) and len(batch) == 2:
        return batch[0], batch[1]
    if isinstance(batch, dict):
        inputs = batch.get("inputs", batch.get("x", batch.get("input_ids")))
        labels = batch.get("labels", batch.get("y"))
        return inputs, labels
    return batch, None


def _to_dict_batch(batch):
    if isinstance(batch, (tuple, list)) and len(batch) == 2:
        return {"input_ids": np.asarray(batch[0]),
                "labels": np.asarray(batch[1])}
    return batch
