"""PipelineEngine — scheduled pipeline-parallel training.

Counterpart of `deepspeed/runtime/pipe/engine.py:45`. Implemented in the
pipeline milestone; this placeholder keeps `deepspeed_tpu.initialize`
honest until then.
"""

from deepspeed_tpu.runtime.engine import DeepSpeedEngine


class PipelineEngine(DeepSpeedEngine):
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "PipelineEngine is under construction in this build; "
            "use DeepSpeedEngine (non-pipeline) configs meanwhile")
