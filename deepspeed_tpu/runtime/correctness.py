"""In-situ A/B correctness harness — the runtime counterpart of the
reference's `pg_correctness_test` toggle (`stage2.py:25,1060`), which
forces dense fp32 gradient all-reduce so the partitioned reduction can
be A/B'd against it on a live model.

The TPU-native form checks the whole STEP, not just the reduction: a
shadow engine runs the same model/batches under the plainest possible
configuration (ZeRO-0, fp32, no offload — pure GSPMD data parallel) and
the harness compares loss trajectories (and optionally parameter norms)
at a configurable interval, logging or raising on divergence. Because
every ZeRO stage is a sharding annotation over the same jitted step,
agreement here certifies the sharded path end-to-end: partitioned
grads, padded leaves, master casts, update, and re-gather.

Usage:

    checker = ABCorrectnessChecker(
        model, params,
        primary_config={..., "zero_optimization": {"stage": 2},
                        "bf16": {"enabled": True}},
        interval=10, loss_atol=0.05)
    for batch in data:
        loss = checker.train_batch(batch=batch)   # steps BOTH engines
    checker.report()
"""

import copy

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


class DivergenceError(AssertionError):
    pass


class ABCorrectnessChecker:
    """Steps a primary (sharded/mixed-precision) engine and a plain
    fp32 ZeRO-0 shadow engine on identical batches and compares.

    interval: compare every N steps. loss_atol: absolute loss
    tolerance (bf16 primaries drift by rounding; fp32 primaries should
    agree to ~1e-5). param_rtol: when set, also compares global
    parameter norms at each check. raise_on_divergence: raise
    DivergenceError instead of logging a warning.

    Scope note: the shadow strips the ENGINE's mixed-precision/ZeRO
    config, but a model whose own config hard-codes a low-precision
    compute dtype (e.g. GPT2Config(dtype=bfloat16)) still computes in
    that dtype on BOTH sides — the A/B then certifies the sharded
    RUNTIME path (partitioned grads, padding, masters, update), not
    the model's compute precision. Build the model in fp32 to A/B
    precision as well."""

    def __init__(self, model, params, primary_config, mesh=None,
                 interval=10, loss_atol=0.05, param_rtol=None,
                 raise_on_divergence=True):
        from deepspeed_tpu import initialize

        ref_config = copy.deepcopy(primary_config)
        ref_config["zero_optimization"] = {"stage": 0}
        for key in ("fp16", "bf16", "bfloat16", "amp"):
            ref_config.pop(key, None)
        self.primary, _, _, _ = initialize(
            model=model, model_parameters=params,
            config=primary_config, mesh=mesh)
        self.reference, _, _, _ = initialize(
            model=model, model_parameters=params,
            config=ref_config, mesh=mesh)
        self.interval = max(1, int(interval))
        self.loss_atol = loss_atol
        self.param_rtol = param_rtol
        self.raise_on_divergence = raise_on_divergence
        self.steps = 0
        self.checks = 0
        self.max_loss_gap = 0.0
        self.max_param_gap = 0.0

    # -- helpers ---------------------------------------------------------
    @staticmethod
    def _param_norm(engine):
        total = 0.0
        for leaf in jax.tree_util.tree_leaves(engine.state.params):
            x = np.asarray(jax.device_get(leaf), np.float32)
            total += float((x.astype(np.float64) ** 2).sum())
        return float(np.sqrt(total))

    def _diverged(self, msg):
        if self.raise_on_divergence:
            raise DivergenceError(msg)
        logger.warning(msg)

    # -- API -------------------------------------------------------------
    def train_batch(self, data_iter=None, batch=None):
        """Step both engines; compare at the configured interval;
        returns the PRIMARY engine's loss."""
        if batch is None:
            assert data_iter is not None
            if getattr(self.primary, "_is_pipe_module", False) or \
                    getattr(self.primary, "_pipelined_protocol", False):
                # pipeline engines collect/reshape batches themselves
                # and would double-advance a shared iterator — the
                # caller must materialize full batches for A/B
                raise ValueError(
                    "ABCorrectnessChecker with a pipelined model needs "
                    "batch= (a full batch both engines can consume); "
                    "the data_iter path would feed them different data")
            gas = self.primary.gradient_accumulation_steps()
            micro = [next(data_iter) for _ in range(gas)]
            batch = jax.tree_util.tree_map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]), *micro)
        loss_p = self.primary.train_batch(batch=batch)
        loss_r = self.reference.train_batch(batch=batch)
        self.steps += 1
        if self.steps % self.interval == 0:
            lp = float(jax.device_get(loss_p))
            lr = float(jax.device_get(loss_r))
            gap = abs(lp - lr)
            self.checks += 1
            if np.isfinite(gap):
                self.max_loss_gap = max(self.max_loss_gap, gap)
            # NaN compares False against everything — a NaN on EITHER
            # side must trip the checker, not sail through
            if not np.isfinite(lp) or not np.isfinite(lr) or \
                    gap > self.loss_atol:
                self._diverged(
                    f"A/B divergence at step {self.steps}: primary loss "
                    f"{lp:.6f} vs fp32 reference {lr:.6f} "
                    f"(|gap| {gap:.6f} > atol {self.loss_atol})")
            if self.param_rtol is not None:
                np_, nr = (self._param_norm(self.primary),
                           self._param_norm(self.reference))
                rgap = abs(np_ - nr) / max(abs(nr), 1e-12)
                self.max_param_gap = max(self.max_param_gap, rgap)
                if rgap > self.param_rtol:
                    self._diverged(
                        f"A/B param-norm divergence at step "
                        f"{self.steps}: {np_:.6f} vs {nr:.6f} "
                        f"(rel {rgap:.2e} > rtol {self.param_rtol})")
        return loss_p

    def report(self):
        summary = {"steps": self.steps, "checks": self.checks,
                   "max_loss_gap": round(self.max_loss_gap, 6),
                   "max_param_rel_gap": round(self.max_param_gap, 8)}
        log_dist(f"A/B correctness: {summary}", ranks=[0])
        return summary
