"""DeepSpeedEngine — the TPU-native training engine.

Counterpart of `deepspeed/runtime/engine.py:95` (1573 LoC of torch
mutation), redesigned around XLA's compilation model:

  * the whole training step — scaled loss, grads, microbatch
    accumulation, overflow vote, loss-scale automaton, clipping, optimizer
    update, param re-cast — is ONE jitted function (`_train_step_fn`).
    The reference's engine.forward/backward/step + ZeRO hook pipeline
    (`engine.py:796-1078`, `stage2.py:583-1489`) becomes a single traced
    program; XLA's latency-hiding scheduler supplies the comm/compute
    overlap that `overlap_comm` hand-builds with CUDA streams.
  * data parallelism needs no allreduce code: the batch is sharded over
    the `data` mesh axis, grads of the global-mean loss are globally
    averaged by construction (GSPMD inserts the reductions; cf. the
    manual bucketed allreduce at `engine.py:1115-1188`).
  * ZeRO-1/2/3 are sharding policies on the optimizer/grad/param state
    (see `runtime/zero/partition.py`), not separate optimizer classes.
  * fp16 dynamic loss scaling runs fully on-device (`lax.cond`-guarded
    update) — the overflow decision never leaves the chip unless fp16
    stats are being reported (ref does a Python-side skip,
    `stage2.py:1346-1368`).
  * async dispatch (default on): the LR schedule is a device-resident
    function of the device `global_steps` counter compiled into the
    step, so the hot loop performs NO host<->device synchronization —
    no per-step lr upload, no `device_get(overflow)` (overflow-skipped
    steps simply don't bump `global_steps`, which IS the reference's
    "scheduler doesn't advance past an overflow step" semantics).
    Host-side metrics sync only at `steps_per_sync` fences; batches
    prefetch on a background thread (`runtime/prefetch.py`).

The three-call API (`engine(batch)` / `engine.backward(loss)` /
`engine.step()`) is preserved for drop-in compatibility; `train_batch`
(one fused step over all grad-accum microbatches) is the fast path.
"""

import contextlib
import copy
import os
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.mesh import (DATA_AXIS, EXPERT_AXIS,
                                        MODEL_AXIS, PIPE_AXIS,
                                        batch_axes, build_mesh,
                                        data_sharding, expert_axis_size,
                                        replicated, stacked_batch_pspecs)
from deepspeed_tpu.runtime.utils import _zeros_like_f32
from deepspeed_tpu.runtime.zero.partition import ZeroShardingPolicy
from deepspeed_tpu.runtime.zero.offload import ZeroOffloadMixin
from deepspeed_tpu.runtime.fp16.loss_scaler import (
    LossScaleState, make_loss_scale_state, make_static_loss_scale_state,
    update_loss_scale, INITIAL_LOSS_SCALE, SCALE_WINDOW, DELAYED_SHIFT,
    MIN_LOSS_SCALE)
from deepspeed_tpu.runtime import lr_schedules
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader
from deepspeed_tpu.runtime.prefetch import PrefetchLoader
from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop
from deepspeed_tpu.runtime import checkpoint as ckpt_io
from deepspeed_tpu.runtime.checkpoint import (save_checkpoint_files,
                                              load_checkpoint_files,
                                              read_latest_tag,
                                              validate_checkpoint_tag,
                                              write_latest_tag)
from deepspeed_tpu.utils.logging import logger, log_dist
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer
from deepspeed_tpu.monitor import (Monitor, SPAN_BACKWARD, SPAN_CKPT,
                                   SPAN_FORWARD, SPAN_STEP)

MEMORY_OPT_ALLREDUCE_SIZE = 500000000



class EngineState(NamedTuple):
    """All device-resident training state (a single pytree so the whole
    step can donate/alias buffers)."""
    params: Any        # compute-dtype params (model.apply consumes these)
    master: Any        # fp32 masters (None in pure-fp32 mode)
    opt_state: Any
    scale: LossScaleState
    acc_grads: Any     # fp32 cross-microbatch accumulator
    skipped: jnp.ndarray   # i32: overflow-skipped step count
    global_steps: jnp.ndarray  # i32


def _global_norm(tree):
    leaves = [jnp.vdot(x.astype(jnp.float32), x.astype(jnp.float32))
              for x in jax.tree_util.tree_leaves(tree)]
    return jnp.sqrt(jnp.sum(jnp.stack(leaves)))


def _batch_token_count(batch):
    """Token/element count of a batch, from the FIRST leaf's static
    shape — no device access. For token models ([.., b, t] int ids)
    this is the literal token count; for dense batches it is the
    element count of the primary input."""
    leaves = jax.tree_util.tree_leaves(batch)
    if not leaves:
        return 0
    return int(np.prod(np.shape(leaves[0])))


def _fetch_to_host(tree):
    """device_get that also handles multi-host (non-fully-addressable)
    sharded arrays by all-gathering them across processes first."""
    def one(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils
            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return jax.device_get(x)
    return jax.tree_util.tree_map(one, tree)


class DeepSpeedEngine(ZeroOffloadMixin):
    """TPU training engine.

    Args mirror `deepspeed.initialize` (ref `__init__.py:50`):
      model: an object with `.loss_fn(params, batch, rngs, deterministic)`
        (e.g. `models.gpt2.GPT2ForCausalLM`), or a flax Module whose
        `apply` returns a scalar loss, or a plain callable
        `loss = f(params, batch, rngs)`.
      model_parameters: the parameter pytree (the JAX analogue of
        `model.parameters()`).
      optimizer: optional optax.GradientTransformation (client optimizer);
        otherwise built from the config's "optimizer" block.
    """

    def __init__(self,
                 args=None,
                 model=None,
                 optimizer=None,
                 model_parameters=None,
                 training_data=None,
                 lr_scheduler=None,
                 mpu=None,
                 dist_init_required=None,
                 collate_fn=None,
                 config=None,
                 config_params=None,
                 dont_change_device=False,
                 mesh=None,
                 rng_seed=42):
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.training_data = training_data
        self.collate_fn = collate_fn
        self.mpu = mpu

        config = config if config is not None else config_params
        if config is None and args is not None and \
                hasattr(args, "deepspeed_config") and \
                args.deepspeed_config is not None:
            config = args.deepspeed_config
        assert config is not None, \
            "DeepSpeed requires --deepspeed_config or a config dict"

        from deepspeed_tpu.runtime.config_utils import load_config_dict
        config_dict = load_config_dict(config)
        self.mesh = mesh if mesh is not None else build_mesh(
            config_dict.get(C.MESH))
        # expert-parallel devices ARE data-parallel devices (the
        # DeepSpeed-MoE convention): the global batch divides over
        # every non-model axis, so an `expert` axis multiplies the
        # data-parallel world exactly like pipe does
        self.dp_world_size = self.mesh.shape[DATA_AXIS] * \
            self.mesh.shape[PIPE_AXIS] * expert_axis_size(self.mesh)
        self.mp_world_size = self.mesh.shape[MODEL_AXIS]

        self._config = DeepSpeedConfig(config_dict, mpu,
                                       world_size=self.dp_world_size)
        # numerics health (monitor/numerics.py): resolved BEFORE the
        # model so layer-exposing resolutions can tap boundaries into
        # the loss they build
        _mon_cfg = self._config.monitor_config
        self._numerics_on = bool(_mon_cfg.enabled and
                                 _mon_cfg.numerics_enabled)
        # set by layer-exposing model resolutions (PipelineModule):
        # same signature as _loss_fn but returns (loss, act_stats[L,3])
        self._loss_and_health_fn = None
        self._act_layer_names = None
        self._resolve_model(model, model_parameters)

        # ---- precision mode ----
        self.fp16_mode = self._config.fp16_enabled
        self.bf16_mode = self._config.bfloat16_enabled
        self.compute_dtype = (jnp.float16 if self.fp16_mode else
                              jnp.bfloat16 if self.bf16_mode else jnp.float32)
        # bf16 {"master_weights": false}: no fp32 master, bf16 Adam
        # moments, stochastic-rounded param writes
        # (runtime/bf16_optimizer.py) — 6 B/param of optimizer state
        # instead of mixed precision's 16 B/param.
        self.bf16_sr_mode = (self.bf16_mode and
                             not self._config.bfloat16_master_weights and
                             not (self.zero_optimization() and
                                  self.zero_cpu_offload()))
        if self.bf16_mode and not self._config.bfloat16_master_weights \
                and not self.bf16_sr_mode:
            logger.warning(
                'bf16 {"master_weights": false} is ignored together '
                "with cpu_offload — the offload path IS the master "
                "store (fp32 masters + moments in host RAM); remove "
                "one of the two settings")
        self.mixed_precision = (self.fp16_mode or self.bf16_mode) and \
            not self.bf16_sr_mode
        self.dynamic_loss_scale_enabled = self.fp16_mode and \
            self._config.loss_scale == 0

        # ---- timers / logging (before deepspeed_io, which uses them) ----
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_micro_batch_size_per_gpu(),
            num_workers=self.dp_world_size,
            steps_per_output=self.steps_per_print())
        # ---- telemetry (deepspeed_tpu/monitor): device-side metric
        # accumulators drained at sync fences, pluggable sinks, step
        # tracing, stall watchdog. Every hot-path hook is one attribute
        # check when monitor.enabled is false.
        self.monitor = Monitor(self, self._config.monitor_config)

        self.training_dataloader = self.deepspeed_io(training_data) \
            if training_data is not None else None
        self.summary_writer = None
        if self.tensorboard_enabled() and jax.process_index() == 0:
            self.summary_writer = self.get_summary_writer()

        self.micro_steps = 0
        # Host-side mirror of the device step counter: used for print/log
        # gating so the hot loop never blocks on device_get (the device
        # counters remain authoritative for checkpointing).
        self._host_steps = 0
        # tokens (elements of the first batch leaf) consumed since the
        # last optimizer step — host int fed to the monitor's
        # device-side accumulator, no sync
        self._tokens_pending = 0
        self._offload_last_norm = None
        # async checkpointing: lazily-built jitted snapshot + writer
        self._ckpt_snapshot_jit = None
        self._ckpt_writer = None
        self._pending_grads = None
        self._pending_loss = None
        self._pending_acts = None
        self._pending_router = None
        self.losses = None

        if self.gradient_predivide_factor() != 1.0 or \
                self._config.prescale_gradients:
            # Pre/post-divide reorders the DP averaging to dodge fp16
            # overflow in NCCL rings (ref engine.py:1123-1135); here grads
            # accumulate in fp32 and GSPMD averages exactly, so the knobs
            # cannot change numerics.
            logger.warning(
                "prescale_gradients/gradient_predivide_factor are no-ops: "
                "gradients accumulate in fp32 under SPMD (exact averaging)")

        # ---- activation checkpointing (ref engine wires the JSON block
        # into deepspeed.checkpointing via configure, checkpointing.py:747)
        ac = self._config.activation_checkpointing_config
        if any([ac.partition_activations, ac.cpu_checkpointing,
                ac.contiguous_memory_optimization,
                ac.synchronize_checkpoint_boundary, ac.profile]):
            from deepspeed_tpu.runtime.activation_checkpointing import \
                checkpointing as ds_checkpointing
            ds_checkpointing.configure(
                mpu, deepspeed_config=self._config, mesh=self.mesh)

        # ---- progressive layer drop ----
        self.progressive_layer_drop = None
        if self.pld_enabled():
            self.progressive_layer_drop = ProgressiveLayerDrop(
                **{k: v for k, v in (self.pld_params() or {}).items()})

        # ---- optimizer + sharding + state ----
        self._rng = jax.random.PRNGKey(rng_seed)
        # cached device constant: the no-PLD keep_prob; building a fresh
        # scalar per step would put a tiny H2D transfer on the hot path
        self._keep_prob_one = jnp.asarray(1.0, jnp.float32)
        self._steps_per_sync = \
            self._config.async_dispatch_steps_per_sync or \
            self.steps_per_print()
        self._init_autotune()
        self._init_overlap()
        self._init_quantized_compute()
        self._init_moe()
        self._configure_optimizer()
        self._configure_lr_scheduler(lr_scheduler)
        self._init_state()
        self._build_step_fns()

        if self._config.dump_state:
            self._config.print("DeepSpeedEngine configuration")

    # ------------------------------------------------------------------
    # model resolution
    # ------------------------------------------------------------------
    def _resolve_model(self, model, model_parameters):
        assert model is not None, "deepspeed.initialize requires a model"
        self.module = model
        if hasattr(model, "loss_fn"):
            if hasattr(model, "bind_zero3_scheduler"):
                # The ZeRO-3 gather scheduler is bound around each
                # TRACE, not left on the model: several engines may
                # share one model object (ABCorrectnessChecker builds a
                # stage-3 primary AND a ZeRO-0 shadow on the same
                # model), and each trace must see ITS engine's
                # schedule — direct model.loss_fn calls outside an
                # engine stay unscheduled.
                raw_loss = model.loss_fn

                def _loss_with_sched(*a, **k):
                    model.bind_zero3_scheduler(
                        getattr(self, "zero3_scheduler", None))
                    try:
                        return raw_loss(*a, **k)
                    finally:
                        model.bind_zero3_scheduler(None)
                self._loss_fn = _loss_with_sched
            else:
                self._loss_fn = model.loss_fn
        elif hasattr(model, "apply"):  # bare flax module returning loss
            import inspect
            try:
                accepted = set(
                    inspect.signature(type(model).__call__).parameters)
            except (TypeError, ValueError):
                accepted = set()

            def _flax_loss(params, batch, rngs=None, deterministic=False,
                           **kwargs):
                kw = {k: v for k, v in kwargs.items() if k in accepted}
                if "deterministic" in accepted:
                    kw["deterministic"] = deterministic
                return model.apply({"params": params}, batch,
                                   rngs=rngs or {}, **kw)
            self._loss_fn = _flax_loss
        elif callable(model):
            import inspect
            try:
                accepted = set(inspect.signature(model).parameters)
            except (TypeError, ValueError):
                accepted = set()

            def _callable_loss(params, batch, rngs=None, deterministic=False,
                               **kwargs):
                kw = {k: v for k, v in kwargs.items() if k in accepted}
                if "deterministic" in accepted:
                    kw["deterministic"] = deterministic
                return model(params, batch, rngs, **kw)
            self._loss_fn = _callable_loss
        else:
            raise TypeError(f"cannot adapt model of type {type(model)}")

        if model_parameters is None and hasattr(model, "params"):
            model_parameters = model.params
        assert model_parameters is not None, \
            "model_parameters (the parameter pytree) is required"
        self._initial_params = model_parameters

    # ------------------------------------------------------------------
    # config accessors (parity with ref engine.py:204-398)
    # ------------------------------------------------------------------
    def train_batch_size(self):
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    def zero_optimization(self):
        return self._config.zero_enabled

    def zero_optimization_stage(self):
        return self._config.zero_optimization_stage

    def zero_reduce_scatter(self):
        return self._config.zero_config.reduce_scatter

    def zero_overlap_comm(self):
        return self._config.zero_config.overlap_comm

    def zero_cpu_offload(self):
        return self._config.zero_config.cpu_offload

    def zero_offload_wire(self):
        """The zero_optimization.offload_wire block (compressed offload
        wire format; runtime/zero/offload.py)."""
        zc = self._config.zero_config
        return dict(grad_bits=zc.offload_wire_grad_bits,
                    param_bits=zc.offload_wire_param_bits,
                    warmup_steps=zc.offload_wire_warmup_steps)

    def zero_reduce_bucket_size(self):
        return self._config.zero_config.reduce_bucket_size

    def zero_allgather_bucket_size(self):
        return self._config.zero_config.allgather_bucket_size

    def zero_elastic_checkpoint(self):
        return self._config.zero_config.elastic_checkpoint

    def zero_load_from_fp32_weights(self):
        return self._config.zero_config.load_from_fp32_weights

    def fp16_enabled(self):
        return self._config.fp16_enabled

    def bfloat16_enabled(self):
        return self._config.bfloat16_enabled

    def amp_enabled(self):
        # ref engine.py amp path; on TPU amp maps to bf16 (config.py)
        return self._config.amp_enabled

    def amp_params(self):
        return self._config.amp_params

    def loss_scale(self):
        return float(jax.device_get(self.state.scale.loss_scale))

    def dynamic_loss_scale(self):
        return self.dynamic_loss_scale_enabled

    def initial_dynamic_scale(self):
        return self._config.initial_dynamic_scale

    def dynamic_loss_scale_args(self):
        return self._config.dynamic_loss_scale_args

    def gradient_clipping(self):
        return self._config.gradient_clipping

    def allreduce_always_fp32(self):
        return self._config.allreduce_always_fp32

    def postscale_gradients(self):
        return not self._config.prescale_gradients

    def gradient_predivide_factor(self):
        return self._config.gradient_predivide_factor

    def sparse_gradients_enabled(self):
        return self._config.sparse_gradients_enabled

    def steps_per_print(self):
        return self._config.steps_per_print

    def async_dispatch_enabled(self):
        """Effective async-dispatch mode (the config flag, vetoed when a
        client lr_scheduler object or ZeRO-Offload forces sync)."""
        return self._async_dispatch

    def steps_per_sync(self):
        """Host<->device metrics-fence cadence in optimizer steps
        (async_dispatch.steps_per_sync, or steps_per_print when 0)."""
        return self._steps_per_sync

    def prefetch_depth(self):
        return self._config.async_dispatch_prefetch_depth

    def wall_clock_breakdown(self):
        return self._config.wall_clock_breakdown

    def memory_breakdown(self):
        return self._config.memory_breakdown

    def tensorboard_enabled(self):
        return self._config.tensorboard_enabled

    def tensorboard_output_path(self):
        return self._config.tensorboard_output_path

    def tensorboard_job_name(self):
        return self._config.tensorboard_job_name

    def optimizer_name(self):
        return self.client_optimizer.__class__.__name__ \
            if self.client_optimizer and not isinstance(
                self.client_optimizer, optax.GradientTransformation) \
            else self._config.optimizer_name

    def optimizer_params(self):
        return self._config.optimizer_params

    def optimizer_legacy_fusion(self):
        return self._config.optimizer_legacy_fusion

    def scheduler_name(self):
        return self._config.scheduler_name

    def scheduler_params(self):
        return self._config.scheduler_params

    def flops_profiler_enabled(self):
        return self._config.flops_profiler_config.enabled

    def flops_profiler_profile_step(self):
        return self._config.flops_profiler_config.profile_step

    def flops_profiler_module_depth(self):
        return self._config.flops_profiler_config.module_depth

    def flops_profiler_top_modules(self):
        return self._config.flops_profiler_config.top_modules

    def flops_profiler_detailed(self):
        return self._config.flops_profiler_config.detailed

    def pld_enabled(self):
        return self._config.pld_enabled

    def pld_params(self):
        return self._config.pld_params

    def pld_theta(self):
        return self.progressive_layer_drop.get_theta() \
            if self.progressive_layer_drop else 1.0

    def checkpoint_tag_validation_enabled(self):
        return self._config.checkpoint_tag_validation_enabled

    def checkpoint_tag_validation_fail(self):
        return self._config.checkpoint_tag_validation_fail

    def checkpoint_async_save(self):
        """checkpoint.async_save: save_checkpoint costs the train loop
        only a device snapshot; serialization runs on a writer thread."""
        return self._config.checkpoint_async_save

    def checkpoint_keep_last(self):
        return self._config.checkpoint_keep_last

    def checkpoint_writer_queue_depth(self):
        return self._config.checkpoint_writer_queue_depth

    def checkpoint_queue_policy(self):
        return self._config.checkpoint_queue_policy

    def elasticity_enabled(self):
        return self._config.elasticity_enabled

    _tb_fallback_warned = False

    def get_summary_writer(self, name="DeepSpeedJobName", base=None):
        """TensorBoard writer for the legacy `tensorboard` config block.
        Served by the native tfevents writer (monitor/tfevents.py) —
        no torch import anywhere on this path; the config keys
        (enabled/output_path/job_name) keep their reference meaning.
        Returns None (warn-once) only when the log dir is unusable."""
        if base is None:
            base = os.path.join(os.path.expanduser("~"), "tensorboard")
        if self.tensorboard_output_path():
            base_dir = self.tensorboard_output_path()
        else:
            base_dir = base
        log_dir = os.path.join(base_dir, self.tensorboard_job_name() or name)
        try:
            from deepspeed_tpu.monitor.tfevents import SummaryWriter
            return SummaryWriter(log_dir)
        except Exception:
            if not DeepSpeedEngine._tb_fallback_warned:
                DeepSpeedEngine._tb_fallback_warned = True
                logger.warning(
                    "tensorboard unavailable; scalar summaries are "
                    "disabled for this run", exc_info=True)
            return None

    # ------------------------------------------------------------------
    # optimizer construction (ref engine.py:544-630 selection matrix)
    # ------------------------------------------------------------------
    def _pure_data_mesh(self):
        """Stage-0 replicated params over a multi-device data-only mesh:
        the scope where per-leaf shard_map collectives (CSR sparse
        grads, 1-bit Adam's compressed allreduce) are legal — the same
        scope as the reference's non-ZeRO fallback path. An `expert`
        axis disqualifies the mesh: those shard_map programs name only
        the data axis (in_specs, pmean, worker counts), while batch
        rows shard over (data, expert) — running them would leave each
        expert replica redundantly recomputing its whole data slice."""
        return (self.zero_optimization_stage() == 0 and
                not self._offload_enabled() and
                self.mesh.shape[DATA_AXIS] > 1 and
                self.mesh.shape[MODEL_AXIS] == 1 and
                self.mesh.shape[PIPE_AXIS] == 1 and
                expert_axis_size(self.mesh) == 1)

    def _build_optimizer_transform(self):
        self._use_onebit_shardmap = False
        self._onebit_freeze_step = None
        if isinstance(self.client_optimizer, optax.GradientTransformation):
            # Client optax optimizer: wrap so lr can be injected if it
            # isn't already an inject_hyperparams transform.
            self._base_lr = None
            return self.client_optimizer

        name = (self._config.optimizer_name or C.ADAM_OPTIMIZER).lower()
        params = dict(self._config.optimizer_params or {})
        lr = params.get("lr", 1e-3)
        betas = params.get("betas", (0.9, 0.999))
        eps = params.get("eps", 1e-8)
        weight_decay = params.get("weight_decay", 0.0)
        self._base_lr = lr

        if self.bf16_sr_mode:
            # Master-less bf16: moments live in bf16, update math in
            # fp32, param write-back stochastically rounded
            # (runtime/bf16_optimizer.py). Adam/AdamW only — the other
            # optimizers keep the fp32-master path.
            if name not in (C.ADAM_OPTIMIZER, C.ADAMW_OPTIMIZER):
                raise ValueError(
                    f'bf16 {{"master_weights": false}} supports '
                    f"Adam/AdamW only (got {name!r}); drop the flag to "
                    "use the fp32-master path")
            from deepspeed_tpu.runtime.bf16_optimizer import adamw_bf16
            if weight_decay and not params.get("adam_w_mode", True) and \
                    name != C.ADAMW_OPTIMIZER:
                logger.warning(
                    "bf16 master_weights=false uses decoupled (AdamW) "
                    "weight decay; adam_w_mode=false is ignored")
            return adamw_bf16(learning_rate=lr, b1=betas[0], b2=betas[1],
                              eps=eps, weight_decay=weight_decay)

        if name == C.ONEBIT_ADAM_OPTIMIZER:
            # 1-bit Adam (ref onebit_adam.py:18): freeze_step warmup then
            # sign-compressed momentum with error feedback. On a
            # multi-device pure-data mesh the engine compiles TWO step
            # programs and switches at freeze_step — exactly the
            # reference's host-side `enable_backward_allreduce = False`
            # flip (ref onebit_adam.py:372): the warmup program carries
            # the dense GSPMD grad reduction, the compressed program
            # keeps grads local and communicates only bit-packed
            # momentum signs inside shard_map.
            from deepspeed_tpu.runtime.fp16.onebit_adam import onebit_adam
            freeze_step = params.get("freeze_step", 100)
            kw = dict(learning_rate=lr, b1=betas[0], b2=betas[1], eps=eps,
                      weight_decay=weight_decay, freeze_step=freeze_step)
            self._onebit_kwargs = kw
            self._onebit_freeze_step = freeze_step
            self._use_onebit_shardmap = self._pure_data_mesh()
            if self._use_onebit_shardmap:
                # worker_error is per-worker state: [dp] leading dim,
                # sharded over the data axis (see onebit_adam docstring)
                kw["num_workers"] = self.mesh.shape[DATA_AXIS]
                self._onebit_kwargs = kw
                return onebit_adam(**kw, static_phase="warmup")
            if self.mesh.shape[DATA_AXIS] > 1:
                logger.warning(
                    "OnebitAdam compressed collective unavailable here "
                    "(needs zero stage 0, no offload, and a pure-data "
                    "mesh); falling back to the single-worker numerics "
                    "form with dense gradient reduction")
            return onebit_adam(**kw)
        if name in (C.ADAM_OPTIMIZER, C.ADAMW_OPTIMIZER):
            # FusedAdam defaults to adam_w_mode (ref ops/adam/fused_adam.py);
            # decoupled weight decay is the TPU-native choice too.
            adam_w_mode = params.get("adam_w_mode", True) or \
                name == C.ADAMW_OPTIMIZER
            if adam_w_mode:
                return optax.inject_hyperparams(optax.adamw)(
                    learning_rate=lr, b1=betas[0], b2=betas[1], eps=eps,
                    weight_decay=weight_decay)
            return optax.inject_hyperparams(optax.adam)(
                learning_rate=lr, b1=betas[0], b2=betas[1], eps=eps)
        if name == C.LAMB_OPTIMIZER:
            # reference-parity LAMB (clipped trust ratio, ref
            # csrc/lamb/fused_lamb_cuda_kernel.cu:279-306) — optax.lamb
            # never clips the coefficient
            from deepspeed_tpu.ops.lamb.fused_lamb import lamb as ds_lamb
            return ds_lamb(
                learning_rate=lr, b1=betas[0], b2=betas[1], eps=eps,
                weight_decay=weight_decay,
                max_coeff=params.get("max_coeff", 10.0),
                min_coeff=params.get("min_coeff", 0.01),
                bias_correction=params.get("bias_correction", True))
        if name == C.SGD_OPTIMIZER:
            momentum = params.get("momentum", 0.0)
            return optax.inject_hyperparams(optax.sgd)(
                learning_rate=lr, momentum=momentum or None)
        raise ValueError(f"Unknown optimizer {name}")

    def _configure_optimizer(self):
        self.optimizer_transform = self._build_optimizer_transform()
        # scheduler-facing shim mirroring torch param_groups
        self._optimizer_shim = lr_schedules._OptimizerShim(
            lr=self._base_lr or 0.0)
        self.optimizer = self  # `engine.optimizer` parity: exposes state

    def _configure_lr_scheduler(self, client_lr_scheduler):
        # Async dispatch needs a schedule it can compile into the step:
        # a client scheduler object is arbitrary host code (sync mode),
        # and ZeRO-Offload's host optimizer step is a sync by nature.
        self._device_lr_fn = None
        self._async_dispatch = (self._config.async_dispatch_enabled and
                                client_lr_scheduler is None and
                                not self._offload_enabled())
        if client_lr_scheduler is not None:
            self.lr_scheduler = client_lr_scheduler
            if self._config.async_dispatch_enabled:
                log_dist(
                    "async_dispatch: disabled — a client lr_scheduler "
                    "object cannot be compiled into the jitted step "
                    "(use the config scheduler block for the sync-free "
                    "hot path)", ranks=[0])
            return
        name = self.scheduler_name()
        if name is None:
            self.lr_scheduler = None
            self._device_lr_fn = lr_schedules.device_schedule_fn(
                None, base_lr=self._base_lr)
            return
        sched_cls = {
            lr_schedules.LR_RANGE_TEST: lr_schedules.LRRangeTest,
            lr_schedules.ONE_CYCLE: lr_schedules.OneCycle,
            lr_schedules.WARMUP_LR: lr_schedules.WarmupLR,
            lr_schedules.WARMUP_DECAY_LR: lr_schedules.WarmupDecayLR,
        }.get(name)
        if sched_cls is None:
            raise ValueError(f"Unknown scheduler {name}")
        params = self.scheduler_params() or {}
        self.lr_scheduler = sched_cls(self._optimizer_shim, **params)
        self._device_lr_fn = lr_schedules.device_schedule_fn(name, params)
        log_dist(f"Using LR scheduler {name}"
                 + (" (device-resident under async dispatch)"
                    if self._async_dispatch else ""), ranks=[0])

    def _current_lr(self):
        if self.lr_scheduler is not None:
            try:
                return float(self.lr_scheduler.get_last_lr()[0])
            except AssertionError:
                lrs = self.lr_scheduler.get_lr()
                return float(lrs[0])
        return float(self._base_lr if self._base_lr is not None else 0.0)

    def get_lr(self):
        # Under async fp16 the host scheduler is an optimistic mirror;
        # an explicit lr query is a user-initiated sync point (like
        # loss_scale()), so refresh it first.
        self._sync_scheduler_mirror()
        return [self._current_lr()]

    def get_mom(self):
        if self.lr_scheduler is not None and \
                hasattr(self.lr_scheduler, "get_mom"):
            mom = self.lr_scheduler.get_mom()
            if mom is not None:
                return mom
        return [self._optimizer_shim.param_groups[0].get("betas",
                                                         (0.9, 0.999))]

    # ------------------------------------------------------------------
    # state init + sharding
    # ------------------------------------------------------------------
    def _init_state(self):
        # Copy jax arrays: device_put of an already-placed array aliases
        # it, and the step donates its input state — without the copy the
        # caller's (possibly shared) initial params would be invalidated
        # after the first step.
        # In SR mode no state group stores fp32 values, so the fp32 tree
        # stays ABSTRACT (at 1.5B params a concrete fp32 copy is 6.2 GB
        # of HBM that would sit next to the real state just long enough
        # to OOM the first step).
        if self.bf16_sr_mode:
            params_f32 = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(np.shape(x), jnp.float32),
                self._initial_params)
        else:
            params_f32 = jax.tree_util.tree_map(
                lambda x: jnp.array(x, dtype=jnp.float32, copy=True)
                if isinstance(x, jax.Array)
                else jnp.asarray(x, jnp.float32), self._initial_params)

        tp_specs = None
        specs_override = getattr(self, "_param_specs_override", None)
        if specs_override is not None:
            # PipelineEngine's per-stage flat layout: flat buffers carry
            # a pipe-axis spec, tied leaves replicate
            tp_specs = specs_override(params_f32)
        elif hasattr(self.module, "tp_param_specs"):
            # TP (and, for pipelined models, pipe-stage) placement; a
            # spec naming a size-1 mesh axis is a no-op, so this is safe
            # for pure-DP meshes too.
            tp_specs = self.module.tp_param_specs(params_f32)
        # _zero_stage_cap: the flat-stage pipe layout already partitions
        # parameters (over pipe); stage-3 data-axis param sharding on
        # top would break the interpreter's local-slice invariant
        effective_stage = min(self.zero_optimization_stage(),
                              getattr(self, "_zero_stage_cap", 3))
        if effective_stage != self.zero_optimization_stage():
            logger.warning(
                f"ZeRO stage {self.zero_optimization_stage()} is capped "
                f"to {effective_stage} under the pipeline's per-stage "
                "flat parameter layout: parameters are already "
                "partitioned over the pipe axis; optimizer state / "
                "gradients still shard over the data axis")
        self.zero_policy = ZeroShardingPolicy(
            self.mesh, effective_stage, param_specs=tp_specs)

        self._param_shardings = self.zero_policy.param_shardings(params_f32)

        # Leaves with no dp-divisible dim are stored PADDED in the
        # sharded state groups (master/moments/grad-accum) so they truly
        # shard instead of silently replicating — the TPU-native form of
        # the reference's sub-partition alignment (ref stage1.py:198-261).
        # Compute-dtype params keep true shapes; padding is sliced off
        # after each update and on checkpoint save.
        self._zero_pad_plan = {}
        # SR mode shards its bf16 Adam moments (and gas>1 fp32
        # accumulator) over the data axis exactly like the fp32-master
        # path, so it needs the same padding for non-divisible leaves.
        if (self.mixed_precision or self.bf16_sr_mode) and \
                not self._offload_enabled():
            self._zero_pad_plan = self.zero_policy.pad_plan(params_f32)
            if self._zero_pad_plan:
                log_dist(
                    f"ZeRO: padding {len(self._zero_pad_plan)} "
                    "non-divisible leaves for data-axis sharding",
                    ranks=[0])
        params_enc = self.zero_policy.encode(params_f32,
                                             self._zero_pad_plan)
        self._master_shardings = self.zero_policy.master_shardings(params_enc)
        self._acc_shardings = self.zero_policy.grad_accum_shardings(params_enc)
        self._params_enc_template = params_enc
        self._init_zero3_scheduler(effective_stage)

        if self.bf16_sr_mode:
            # cast straight from the caller's params — no fp32 detour.
            # jitted with out_shardings: outputs are fresh buffers (the
            # donation contract the old copy=True provided) AND born
            # sharded, so no unsharded cast tree transits HBM/RAM
            # (25 GB at 13B).
            params = jax.jit(
                lambda t: jax.tree_util.tree_map(
                    lambda x: jnp.asarray(x, self.compute_dtype), t),
                out_shardings=self._param_shardings)(self._initial_params)
            master = None
        elif self.mixed_precision or self._offload_enabled():
            params = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(
                    jnp.asarray(x, self.compute_dtype), s),
                params_f32, self._param_shardings)
            # the fp32 master goes to device only in true mixed
            # precision — offload keeps it in host RAM
            master = jax.device_put(params_enc, self._master_shardings) \
                if self.mixed_precision else None
        else:
            master = None
            params = jax.device_put(params_f32, self._param_shardings)

        if self._offload_enabled():
            # ZeRO-Offload: no device master/opt state; host-side fp32
            # masters + CPU-Adam moments (runtime/zero/offload.py)
            self._init_offload(params_f32)
            self.state = EngineState(
                params=params, master=None, opt_state=(),
                scale=make_static_loss_scale_state(
                    self._host_scaler.cur_scale),
                acc_grads=jax.device_put(_zeros_like_f32(params_f32),
                                         self._acc_shardings),
                skipped=jnp.asarray(0, jnp.int32),
                global_steps=jnp.asarray(0, jnp.int32))
            n_params = sum(np.prod(l.shape) for l in
                           jax.tree_util.tree_leaves(params_f32))
            log_dist(
                f"engine initialized (offload): {n_params/1e6:.1f}M params, "
                f"zero_stage={self.zero_optimization_stage()}, "
                f"dtype={self.compute_dtype.__name__}, "
                f"mesh={dict(self.mesh.shape)}", ranks=[0])
            self._register_memory_ledger()
            self._initial_params = None   # don't pin the caller's copy
            return

        if self.mixed_precision:
            opt_target = master
        elif self.bf16_sr_mode and self._zero_pad_plan:
            # moments live in the padded (encoded) layout so they truly
            # shard; params themselves keep true shapes for the model
            opt_target = self.zero_policy.encode(params,
                                                 self._zero_pad_plan)
        else:
            opt_target = params
        # Shardings are computed from ABSTRACT shapes and the init runs
        # jitted with out_shardings, so moments are born sharded — an
        # eager init would materialize the full unsharded moment tree
        # (100+ GB at 13B) on one device before resharding.
        opt_shape = jax.eval_shape(self.optimizer_transform.init,
                                   opt_target)
        if self.lr_scheduler is not None and \
                "learning_rate" not in getattr(opt_shape, "hyperparams", {}):
            logger.warning(
                "an LR scheduler is configured but the client optimizer "
                "exposes no injectable 'learning_rate' hyperparam "
                "(wrap it with optax.inject_hyperparams); scheduler values "
                "will not be applied")
        self._opt_shardings = self.zero_policy.opt_state_shardings(
            opt_shape, self._params_enc_template)
        if self._use_onebit_shardmap:
            self._opt_shardings = self._opt_shardings._replace(
                worker_error=jax.tree_util.tree_map(
                    lambda w: NamedSharding(
                        self.mesh,
                        PartitionSpec(DATA_AXIS,
                                      *([None] * (w.ndim - 1)))),
                    opt_shape.worker_error))
        opt_state = jax.jit(
            self.optimizer_transform.init,
            out_shardings=self._opt_shardings)(opt_target)

        if self.fp16_mode:
            if self.dynamic_loss_scale_enabled:
                args = self.dynamic_loss_scale_args() or {}
                scale = make_loss_scale_state(
                    init_scale=args.get(INITIAL_LOSS_SCALE,
                                        self.initial_dynamic_scale()),
                    delayed_shift=args.get(DELAYED_SHIFT, 2))
            else:
                scale = make_static_loss_scale_state(self._config.loss_scale)
        else:
            scale = make_static_loss_scale_state(1.0)

        # With no gradient accumulation the persistent fp32 accumulator
        # is pure overhead (equal in size to the master weights); grads
        # flow straight from the microbatch into the update instead.
        if self._jit_gas() == 1:
            acc = ()
        else:
            acc = jax.device_put(_zeros_like_f32(self._params_enc_template),
                                 self._acc_shardings)

        self.state = EngineState(
            params=params, master=master, opt_state=opt_state, scale=scale,
            acc_grads=acc,
            skipped=jnp.asarray(0, jnp.int32),
            global_steps=jnp.asarray(0, jnp.int32))

        n_params = self._count_model_params(params_f32)
        # cached for the monitor's in-loop MFU derivation (6·N·tokens/s
        # against the chip's nominal peak — the bench convention)
        self._n_model_params = n_params
        log_dist(
            f"engine initialized: {n_params/1e6:.1f}M params, "
            f"zero_stage={self.zero_policy.stage}, "
            f"dtype={self.compute_dtype.__name__}, "
            f"mesh={dict(self.mesh.shape)}", ranks=[0])
        if self._numerics_on:
            # host-side labels for the numerics stat rows: grad groups
            # from the encoded-layout template (the tree the jitted
            # stats walk), activation boundaries from the resolver
            from deepspeed_tpu.monitor import numerics as _num
            self.monitor.set_numerics_labels(
                grad=_num.group_paths(self._params_enc_template),
                act=self._act_layer_names)
        self._register_memory_ledger()
        self._initial_params = None   # don't pin the caller's copy

    def _init_autotune(self):
        """Wire the kernel block-size autotuner (ops/autotune.py):
        apply the `autotune` config block (enabled toggle + table
        path) and attach the monitor so `autotune_search` /
        `autotune_hit` events flow to the sinks. Lookups then happen
        transparently inside the kernel entry points at trace time —
        pure host-side dict reads, no device sync."""
        from deepspeed_tpu.ops import autotune
        at = self._config.autotune
        autotune.configure(
            enabled=at["enabled"],
            table_path=at["table_path"],
            monitor=self.monitor if self.monitor.enabled else False)

    def _init_overlap(self):
        """Wire the `overlap` config block into the shared
        communication/compute overlap runtime (ops/overlap.py):
        enabled toggle, pinned-vs-autotuned site set, and the default
        issue distance. Emits one `overlap` monitor event recording
        the configuration. Schedule resolution afterwards is a pure
        host-side dict read at trace time — no device sync."""
        from deepspeed_tpu.ops import overlap
        ov = self._config.overlap
        overlap.configure(
            enabled=ov["enabled"],
            sites=ov["sites"],
            issue_distance=ov["issue_distance"])
        if self.monitor.enabled:
            self.monitor.event(
                "overlap", enabled=ov["enabled"],
                sites=(ov["sites"] if isinstance(ov["sites"], str)
                       else ",".join(sorted(ov["sites"]))),
                issue_distance=ov["issue_distance"])

    def _init_quantized_compute(self):
        """Wire the `quantized_compute` config block into the model:
        call its `configure_quantized_compute` hook (GPT-2 family)
        with the configured mode/block/stochastic_rounding, emit one
        `quantized_matmul` monitor event recording the configuration,
        and warn when the model does not expose the hook (the config
        then has no effect on this model)."""
        qc = self._config.quantized_compute
        if not qc["enabled"]:
            return
        target = getattr(self, "module", None)
        hook = getattr(target, "configure_quantized_compute", None)
        if hook is None:
            logger.warning(
                "quantized_compute.enabled is set but the model "
                f"({type(target).__name__}) exposes no "
                "configure_quantized_compute hook; forward matmuls "
                "stay unquantized")
            applied = False
        else:
            hook(qc["mode"], block=qc["block"],
                 stochastic_rounding=qc["stochastic_rounding"])
            applied = True
        if self.monitor.enabled:
            from deepspeed_tpu.ops.transformer.quantized_matmul \
                import resolve_quantized_compute
            self.monitor.event(
                "quantized_matmul", applied=applied,
                mode=qc["mode"], block=qc["block"],
                stochastic_rounding=qc["stochastic_rounding"],
                active=bool(applied and
                            resolve_quantized_compute(qc["mode"])))

    def _init_moe(self):
        """Wire the `moe` config block into the model
        (deepspeed_tpu/moe/): validate the expert mesh axis against the
        expert count, call the model's `configure_moe` hook with the
        engine mesh + router knobs (structural keys are VERIFIED
        against the built parameter tree, router knobs applied), and
        emit one `moe` monitor event recording the configuration.
        Runs BEFORE state init so `tp_param_specs` sees the expert
        placement when the ZeRO policy is built."""
        mc = self._config.moe
        self._moe_active = False
        self._moe_stats_on = False
        if not mc["enabled"]:
            return
        target = getattr(self, "module", None)
        hook = getattr(target, "configure_moe", None)
        if hook is None:
            logger.warning(
                "moe.enabled is set but the model "
                f"({type(target).__name__}) exposes no configure_moe "
                "hook; the moe block has no effect on this model")
            return
        es = expert_axis_size(self.mesh)
        if mc["num_experts"] % es:
            raise ValueError(
                f"moe.num_experts={mc['num_experts']} must divide by "
                f"the mesh expert axis ({es}): each expert-parallel "
                "device group owns num_experts/expert contiguous "
                "experts")
        hook(mesh=self.mesh,
             num_experts=mc["num_experts"],
             every_n_layers=mc["every_n_layers"],
             top_k=mc["top_k"],
             capacity_factor=mc["capacity_factor"],
             aux_loss_weight=mc["aux_loss_weight"],
             jitter_eps=mc["jitter_eps"],
             fused_dispatch=mc["fused_dispatch"])
        self._moe_active = True
        # router stats ride the jitted step only when something drains
        # them (the monitor fence) — dense-engine traces stay identical
        self._moe_stats_on = self.monitor.enabled
        if self.monitor.enabled:
            self.monitor.event(
                "moe", num_experts=mc["num_experts"],
                top_k=mc["top_k"],
                capacity_factor=mc["capacity_factor"],
                aux_loss_weight=mc["aux_loss_weight"],
                every_n_layers=mc["every_n_layers"],
                jitter_eps=mc["jitter_eps"],
                fused_dispatch=mc["fused_dispatch"],
                expert_axis=es)
        log_dist(
            f"MoE: {mc['num_experts']} experts (top_k={mc['top_k']}, "
            f"cf={mc['capacity_factor']}, every_n_layers="
            f"{mc['every_n_layers']}) over expert axis {es}",
            ranks=[0])

    def _init_zero3_scheduler(self, effective_stage):
        """Build + bind the explicit ZeRO-3 gather/release runtime
        (runtime/zero/stage3.py): layer-granular all-gather prefetched
        `prefetch_layers` ahead of use, released after its fwd/bwd use,
        gradients reduce-scattered into the owning data-axis shard.
        Weaves through models exposing `bind_zero3_scheduler` (GPT-2 /
        BERT layer stacks) or the sequential PipelineModule chain;
        everything else keeps the implicit-GSPMD stage-3 behavior
        (params sharded, XLA chooses where to materialize)."""
        self.zero3_scheduler = None
        zc = self._config.zero_config
        if effective_stage != 3 or not zc.stage3_enabled:
            return
        if self.mesh.shape[MODEL_AXIS] > 1:
            logger.warning(
                "ZeRO-3 gather scheduler: disabled on a model-parallel "
                "mesh (the scheduled gather replicates over ALL "
                "non-data axes, which would undo tensor-parallel "
                "placement); stage-3 params stay sharded with "
                "XLA-implicit gathers")
            return
        if self.progressive_layer_drop is not None:
            logger.warning(
                "ZeRO-3 gather scheduler: disabled with "
                "progressive_layer_drop (the scheduled stack has no "
                "per-layer keep-prob gate); stage-3 params stay "
                "sharded with XLA-implicit gathers")
            return
        from deepspeed_tpu.runtime.zero.stage3 import Zero3GatherScheduler
        s3 = self.zero_stage3_config()
        sched = Zero3GatherScheduler(
            self.mesh,
            prefetch_layers=s3["prefetch_layers"],
            release_after_use=s3["release_after_use"],
            gather_dtype=s3["gather_dtype"])
        if not hasattr(self.module, "bind_zero3_scheduler") and \
                not getattr(self, "_zero3_chain_capable", False):
            log_dist(
                "ZeRO-3: model exposes no layer-stack hook "
                "(bind_zero3_scheduler) and is not a sequential "
                "PipelineModule chain; params stay sharded with "
                "XLA-implicit gathers (no gather scheduling control)",
                ranks=[0])
            return
        self.zero3_scheduler = sched
        log_dist(
            "ZeRO-3 runtime: gather/release scheduler on "
            f"(prefetch_layers={sched.prefetch_layers}, "
            f"release_after_use={sched.release_after_use}, "
            f"gather_dtype={zc.stage3_gather_dtype}) — live full-param "
            f"bytes bounded by {sched.prefetch_layers + 1} layers"
            if sched.release_after_use else
            "ZeRO-3 runtime: NAIVE up-front gather "
            "(stage3.release_after_use=false) — the whole param stack "
            "is gathered at step start and held live; this is the "
            "bench baseline, not a memory-bounded mode", ranks=[0])

    def zero_stage3_config(self):
        """The zero_optimization.stage3 block (explicit stage-3
        gather/release runtime; runtime/zero/stage3.py)."""
        zc = self._config.zero_config
        return dict(enabled=zc.stage3_enabled,
                    prefetch_layers=zc.stage3_prefetch_layers,
                    release_after_use=zc.stage3_release_after_use,
                    gather_dtype=zc.stage3_gather_dtype)

    def _register_memory_ledger(self):
        """Register the engine's long-lived device state groups with
        the monitor's memory ledger (monitor/memory.py). Init-time
        shape/sharding metadata only — per-device bytes come from
        `sharding.shard_shape`, so ZeRO-sharded groups register what
        ONE device actually holds. Runs unconditionally (the ledger is
        a dict; there is no per-step cost)."""
        from deepspeed_tpu.monitor import memory as _mem
        led = self.monitor.ledger
        st = self.state
        led.register_tree(_mem.CAT_PARAMS, "engine.params", st.params)
        if st.master is not None:
            led.register_tree(_mem.CAT_MASTER, "engine.master_fp32",
                              st.master)
        if st.opt_state:
            led.register_tree(_mem.CAT_OPT, "engine.opt_state",
                              st.opt_state)
        if st.acc_grads:
            led.register_tree(_mem.CAT_GRADS, "engine.acc_grads",
                              st.acc_grads)
        if getattr(self, "zero3_scheduler", None) is not None:
            # stage-3 gathered-param prefetch window: a DYNAMIC entry —
            # the scheduler learns its per-layer bytes when the first
            # step traces, and the ledger samples it at each fence, so
            # OOM forensics can name stage3.prefetch_layers as the knob
            led.register_dynamic(
                _mem.CAT_ZERO3, "zero3.gather_window",
                self.zero3_scheduler.live_window_bytes)
        if getattr(self, "_moe_active", False):
            # MoE all-to-all dispatch buffers: the [E, C, H] send +
            # expert-output recv pair per MoE layer — per-layer bytes
            # learned at trace time (deepspeed_tpu/moe/dispatch.py),
            # times the model's MoE layer count. DYNAMIC like
            # zero3_gather: 0 until the first step traces; OOM
            # forensics can then name moe.capacity_factor as the knob
            from deepspeed_tpu.moe.dispatch import \
                dispatch_bytes_per_layer
            info = getattr(self.module, "moe_info", lambda: None)()
            n_moe_layers = int((info or {}).get("moe_layers", 1))
            n_experts = (info or {}).get("num_experts")
            width = (info or {}).get("width")
            mesh = self.mesh
            led.register_dynamic(
                _mem.CAT_MOE, "moe.dispatch_buffers",
                lambda: dispatch_bytes_per_layer(
                    mesh, num_experts=n_experts,
                    width=width) * n_moe_layers)
        # comm/compute overlap in-flight staging (MoE dispatch window,
        # ring send/recv rotations): per-device bytes registered by
        # the sites at trace time (ops/overlap.py record_inflight) —
        # DYNAMIC like zero3_gather: 0 until the first step traces and
        # 0 whenever every site resolves to overlap-off; OOM forensics
        # can then name overlap.issue_distance as the knob
        from deepspeed_tpu.ops import overlap as _overlap
        led.register_dynamic(
            _mem.CAT_OVERLAP, "overlap.inflight_window",
            _overlap.inflight_bytes)

    def _count_model_params(self, tree):
        """Model parameter count for logs/profiling; engines whose
        stored layout carries padding override this."""
        return sum(int(np.prod(l.shape)) for l in
                   jax.tree_util.tree_leaves(tree))

    # ------------------------------------------------------------------
    # jitted step functions
    # ------------------------------------------------------------------
    def _scaled_loss_fn(self, params, batch, rng, loss_scale, keep_prob):
        """Returns (scaled_loss, (raw_loss, act_stats, router_stats)).
        act_stats is None unless numerics health is on AND the model
        resolution provided a boundary-tapping loss
        (`_loss_and_health_fn`); router_stats ([E+2] device vector —
        per-expert load, drop fraction, aux loss) is None unless an
        MoE model is wired AND the monitor drains it at fences."""
        gas = self._jit_gas()
        # "quant" is the per-step stream the quantized-compute family's
        # stochastic rounding consumes (decorrelated from dropout by the
        # fold; models without quantized modules never draw from it)
        rngs = {"dropout": rng, "params": rng,
                "quant": jax.random.fold_in(rng, 0x51)}
        kwargs = {}
        if self.progressive_layer_drop is not None:
            kwargs["layer_keep_prob"] = keep_prob
        rstats = None
        if self._numerics_on and self._loss_and_health_fn is not None:
            loss, acts = self._loss_and_health_fn(
                params, batch, rngs=rngs, deterministic=False, **kwargs)
        elif self._moe_stats_on:
            # the stats already live in the traced loss graph (the aux
            # term consumes them) — returning them adds no compute,
            # and they stay device-side until the monitor fence
            loss, rstats = self._loss_fn(
                params, batch, rngs=rngs, deterministic=False,
                return_router_stats=True, **kwargs)
            acts = None
        else:
            loss = self._loss_fn(params, batch, rngs=rngs,
                                 deterministic=False, **kwargs)
            acts = None
        return loss * (loss_scale / gas), (loss, acts, rstats)

    def _micro_grad(self, params, batch, rng, loss_scale, keep_prob):
        """(raw_loss, grads, act_stats, router_stats) for one
        microbatch; act_stats is None unless numerics activation
        tapping is active, router_stats unless MoE stats are on."""
        if self._use_shardmap_grads:
            loss, grads = self._micro_grad_shardmap(params, batch, rng,
                                                    loss_scale, keep_prob)
            return loss, grads, None, None
        grad_fn = jax.value_and_grad(self._scaled_loss_fn, has_aux=True)
        (_, (raw_loss, acts, rstats)), grads = grad_fn(
            params, batch, rng, loss_scale, keep_prob)
        if not (self.bf16_sr_mode and self._jit_gas() == 1):
            # fp32 grads for accumulation / the fp32-master update. In
            # SR mode at gas=1 they stay in compute dtype: the update
            # math casts per-leaf inside its fused elementwise chain,
            # and a whole-tree fp32 cast here would MATERIALIZE a
            # params-sized fp32 tree (6.2 GB at 1.5B) at peak memory.
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)
        # pad-plan leaves: grads join the encoded (padded) layout here so
        # accumulator/master/update shapes all agree; padding is zeros
        grads = self.zero_policy.encode(grads, self._zero_pad_plan)
        grads = jax.lax.with_sharding_constraint(
            grads, self._acc_shardings)
        return raw_loss, grads, acts, rstats

    def _sparse_grad_paths(self):
        if not self.sparse_gradients_enabled():
            return ()
        return tuple(getattr(self.module, "sparse_grad_paths",
                             lambda: ())())

    def _micro_grad_shardmap(self, params, batch, rng, loss_scale,
                             keep_prob):
        """Gradients via an explicit shard_map over the data axis, so
        per-leaf collectives can diverge from dense psum: embedding
        grads ride the CSR all-gather (ref `engine.py:1190-1246`) and
        1-bit Adam's compressed allreduce gets a real axis to run over.
        Only used at ZeRO stage 0 (params replicated), matching the
        reference, whose CSR path lives in the non-ZeRO fallback
        (`engine.py:836,1160`)."""
        from deepspeed_tpu.runtime.compat import shard_map
        from deepspeed_tpu.runtime.csr_tensor import csr_mean_rows

        sparse_paths = self._sparse_grad_paths()
        mesh = self.mesh

        kp_is_none = keep_prob is None

        def per_shard(params, batch, rng, loss_scale, kp):
            kp = None if kp_is_none else kp
            rng = jax.random.fold_in(
                rng, jax.lax.axis_index(DATA_AXIS))
            grad_fn = jax.value_and_grad(self._scaled_loss_fn,
                                         has_aux=True)
            # act/router stats are dropped on the CSR shard_map path
            # (its out_specs predate numerics health; stage-0 sparse
            # models still get grad-group stats from the update tail)
            (_, (raw_loss, _acts, _rstats)), grads = grad_fn(
                params, batch, rng, loss_scale, kp)
            tokens = int(np.prod(
                jax.tree_util.tree_leaves(batch)[0].shape))

            flat = jax.tree_util.tree_flatten_with_path(grads)
            leaves = []
            for path, g in flat[0]:
                key = jax.tree_util.keystr(path)
                g = g.astype(jnp.float32)
                if any(p in key for p in sparse_paths) and g.ndim == 2:
                    capacity = min(g.shape[0], tokens)
                    g = csr_mean_rows(g, DATA_AXIS, capacity)
                else:
                    g = jax.lax.pmean(g, DATA_AXIS)
                leaves.append(g)
            grads = jax.tree_util.tree_unflatten(flat[1], leaves)
            return jax.lax.pmean(raw_loss, DATA_AXIS), grads

        P = PartitionSpec

        def batch_spec(x):
            return P(DATA_AXIS, *([None] * (x.ndim - 1)))

        batch_specs = jax.tree_util.tree_map(batch_spec, batch)
        kp_in = jnp.float32(0.0) if kp_is_none else keep_prob
        raw_loss, grads = shard_map(
            per_shard, mesh=mesh,
            in_specs=(P(), batch_specs, P(), P(), P()),
            out_specs=(P(), P()),
            check_vma=False)(params, batch, rng, loss_scale, kp_in)
        return raw_loss, grads

    def _unscale_clip_and_update(self, state: EngineState, lr,
                                 grads=None, transform=None,
                                 local_axis=None, with_health=True):
        """Tail of the step: unscale, overflow vote, clip, cond-update.
        `grads` (gas=1 fast path) bypasses the persistent accumulator.
        `transform` overrides self.optimizer_transform (1-bit Adam's
        compressed-phase program). `local_axis`: set when running
        per-shard inside shard_map with LOCAL grads — the norm becomes
        sqrt(psum(|g_w|^2)/W) (exact when shards agree, conservative
        otherwise, and continuous with the warmup path's global norm at
        the phase transition), the clip factor derived from it is
        identical on every worker, and sharding constraints (illegal
        inside shard_map) are skipped."""
        if transform is None:
            transform = self.optimizer_transform
        scale = state.scale.loss_scale
        grads = grads if grads is not None else state.acc_grads
        if self.fp16_mode:
            grads = jax.tree_util.tree_map(lambda g: g / scale, grads)
        # else: scale is statically 1.0 — dividing by the traced fp32
        # scalar would type-promote every bf16 grad leaf to fp32 with two
        # consumers (norm + update), letting XLA materialize a full fp32
        # grad tree at peak in SR gas=1 mode
        clip = self.gradient_clipping()
        if self._numerics_on and with_health:
            # per-group numerics health on the UNSCALED grads (norm /
            # absmax / nonfinite flag per top-level group — the
            # overflow source). The per-leaf sum-of-squares pass is
            # computed ONCE and shared with the global norm below, so
            # with clipping/fp16 the accumulators add exactly one new
            # reduction pass (absmax) per leaf to the jitted step
            from deepspeed_tpu.monitor import numerics as _num
            sq_tree = _num.leaf_sumsq(grads)
            health_grad = _num.grad_group_stats(grads, sq_tree=sq_tree)
        else:
            sq_tree = None
            health_grad = None
        if self.fp16_mode or (clip and clip > 0):
            grad_norm = jnp.sqrt(jnp.sum(jnp.stack(
                jax.tree_util.tree_leaves(sq_tree)))) \
                if sq_tree is not None else _global_norm(grads)
        else:
            # nothing consumes the norm (no overflow vote off-fp16, no
            # clip): computing it anyway costs a full extra HBM read of
            # the grad tree (~3 GB at 1.5B) purely for logging
            grad_norm = jnp.float32(0.0)
        if local_axis is not None:
            w = self.mesh.shape[local_axis]
            grad_norm = jnp.sqrt(
                jax.lax.psum(grad_norm * grad_norm, local_axis) / w)
        if self.fp16_mode:
            overflow = ~jnp.isfinite(grad_norm)
        else:
            overflow = jnp.asarray(False)

        if clip and clip > 0:
            factor = jnp.minimum(1.0, clip / (grad_norm + 1e-6))
            factor = jnp.where(jnp.isfinite(factor), factor, 1.0)
            # factor cast to each leaf's dtype: an fp32 scalar multiply
            # would re-widen bf16 grads outside the fused update chain
            grads = jax.tree_util.tree_map(
                lambda g: g * factor.astype(g.dtype), grads)

        sr_padded = self.bf16_sr_mode and bool(self._zero_pad_plan)
        if self.mixed_precision:
            opt_target = state.master
        elif sr_padded:
            # moments/grads live padded; join them for the update and
            # slice the padding back off for the stored params
            opt_target = self.zero_policy.encode(state.params,
                                                 self._zero_pad_plan)
        else:
            opt_target = state.params

        def do_update(target, opt_state):
            opt_state = self._with_lr(opt_state, lr)
            updates, new_opt = transform.update(
                grads, opt_state, target)
            if self.bf16_sr_mode:
                # fp32 updates land on bf16 params via stochastic
                # rounding — a deterministic bf16 add would swallow
                # updates below ulp(p) (bf16_optimizer.py docstring)
                from deepspeed_tpu.runtime.bf16_optimizer import \
                    stochastic_round_apply
                key = jax.random.fold_in(jax.random.PRNGKey(17),
                                         state.global_steps)
                new_target = stochastic_round_apply(target, updates, key)
            else:
                new_target = optax.apply_updates(target, updates)
            return new_target, new_opt

        def skip_update(target, opt_state):
            return target, opt_state

        if self.fp16_mode:
            new_target, new_opt = jax.lax.cond(
                overflow, skip_update, do_update, opt_target,
                state.opt_state)
        else:
            # overflow is statically False without fp16 loss scaling —
            # a lax.cond here would keep BOTH branches' outputs alive
            # (the skip branch returns the old params), blocking buffer
            # donation of params/opt_state into the update at exactly
            # the step's peak-memory point
            new_target, new_opt = do_update(opt_target, state.opt_state)

        if self.mixed_precision:
            new_master = new_target if local_axis is not None else \
                jax.lax.with_sharding_constraint(
                    new_target, self._master_pspecs_cached)
            new_params = jax.tree_util.tree_map(
                lambda m: m.astype(self.compute_dtype),
                self.zero_policy.decode(new_master, self._zero_pad_plan))
            if local_axis is None:
                new_params = jax.lax.with_sharding_constraint(
                    new_params, self._param_pspecs_cached)
        else:
            new_master = None
            if sr_padded:
                new_target = self.zero_policy.decode(new_target,
                                                     self._zero_pad_plan)
            new_params = new_target if local_axis is not None else \
                jax.lax.with_sharding_constraint(
                    new_target, self._param_pspecs_cached)

        dyn_args = self.dynamic_loss_scale_args() or {}
        new_scale = update_loss_scale(
            state.scale, overflow,
            scale_window=dyn_args.get(SCALE_WINDOW, 1000),
            min_scale=dyn_args.get(MIN_LOSS_SCALE, 1.0),
            delayed_shift=dyn_args.get(DELAYED_SHIFT, 2),
            dynamic=self.dynamic_loss_scale_enabled)

        if self._jit_gas() == 1 and not self._offload_enabled():
            new_acc = ()
        else:
            new_acc = _zeros_like_f32(state.acc_grads)
        new_state = EngineState(
            params=new_params, master=new_master, opt_state=new_opt,
            scale=new_scale,
            acc_grads=new_acc,
            skipped=state.skipped + overflow.astype(jnp.int32),
            global_steps=state.global_steps +
            (1 - overflow.astype(jnp.int32)))
        return new_state, overflow, grad_norm, health_grad

    def _resolve_step_lr(self, state, lr):
        """Inside-jit lr resolution: under async dispatch the host
        passes lr=None and the schedule is evaluated HERE, on the
        device-side count of successful steps — no host scalar ever
        rides the step. `global_steps` doesn't advance on an fp16
        overflow skip, so the schedule holds still across skipped
        steps exactly like the reference's host-side rewind. lr=None
        with no device schedule (client optax optimizer) passes
        through to `_with_lr`'s leave-untouched path."""
        if lr is None and self._device_lr_fn is not None:
            return self._device_lr_fn(state.global_steps)
        return lr

    def _with_lr(self, opt_state, lr):
        """Override injected learning_rate hyperparam with a traced scalar.
        lr=None (client optimizer with no scheduler) leaves the client's
        own learning rate untouched."""
        if lr is None:
            return opt_state
        if hasattr(opt_state, "hyperparams") and \
                "learning_rate" in opt_state.hyperparams:
            hp = dict(opt_state.hyperparams)
            hp["learning_rate"] = jnp.asarray(lr, jnp.float32)
            return opt_state._replace(hyperparams=hp)
        return opt_state

    def _scan_microbatches(self, micro_fn, acc0, stacked_batch, rng, gas,
                           force_scan=False):
        """Accumulate over the gas microbatches of a stacked [gas, ...]
        batch. micro_fn(mb, rng) -> (loss, grads, act_stats,
        router_stats). Returns (grads_or_acc, mean_loss, act_stats,
        router_stats) — act_stats ([L,3] device numerics health, or
        None) reduced over microbatches (max/mean/sum per column),
        router_stats ([E+2], or None) averaged over microbatches.
        gas==1 skips the accumulator and the per-microbatch rng fold
        (grads flow straight to the update) unless force_scan — the
        offload path always accumulates into its persistent buffer."""
        if gas == 1 and not force_scan:
            mb = jax.tree_util.tree_map(lambda x: x[0], stacked_batch)
            loss, grads, acts, rstats = micro_fn(mb, rng)
            return grads, loss, acts, rstats

        def body(carry, mb):
            acc, i = carry
            loss, grads, acts, rstats = micro_fn(
                mb, jax.random.fold_in(rng, i))
            acc = jax.tree_util.tree_map(jnp.add, acc, grads)
            # acts/rstats=None are empty pytrees: scan stacks nothing
            return (acc, i + 1), (loss, acts, rstats)

        (acc, _), (losses, acts, rstats) = jax.lax.scan(
            body, (acc0, jnp.asarray(0, jnp.int32)), stacked_batch,
            length=gas)
        if acts is not None:
            from deepspeed_tpu.monitor import numerics as _num
            acts = _num.combine_act_microbatches(acts)
        if rstats is not None:
            # [gas, E+2] -> [E+2]: every entry (load/drop fractions,
            # aux) is a per-step mean quantity — average over the
            # accumulation window
            rstats = jnp.mean(rstats, axis=0)
        return acc, jnp.mean(losses), acts, rstats

    def _build_step_fns(self):
        mesh = self.mesh
        self._master_pspecs_cached = jax.tree_util.tree_map(
            lambda s: s, self._master_shardings)
        self._param_pspecs_cached = self._param_shardings

        # Explicit shard_map grads: needed when per-leaf DP collectives
        # diverge from dense psum (CSR sparse embedding grads).  Gated
        # to stage 0 with a pure data mesh — the same scope as the
        # reference's buffered_allreduce_fallback CSR path.
        self._use_shardmap_grads = (
            self._pure_data_mesh() and bool(self._sparse_grad_paths()))
        if self.sparse_gradients_enabled() and \
                not self._use_shardmap_grads and \
                self.mesh.shape[DATA_AXIS] > 1:
            logger.warning(
                "sparse_gradients requested but unavailable here "
                "(needs zero stage 0, a pure-data mesh, and a model "
                "exposing sparse_grad_paths()); using dense reduction")

        def micro_grad_fn(params, batch, rng, loss_scale, keep_prob):
            return self._micro_grad(params, batch, rng, loss_scale, keep_prob)

        self._micro_grad_jit = jax.jit(micro_grad_fn)

        def accum_fn(acc, grads):
            return jax.tree_util.tree_map(jnp.add, acc, grads)

        self._accum_jit = jax.jit(accum_fn, donate_argnums=(0,))

        def apply_fn(state, lr):
            lr = self._resolve_step_lr(state, lr)
            return self._unscale_clip_and_update(state, lr)

        self._apply_jit = jax.jit(apply_fn, donate_argnums=(0,))

        gas = self._jit_gas()

        if self._offload_enabled():
            self._build_offload_fns()

            def fused_grads_only(state, stacked_batch, rng, keep_prob):
                micro = lambda mb, r: self._micro_grad(
                    state.params, mb, r, state.scale.loss_scale, keep_prob)
                acc, loss, acts, rstats = self._scan_microbatches(
                    micro, state.acc_grads, stacked_batch, rng, gas,
                    force_scan=True)
                return state._replace(acc_grads=acc), loss, acts, rstats

            self._offload_grads_jit = jax.jit(fused_grads_only,
                                              donate_argnums=(0,))

        def fused_train_step(state, stacked_batch, rng, lr, keep_prob):
            """scan over gas microbatches then update; one compile."""
            lr = self._resolve_step_lr(state, lr)
            micro = lambda mb, r: self._micro_grad(
                state.params, mb, r, state.scale.loss_scale, keep_prob)
            out, loss, acts, rstats = self._scan_microbatches(
                micro, state.acc_grads, stacked_batch, rng, gas)
            if gas == 1:
                # no accumulator: grads flow straight into the update
                new_state, overflow, grad_norm, hgrad = \
                    self._unscale_clip_and_update(state, lr, grads=out)
            else:
                state = state._replace(acc_grads=out)
                new_state, overflow, grad_norm, hgrad = \
                    self._unscale_clip_and_update(state, lr)
            health = {"grad": hgrad, "act": acts} \
                if self._numerics_on else None
            return new_state, loss, overflow, grad_norm, health, rstats

        self._fused_step_jit = jax.jit(fused_train_step,
                                       donate_argnums=(0,))

        self._onebit_compressed_active = False
        self._onebit_warned_manual = False
        if self._use_onebit_shardmap:
            self._build_onebit_compressed_step()

        def eval_fn(params, batch):
            return self._loss_fn(params, batch, rngs=None,
                                 deterministic=True)

        self._eval_jit = jax.jit(eval_fn)

    def _build_onebit_compressed_step(self):
        """Compressed-phase 1-bit Adam step (ref `onebit_adam.py:330-372`):
        the whole train step runs inside one shard_map over the data
        axis. Gradients stay LOCAL to each data shard — there is no
        dense reduction anywhere in this program (the reference
        achieves this by flipping `enable_backward_allreduce = False`
        at freeze_step) — and the only cross-shard traffic is the
        bit-packed sign payload + one fp32 scale per worker inside
        `compressed_allreduce` (~1/32 of the dense fp32 wire volume).
        Params/opt-state are replicated in and provably identical out:
        every shard decodes the same gathered signs, so the update is
        deterministic across workers."""
        from deepspeed_tpu.runtime.compat import shard_map
        from deepspeed_tpu.runtime.fp16.onebit_adam import onebit_adam

        transform = onebit_adam(**self._onebit_kwargs,
                                axis_name=DATA_AXIS,
                                static_phase="compressed")
        mesh = self.mesh
        gas = self._jit_gas()

        def local_step(state, stacked_batch, rng, lr, keep_prob):
            lr = self._resolve_step_lr(state, lr)

            def micro(mb, mb_rng):
                mb_rng = jax.random.fold_in(
                    mb_rng, jax.lax.axis_index(DATA_AXIS))
                grad_fn = jax.value_and_grad(self._scaled_loss_fn,
                                             has_aux=True)
                (_, (raw_loss, _acts, _rstats)), grads = grad_fn(
                    state.params, mb, mb_rng, state.scale.loss_scale,
                    keep_prob)
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), grads)
                # numerics health + router stats are dropped on the
                # compressed 1-bit path (its shard_map out_specs
                # predate them)
                return (jax.lax.pmean(raw_loss, DATA_AXIS), grads,
                        None, None)

            grads, loss, _acts, _rstats = self._scan_microbatches(
                micro, _zeros_like_f32(state.params), stacked_batch,
                rng, gas)
            # with_health=False: nothing consumes health here — don't
            # even trace the stat reductions on the compressed path
            new_state, overflow, grad_norm, _hgrad = \
                self._unscale_clip_and_update(
                    state, lr, grads=grads, transform=transform,
                    local_axis=DATA_AXIS, with_health=False)
            return new_state, loss, overflow, grad_norm

        P = PartitionSpec

        def state_specs(state):
            """Everything replicated EXCEPT worker_error, whose leading
            [dp] dim is sharded over data: each worker owns its error-
            feedback slice (it diverges per worker by construction, so
            declaring it replicated would silently collapse it on
            checkpoint/reshard)."""
            specs = jax.tree_util.tree_map(lambda _: P(), state)
            return specs._replace(opt_state=specs.opt_state._replace(
                worker_error=jax.tree_util.tree_map(
                    lambda w: P(DATA_AXIS, *([None] * (w.ndim - 1))),
                    state.opt_state.worker_error)))

        def compressed_step(state, stacked_batch, rng, lr, keep_prob):
            batch_specs = stacked_batch_pspecs(stacked_batch)
            st_specs = state_specs(state)
            new_state, loss, overflow, grad_norm = shard_map(
                local_step, mesh=mesh,
                in_specs=(st_specs, batch_specs, P(), P(), P()),
                out_specs=(st_specs, P(), P(), P()),
                check_vma=False)(state, stacked_batch, rng, lr,
                                 keep_prob)
            # arity parity with _fused_step_jit (no numerics health or
            # router stats on the compressed path)
            return new_state, loss, overflow, grad_norm, None, None

        self._onebit_compressed_jit = jax.jit(compressed_step,
                                              donate_argnums=(0,))

    # ------------------------------------------------------------------
    # data path
    # ------------------------------------------------------------------
    def deepspeed_io(self, dataset, batch_size=None, route=C.ROUTE_TRAIN,
                     pin_memory=None, data_sampler=None, collate_fn=None,
                     num_local_io_workers=None):
        if route not in C.ROUTES:
            raise ValueError(
                f"deepspeed_io route must be one of {list(C.ROUTES)}, "
                f"got {route!r}")
        if batch_size is None:
            # Each process loads its share of the *global* microbatch
            # (micro_bs is per-device; one controller may host many devices).
            devices_per_process = max(
                1, self.dp_world_size // jax.process_count())
            batch_size = self.train_micro_batch_size_per_gpu() * \
                devices_per_process
        return DeepSpeedDataLoader(
            dataset=dataset,
            batch_size=batch_size,
            collate_fn=collate_fn or self.collate_fn,
            local_rank=jax.process_index(),
            tput_timer=self.tput_timer if route == C.ROUTE_TRAIN else None,
            data_parallel_world_size=jax.process_count(),
            data_parallel_rank=jax.process_index())

    def _shard_batch(self, batch):
        """Device-put a host batch with batch-dim sharding over the mesh."""
        def put(x):
            x = np.asarray(x)
            return jax.device_put(x, data_sharding(self.mesh, x.ndim))
        return jax.tree_util.tree_map(put, batch)

    # ------------------------------------------------------------------
    # train API
    # ------------------------------------------------------------------
    def _jit_gas(self):
        """Microbatch count the fused jitted step scans over. Pipeline
        engines fold microbatching inside the loss and override this."""
        return self.gradient_accumulation_steps()

    def _microbatches_per_step(self):
        """Microbatches consumed per train_batch call (micro_steps and
        throughput accounting); pipeline engines override."""
        return self._jit_gas()

    def is_gradient_accumulation_boundary(self):
        return (self.micro_steps + 1) % self.gradient_accumulation_steps() == 0

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _keep_prob(self):
        if self.progressive_layer_drop is not None:
            return jnp.asarray(self.progressive_layer_drop.get_theta(),
                               jnp.float32)
        return self._keep_prob_one

    def _spans_active(self):
        """Record fwd/bwd/step spans when wall_clock_breakdown is on OR
        a Perfetto trace is being exported (monitor.trace.enabled) —
        the exporter renders the same fence-free spans as slices."""
        return self.wall_clock_breakdown() or \
            self.monitor.trace_export is not None

    def forward(self, batch, **kwargs):
        """Compute loss (and cache grads for `backward`)."""
        if self._spans_active():
            # fence-free span (monitor/trace.py): host dispatch time +
            # profiler TraceAnnotation, reported at sync fences — the
            # legacy path barriered the device TWICE per microstep here
            self.monitor.trace.start(SPAN_FORWARD)
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self._host_steps)
        batch = self._shard_batch(batch)
        self._tokens_pending += _batch_token_count(batch)
        # legacy-loop twin of train_batch's accounting: here batch is
        # ONE microbatch [rows, ...], so tokens/sample = trailing dims
        # (the deepspeed_io dataloader drives the tput timer on this
        # path, and the monitor's MFU derivation needs the ratio)
        lead = np.shape(jax.tree_util.tree_leaves(batch)[0]) \
            if jax.tree_util.tree_leaves(batch) else ()
        self._tokens_per_sample = int(np.prod(lead[1:])) \
            if len(lead) > 1 else 1
        loss, grads, acts, rstats = self._micro_grad_jit(
            self.state.params, batch, self._next_rng(),
            self.state.scale.loss_scale, self._keep_prob())
        self._pending_grads = grads
        self._pending_loss = loss
        # numerics health / router stats, manual path: the LAST
        # microbatch's stats stand in for the accumulation window
        # (device arrays, no sync; folded at the model step)
        self._pending_acts = acts
        self._pending_router = rstats
        if self._spans_active():
            self.monitor.trace.stop(SPAN_FORWARD)
        return loss

    __call__ = forward

    def backward(self, loss=None, allreduce_gradients=True, release_loss=False):
        """Fold the cached microbatch grads into the accumulator.

        release_loss=True drops the engine's own reference to the loss
        buffer (ref engine.py:934): `engine.losses` stays None and the
        device buffer frees as soon as the caller's reference dies —
        use it when the loop never reads `engine.losses`."""
        assert self._pending_grads is not None, \
            "backward() called without a preceding forward()"
        if self._spans_active():
            self.monitor.trace.start(SPAN_BACKWARD)
        if not jax.tree_util.tree_leaves(self.state.acc_grads):
            # gas=1 fast path keeps no persistent accumulator; the first
            # (only) microbatch's grads stand in directly
            acc = self._pending_grads
        else:
            acc = self._accum_jit(self.state.acc_grads,
                                  self._pending_grads)
        self.state = self.state._replace(acc_grads=acc)
        self._pending_grads = None
        if release_loss:
            self._pending_loss = None
            self.losses = None
        else:
            self.losses = loss if loss is not None else self._pending_loss
        if self._spans_active():
            self.monitor.trace.stop(SPAN_BACKWARD)
        return loss

    def _release_pending_loss(self):
        """Drop the forward()-cached loss reference at the end of
        step(): keeping it pinned would hold one stale device buffer
        alive across every subsequent step."""
        self._pending_loss = None

    def step(self, lr_kwargs=None):
        """Advance one micro step; at the grad-accum boundary, apply the
        model step (ref engine.py:955-1078)."""
        if self._spans_active():
            self.monitor.trace.start(SPAN_STEP)
        if self.is_gradient_accumulation_boundary():
            self._take_model_step(lr_kwargs)
        self.micro_steps += 1
        self._release_pending_loss()
        if self._spans_active():
            self.monitor.trace.stop(SPAN_STEP)

    def _take_model_step(self, lr_kwargs=None):
        lr = self._host_step_lr()
        tokens = self._tokens_pending
        self._tokens_pending = 0
        if self._offload_enabled():
            overflow = self._offload_take_step(lr)
            self._host_steps += 1
            if self.monitor.enabled:
                health = None
                if self._numerics_on:
                    health = {"grad": None,
                              "act": getattr(self, "_pending_acts",
                                             None)}
                    self._pending_acts = None
                router = self._pending_router
                self._pending_router = None
                self.monitor.on_step(
                    loss=self.losses, grad_norm=self._offload_last_norm,
                    loss_scale=self._host_scaler.cur_scale,
                    overflow=overflow, tokens=tokens,
                    wire_stats=self.wire_stats, health=health,
                    router=router)
            self._after_model_step(jnp.asarray(overflow))
            return
        if self._use_onebit_shardmap and not self._onebit_warned_manual \
                and self._host_steps >= self._onebit_freeze_step:
            # the compressed program exists only on the fused
            # train_batch path; the manual API would run warmup Adam
            # forever past freeze_step — say so once
            logger.warning(
                "OnebitAdam: forward()/backward()/step() never enters "
                "the compressed phase; use train_batch() to get the "
                "bit-packed collective past freeze_step")
            self._onebit_warned_manual = True
        self.state, overflow, grad_norm, hgrad = \
            self._apply_jit(self.state, lr)
        self._host_steps += 1
        if self.monitor.enabled:
            health = None
            if self._numerics_on:
                health = {"grad": hgrad,
                          "act": getattr(self, "_pending_acts", None)}
                self._pending_acts = None
            router = self._pending_router
            self._pending_router = None
            self.monitor.on_step(
                loss=self.losses, grad_norm=grad_norm,
                loss_scale=self.state.scale.loss_scale,
                overflow=overflow, tokens=tokens, health=health,
                router=router)
        self._after_model_step(overflow)

    def _next_lr(self):
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
            return float(self.lr_scheduler.get_last_lr()[0])
        if self._base_lr is None:
            # Client optax optimizer: its own schedule/lr applies unchanged.
            return None
        return float(self._base_lr)

    def _host_step_lr(self):
        """Per-step host half of the lr plumbing. Sync mode: advance
        the scheduler and return the concrete scalar (uploaded as a
        step argument). Async mode: advance the host scheduler as an
        OPTIMISTIC mirror — pure Python, no device work, exact except
        across fp16 overflow skips (fence-corrected) — and return None:
        the jitted step computes the lr on device."""
        if not self._async_dispatch:
            return self._next_lr()
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        return None

    def _sync_scheduler_mirror(self):
        """Correct the optimistic host scheduler mirror from the device
        step counter (one device_get). Only fp16 overflow skips can make
        the mirror drift, so this is a no-op everywhere else."""
        if self._async_dispatch and self.fp16_mode and \
                self.lr_scheduler is not None:
            gs = int(jax.device_get(self.state.global_steps))
            if self.lr_scheduler.last_batch_iteration != gs - 1:
                self.lr_scheduler.step(gs - 1)

    def _after_model_step(self, overflow):
        if self.fp16_mode and not self._async_dispatch:
            # Legacy synced loop: host-side scheduler rewind (parity:
            # scheduler doesn't advance past an overflow step in the
            # reference). This device_get serializes host and device
            # every step; async mode gets the same semantics for free
            # from the device-resident schedule.
            # ds-lint: allow[HOTSYNC] legacy synced loop only: the deliberate per-step rendezvous async mode exists to delete
            if bool(jax.device_get(overflow)) and \
                    self.lr_scheduler is not None:
                self.lr_scheduler.step(
                    self.lr_scheduler.last_batch_iteration - 1)
        # print fences are fences too: a steps_per_sync that doesn't
        # divide into the print multiples must not suppress
        # steps_per_print output
        if self._host_steps % self._steps_per_sync == 0 or \
                self._host_steps % self.steps_per_print() == 0:
            self._sync_fence()

    def _sync_fence(self):
        """The hot loop's only host<->device rendezvous: refresh the
        scheduler mirror and materialize device metrics (step counters,
        loss, lr, loss scale) for logging/TensorBoard. Runs every
        `steps_per_sync` optimizer steps (default: steps_per_print)."""
        self._sync_scheduler_mirror()
        at_print = self._host_steps % self.steps_per_print() == 0
        spans = None
        if self.monitor.enabled:
            # drains the device metric accumulator (ONE device_get per
            # fence), samples host gauges, emits to sinks, feeds the
            # stall watchdog
            event = self.monitor.on_fence()
            spans = event.get("spans") if event else None
        elif self.wall_clock_breakdown() and at_print:
            # wall_clock_breakdown without the monitor block: the trace
            # still accumulated span times; drain over the full print
            # window so the flag keeps producing output on its own
            spans = self.monitor.trace.drain()
        if at_print and spans:
            log_dist(
                "span ms/step (host dispatch, fence-aligned) | " +
                " | ".join(f"{k}: {v['ms_per']:.2f}"
                           for k, v in spans.items()),
                ranks=[0])
        if self.summary_writer is not None and at_print:
            gs = self.global_steps
            samples = gs * self.train_batch_size()
            self.summary_writer.add_scalar(
                "Train/Samples/lr", self._current_lr(), samples)
            if self.losses is not None:
                self.summary_writer.add_scalar(
                    "Train/Samples/train_loss",
                    float(np.asarray(jax.device_get(self.losses))),
                    samples)
            if self.fp16_mode:
                self.summary_writer.add_scalar(
                    "Train/Samples/loss_scale", self.loss_scale(),
                    samples)
            # the native writer buffers via the file object; make the
            # scalars visible to a live TensorBoard at print cadence
            self.summary_writer.flush()
        if at_print:
            # _current_lr, not get_lr(): the mirror was synced above and
            # get_lr() would pay a second device round trip for it
            log_dist(
                f"step={self.global_steps}, skipped={self.skipped_steps}, "
                f"lr={[self._current_lr()]}, mom={self.get_mom()}",
                ranks=[0])

    def stage_batch(self, batch):
        """Place a stacked [gas, micro_bs, ...] batch pytree on device
        with the engine's batch sharding (dim 1 over the data axis).
        Idempotent: leaves already staged as jax.Arrays skip the host
        np.asarray round trip (which would drag them BACK through the
        host link), and device_put reshards device-side — a no-op when
        the sharding already matches. Input pipelines call this ahead
        of time to prefetch; train_batch applies it to whatever it is
        handed."""
        # expert-parallel devices are data-parallel devices: batch rows
        # divide over (data, expert) when the mesh carries an expert
        # axis (deepspeed_tpu/moe/), over data alone otherwise
        row_axes = (DATA_AXIS, EXPERT_AXIS) \
            if expert_axis_size(self.mesh) > 1 else DATA_AXIS

        def put_stacked(x):
            if not isinstance(x, jax.Array):
                x = np.asarray(x)
            spec = [None] * np.ndim(x)
            if np.ndim(x) > 1:
                spec[1] = row_axes
            return jax.device_put(
                x, NamedSharding(self.mesh, PartitionSpec(*spec)))

        return jax.tree_util.tree_map(put_stacked, batch)

    def prefetch(self, data_source, depth=None, stacked=False):
        """Wrap a microbatch iterable in a background PrefetchLoader:
        collation + `stage_batch` placement run on a worker thread,
        `depth` (default async_dispatch.prefetch_depth) staged batches
        ahead of the step loop. Feed the result to `train_batch` as
        `data_iter`."""
        mon = self.monitor
        loader = PrefetchLoader(
            data_source, stage_fn=self.stage_batch, gas=self._jit_gas(),
            depth=depth if depth is not None else self.prefetch_depth(),
            stacked=stacked,
            heartbeat=(lambda: mon.heartbeat("prefetch"))
            if mon.enabled else None,
            finished=(lambda: mon.heartbeat_done("prefetch"))
            if mon.enabled else None,
            span=(lambda t0, dur: mon.subsystem_span(
                "prefetch", "stage_batch", t0, dur))
            if mon.trace_export is not None else None)
        # queue-occupancy gauge + stall-diagnosis heartbeats ride the
        # live loader
        self.monitor.attach_prefetch(loader)
        return loader

    def train_batch(self, data_iter=None, batch=None):
        """Fast path: one fused jitted step over all grad-accum
        microbatches. Pass an iterator yielding microbatches, a
        PrefetchLoader (pre-staged batches, no host collate here), or a
        pre-stacked batch pytree with leading dim [gas, micro_bs, ...].

        An exception escaping the step loop is a forensic moment: the
        flight recorder (monitor/flight.py) dumps the last events +
        heartbeat ages before it propagates (StopIteration — a merely
        exhausted data iterator — is not a crash)."""
        try:
            return self._train_batch_impl(data_iter=data_iter,
                                          batch=batch)
        except StopIteration:
            raise
        except BaseException as e:
            if self.monitor.enabled and \
                    not getattr(e, "_ds_flight_dumped", False):
                try:
                    e._ds_flight_dumped = True
                except Exception:  # ds-lint: allow[BROADEXC] exotic exception classes may reject attribute marks; dedup is best-effort
                    pass
                self.monitor.on_crash(e)
            raise

    def _train_batch_impl(self, data_iter=None, batch=None):
        gas = self._jit_gas()
        if batch is None:
            assert data_iter is not None
            if isinstance(data_iter, PrefetchLoader):
                # collated + staged on the prefetch worker thread
                batch = next(data_iter)
            else:
                micro = [next(data_iter) for _ in range(gas)]
                batch = jax.tree_util.tree_map(
                    lambda *xs: np.stack([np.asarray(x) for x in xs]),
                    *micro)
        else:
            leading = jax.tree_util.tree_leaves(batch)[0].shape[0]
            assert leading == gas, \
                f"stacked batch leading dim {leading} != gas {gas}"

        self.tput_timer.start()
        batch = self.stage_batch(batch)
        tokens = _batch_token_count(batch)
        # tokens per SAMPLE (static shape math, no device access): the
        # stacked batch is [gas, global_rows, ...] and tput counts
        # samples as rows — the monitor's tokens/s/chip + MFU derive
        # from this times avg_samples_per_sec
        lead = np.shape(jax.tree_util.tree_leaves(batch)[0]) \
            if jax.tree_util.tree_leaves(batch) else ()
        self._tokens_per_sample = int(np.prod(lead[2:])) \
            if len(lead) > 2 else 1
        lr = self._host_step_lr()
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self._host_steps)
        if self.flops_profiler_enabled() and \
                self._host_steps + 1 == self.flops_profiler_profile_step():
            self._profile_fused_step(batch, lr)
        if self._spans_active():
            self.monitor.trace.start(SPAN_STEP)
        health = None
        rstats = None
        if self._offload_enabled():
            self.state, loss, acts, rstats = self._offload_grads_jit(
                self.state, batch, self._next_rng(), self._keep_prob())
            overflow = jnp.asarray(self._offload_take_step(lr))
            grad_norm = None
            if self._numerics_on:
                health = {"grad": None, "act": acts}
        else:
            step_fn = self._fused_step_jit
            if self._use_onebit_shardmap:
                # Host-side phase switch at freeze_step (the XLA-native
                # form of ref onebit_adam.py:372's
                # enable_backward_allreduce flip): one recompile, after
                # which no dense grad reduction exists in the program.
                # Keyed on the OPTIMIZER's step count (like the
                # reference's state['step']) so a reload with
                # load_optimizer_states=False correctly re-warms; the
                # cheap host-step pre-check keeps the warmup hot loop
                # free of device_get syncs (count <= host steps always).
                if not self._onebit_compressed_active and \
                        self._host_steps >= self._onebit_freeze_step and \
                        int(jax.device_get(self.state.opt_state.count)) >= self._onebit_freeze_step:  # ds-lint: allow[HOTSYNC] host-step pre-check gates this fetch to at most one per run (the freeze_step phase switch)
                    self._onebit_compressed_active = True
                    log_dist(
                        "OnebitAdam: entering compressed phase "
                        f"(freeze_step={self._onebit_freeze_step}); "
                        "momentum now rides the bit-packed collective",
                        ranks=[0])
                if self._onebit_compressed_active:
                    step_fn = self._onebit_compressed_jit
            self.state, loss, overflow, grad_norm, health, rstats = \
                step_fn(self.state, batch, self._next_rng(), lr,
                        self._keep_prob())
        if self._spans_active():
            self.monitor.trace.stop(SPAN_STEP)
        mbs = self._microbatches_per_step()
        self.micro_steps += mbs
        self._host_steps += 1
        # losses before the fence: _sync_fence logs THIS step's loss
        self.losses = loss
        if self.monitor.enabled:
            if self._offload_enabled():
                self.monitor.on_step(
                    loss=loss, grad_norm=self._offload_last_norm,
                    loss_scale=self._host_scaler.cur_scale,
                    overflow=overflow, tokens=tokens,
                    wire_stats=self.wire_stats, health=health,
                    router=rstats)
            else:
                self.monitor.on_step(
                    loss=loss, grad_norm=grad_norm,
                    loss_scale=self.state.scale.loss_scale,
                    overflow=overflow, tokens=tokens, health=health,
                    router=rstats)
        self._after_model_step(overflow)
        # one fused step consumed `mbs` microbatches worth of samples
        self.tput_timer.stop(count=mbs)
        return loss

    def _profile_fused_step(self, batch, lr):
        """One-shot HLO cost-analysis profile of the fused train step
        (ref engine.py:803-832 drives FlopsProfiler at profile_step)."""
        from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler
        from deepspeed_tpu.profiling.flops_profiler.profiler import num_params
        prof = FlopsProfiler(self.module)
        prof.total_params = self._count_model_params(self.state.params)
        prof.start_profile()
        # fixed key: profiling must not perturb the training RNG stream
        prof_rng = jax.random.PRNGKey(0)
        try:
            if self._offload_enabled():
                prof.profile_jitted(self._offload_grads_jit, self.state,
                                    batch, prof_rng,
                                    self._keep_prob(), measure_time=False)
            else:
                prof.profile_jitted(self._fused_step_jit, self.state, batch,
                                    prof_rng, lr, self._keep_prob(),
                                    measure_time=False)
        except Exception as e:  # donated-buffer retrace edge cases
            import traceback
            logger.warning(
                f"flops profile failed: {e}\n{traceback.format_exc()}")
            return
        prof.stop_profile()
        prof.print_model_profile(
            profile_step=self.flops_profiler_profile_step(),
            module_depth=self.flops_profiler_module_depth(),
            top_modules=self.flops_profiler_top_modules(),
            detailed=self.flops_profiler_detailed())

    def eval_batch(self, batch):
        batch = self._shard_batch(batch)
        return self._eval_jit(self.state.params, batch)

    def allreduce_gradients(self, bucket_size=MEMORY_OPT_ALLREDUCE_SIZE):
        """No-op under SPMD: gradient reduction is compiled into the step
        (kept for API parity with ref engine.py:836)."""
        return None

    def train(self, mode=True):
        self._training = mode
        return self

    def eval(self):
        return self.train(False)

    # ------------------------------------------------------------------
    # properties
    # ------------------------------------------------------------------
    @property
    def global_steps(self):
        """Total optimizer steps taken (successful + overflow-skipped).
        Every step bumps exactly one of the two device counters, so the
        sum equals the host step mirror EXACTLY (not just optimistically)
        — under async dispatch it is served from the mirror with no
        device sync. Otherwise both counters come back in one fused
        fetch instead of two sequential device_get round trips."""
        if self._async_dispatch:
            return self._host_steps
        gs, sk = jax.device_get((self.state.global_steps,
                                 self.state.skipped))
        return int(gs) + int(sk)

    @property
    def skipped_steps(self):
        return int(jax.device_get(self.state.skipped))

    @property
    def params(self):
        return self.state.params

    def module_state_dict(self):
        """Full fp32 module weights on host (ref `engine.py:1248`);
        multi-host shardings are gathered via process_allgather."""
        return _fetch_to_host(self.fp32_params)

    def _module_ckpt_template(self):
        """Template handed to per-layer checkpoint loaders; engines with
        a non-tree stored layout override this with the logical tree."""
        return self.state.params

    def _module_from_ckpt(self, tree):
        """Convert a loaded logical module tree into the engine's stored
        layout (identity for tree-layout engines)."""
        return tree

    def _logical_module_tree(self, stored):
        """Convert a stored-layout fp32/compute module tree into the
        module's logical tree for serialization (identity here; the
        pipeline engine unflattens its per-stage flat layout)."""
        return stored

    @property
    def fp32_params(self):
        if self._offload_enabled():
            # copy=True: on the CPU backend jnp.asarray may ALIAS the
            # numpy buffer, and _host_master is updated in place by
            # every subsequent optimizer step — a caller holding this
            # tree would silently see it mutate
            return self._offload_unravel(
                jnp.array(self._host_master, copy=True))
        if self.mixed_precision:
            return self.zero_policy.decode(self.state.master,
                                           self._zero_pad_plan)
        return self.state.params

    # ------------------------------------------------------------------
    # checkpointing (ref engine.py:1248-1573; layout preserved)
    # ------------------------------------------------------------------
    def _ckpt_payload(self, state):
        """The checkpoint-facing device trees decoded from live state
        (pad-plan leaves in true unpadded shapes so the checkpoint
        stays elastic across dp sizes)."""
        payload = dict(
            opt_state=self.zero_policy.decode(
                state.opt_state, self._zero_pad_plan,
                suffix_match=True),
            scale=state.scale,
            global_steps=state.global_steps,
            skipped=state.skipped)
        if not self._offload_enabled():
            if self.mixed_precision:
                payload["module"] = self.zero_policy.decode(
                    state.master, self._zero_pad_plan)
            else:
                payload["module"] = state.params
        return payload

    def _build_ckpt_snapshot_fn(self):
        """Jitted snapshot: decode the checkpoint-facing trees from the
        live state and copy every leaf into FRESH buffers. The copies
        cannot alias the state the step functions donate, so training
        can keep stepping while the writer serializes."""
        return jax.jit(lambda state: jax.tree_util.tree_map(
            jnp.copy, self._ckpt_payload(state)))

    def _checkpoint_snapshot(self, client_state, isolate=True):
        """Phase 1 of save_checkpoint — the only part the train loop
        pays for: one jitted device-side copy (dispatched async) plus
        host memcpys of the ZeRO-Offload master/moments/wire state
        (taken before the next host Adam step can mutate them).
        isolate=False (inline writes: sync and multi-process saves)
        skips every copy and serializes straight from live state — the
        legacy sync path's memory profile; nothing steps while an
        inline write runs, so aliasing is safe."""
        if isolate:
            if self._ckpt_snapshot_jit is None:
                self._ckpt_snapshot_jit = self._build_ckpt_snapshot_fn()
            payload = self._ckpt_snapshot_jit(self.state)
        else:
            payload = self._ckpt_payload(self.state)
        snap = dict(
            # PipelineModule-style models write one file per layer so
            # the checkpoint reloads onto any stage partitioning
            # (ref pipe/module.py:536-567)
            per_layer=hasattr(self.module, "save_state_dict") and
            hasattr(self.module, "load_state_dir"),
            payload=payload,
            # _rng buffers are replaced (never donated) by _next_rng,
            # so the reference stays valid without a copy
            rng=self._rng,
            meta=dict(
                micro_steps=self.micro_steps,
                dp_world_size=self.dp_world_size,
                lr_scheduler=self.lr_scheduler.state_dict()
                if self.lr_scheduler else None),
            # deep copy: the caller (and the training loop) may keep
            # mutating nested client_state values while the background
            # writer serializes — the snapshot must freeze them now
            client_state=copy.deepcopy(dict(client_state or {})),
            # the EFFECTIVE stage (may be capped under pipe flat mode);
            # checkpoint metadata must describe what actually ran
            zero_stage=self.zero_policy.stage,
        )
        if self._offload_enabled():
            snap.update(self._offload_checkpoint_snapshot(
                isolate=isolate))
            snap["module"] = self._logical_module_tree(snap["module"])
        else:
            # logical layout for the writer; the pipe engine's override
            # slices the snapshot buffers (still async, no host fetch)
            snap["module"] = self._logical_module_tree(payload["module"])
        return snap

    def _write_checkpoint(self, save_dir, tag, snap, save_latest,
                          commit_gate=None, writer=None):
        """Phase 2 (runs on the background writer thread under
        async_save): device_get the snapshot and serialize into a
        `<tag>.tmp` staging dir, fsync, atomically rename to `<tag>`,
        update `latest` LAST, then rotate per checkpoint.keep_last.
        `commit_gate` (from AsyncCheckpointWriter.submit) orders the
        commit sections of concurrent writers by submission. `writer`
        is the owning AsyncCheckpointWriter: a job whose writer was
        ABANDONED still commits its tag dir but skips the `latest`
        update and rotation (it may be racing a successor engine that
        already committed newer tags)."""
        import time as _time
        write_t0 = _time.perf_counter()
        self.monitor.heartbeat("checkpoint")
        multi_proc = jax.process_count() > 1

        def _barrier(phase):
            # shared-filesystem commit protocol: every process's shard
            # writes must land before process 0 renames, and no process
            # may return before the commit is visible
            if multi_proc:
                from jax.experimental import multihost_utils
                multihost_utils.sync_global_devices(f"ckpt_{phase}_{tag}")

        staging = ckpt_io.staging_dir(save_dir, tag)
        if os.path.exists(staging) and jax.process_index() == 0:
            import shutil
            shutil.rmtree(staging)   # stale leftover of a killed save
        _barrier("begin")
        os.makedirs(staging, exist_ok=True)
        payload = snap["payload"]
        gs, sk = jax.device_get((payload["global_steps"],
                                 payload["skipped"]))
        if snap["per_layer"]:
            # all processes participate (per-layer gathers are
            # collectives on multi-host shardings); proc 0 writes
            self.module.save_state_dict(staging, snap["module"])
        # module/opt_state stay as (possibly sharded) jax arrays: the
        # writer streams each process's addressable shards to its own
        # zero_pp_rank files — no host gather (ref engine.py:1522-1531).
        sd = dict(
            module={} if snap["per_layer"] else snap["module"],
            global_steps=int(gs) + int(sk),
            skipped_steps=int(sk),
            micro_steps=snap["meta"]["micro_steps"],
            dp_world_size=snap["meta"]["dp_world_size"],
            lr_scheduler=snap["meta"]["lr_scheduler"],
            rng=jax.device_get(snap["rng"]),
        )
        sd.update(snap["client_state"])
        optim_sd = dict(
            opt_state=payload["opt_state"],
            scale=jax.device_get(payload["scale"]),
            zero_stage=snap["zero_stage"],
        )
        if "host_adam" in snap:
            optim_sd["host_adam"] = snap["host_adam"]
            optim_sd["host_master"] = snap["host_master"]
            if "offload_wire" in snap:
                optim_sd["offload_wire"] = snap["offload_wire"]
        save_checkpoint_files(save_dir, tag, sd, optim_sd,
                              ckpt_dir=staging)
        _barrier("staged")
        with (commit_gate() if commit_gate is not None
              else contextlib.nullcontext()):
            if jax.process_index() == 0:
                ckpt_io.commit_staging_dir(save_dir, tag)
                stale = writer is not None and writer.abandoned.is_set()
                if stale:
                    logger.warning(
                        f"abandoned checkpoint writer committed tag "
                        f"'{tag}' but is leaving `latest` and rotation "
                        "alone (a successor engine may own them now)")
                if save_latest and not stale:
                    write_latest_tag(save_dir, tag)
                keep_last = self.checkpoint_keep_last()
                if keep_last and not stale:
                    deleted = ckpt_io.rotate_checkpoints(
                        save_dir, keep_last, protect=(tag,))
                    if deleted:
                        log_dist("checkpoint rotation removed "
                                 f"{deleted}", ranks=[0])
        _barrier("committed")
        if self.monitor.enabled:
            # runs on the writer thread under async_save — the monitor
            # event path and counters are thread-safe by contract
            commit_ms = (_time.perf_counter() - write_t0) * 1e3
            self.monitor.registry.inc("ckpt/commits")
            self.monitor.registry.set_counter("ckpt/last_commit_ms",
                                              round(commit_ms, 2))
            self.monitor.heartbeat("checkpoint")
            self.monitor.event(
                "ckpt_commit", tag=str(tag), dir=save_dir,
                wall_ms=round(commit_ms, 2),
                global_steps=int(gs) + int(sk))
        log_dist(f"saved checkpoint {tag} to {save_dir}", ranks=[0])

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True, async_save=None):
        """Snapshot-then-write checkpoint save. With
        checkpoint.async_save (default true) the call returns after the
        device-side snapshot; a background thread serializes into a
        staging dir and commits atomically (`wait_for_checkpoint` is
        the barrier). `async_save` overrides the config per call.
        Returns False only when checkpoint.queue_policy="drop"
        discarded the save under backpressure."""
        # the checkpoint must carry the TRUE schedule position, not the
        # optimistic async mirror (drifts across fp16 overflow skips)
        self._sync_scheduler_mirror()
        if tag is None:
            tag = f"global_step{self.global_steps}"
        # a still-running ABANDONED writer may own this tag's shared
        # `<tag>.tmp` staging dir (recovery replays regenerate the
        # same tag names); writing into it concurrently would commit a
        # torn mix of two saves — skip, the next boundary's tag is free
        for w in list(getattr(self, "_abandoned_ckpt_writers", [])):
            if not w.pending():
                self._abandoned_ckpt_writers.remove(w)
            elif w.tag_in_flight(tag):
                logger.warning(
                    f"skipping checkpoint save '{tag}': an abandoned "
                    "writer still holds this tag's staging dir")
                return False
        if self.checkpoint_tag_validation_enabled():
            validate_checkpoint_tag(
                tag, fail_on_mismatch=self.checkpoint_tag_validation_fail())
        if async_save is None:
            async_save = self.checkpoint_async_save()
        if async_save and jax.process_count() > 1:
            # the shared-dir commit protocol barriers across processes;
            # running those collectives on a writer thread while the
            # main thread dispatches step collectives is a deadlock
            # trap — multi-process saves stay inline
            log_dist(
                "checkpoint.async_save: forced off under multi-process "
                "(the commit barrier is a collective; it must not run "
                "on a background thread)", ranks=[0])
            async_save = False
        if async_save:
            if self._ckpt_writer is None:
                self._ckpt_writer = ckpt_io.AsyncCheckpointWriter(
                    queue_depth=self.checkpoint_writer_queue_depth(),
                    queue_policy=self.checkpoint_queue_policy())
            # queue_policy="drop" decides BEFORE the snapshot is built:
            # a dropped save must not pay the device copy + host
            # memcpys it is dropping
            if not self._ckpt_writer.admit(tag):
                return False
        with self.monitor.trace.span(SPAN_CKPT):
            # the only part of an async save the train loop pays for
            snap = self._checkpoint_snapshot(client_state,
                                             isolate=async_save)
        if not async_save:
            # an in-flight async writer may hold this tag's staging dir
            # or commit `latest` after us — drain it before an inline
            # write touches the same save_dir (the snapshot above has
            # already frozen the state this save will contain)
            self.wait_for_checkpoint()
            self._write_checkpoint(save_dir, str(tag), snap, save_latest)
            return True
        # memory ledger: the snapshot's fresh double-buffers are alive
        # from here until the writer finishes (success or failure) —
        # exactly the window an OOM post-mortem needs attributed
        tokens = self._register_ckpt_snapshot(str(tag), snap)
        led = self.monitor.ledger
        writer = self._ckpt_writer
        try:
            accepted = writer.submit(
                lambda commit_gate: self._write_checkpoint(
                    save_dir, str(tag), snap, save_latest,
                    commit_gate=commit_gate, writer=writer),
                tag,
                on_done=lambda: [led.release(t) for t in tokens])
        except BaseException:
            # submit re-raises pending writer errors BEFORE accepting
            # the job — a leaked entry would pollute every later
            # memory event with a phantom snapshot
            for t in tokens:
                led.release(t)
            raise
        if not accepted:
            for t in tokens:
                led.release(t)
        return accepted

    def _register_ckpt_snapshot(self, tag, snap):
        """Register the isolated snapshot's copies with the memory
        ledger: device payload buffers (per-device bytes) + the
        offload host memcpys. Entry names carry a per-engine sequence
        number — a re-save of the SAME tag while the first write is in
        flight must not replace the first save's entries (whose
        on_done would then release the live second snapshot). Returns
        the tokens the writer's on_done releases."""
        from deepspeed_tpu.monitor import memory as _mem
        led = self.monitor.ledger
        seq = self._ckpt_snap_seq = \
            getattr(self, "_ckpt_snap_seq", 0) + 1
        name = f"snapshot:{tag}@{seq}"
        tokens = [led.register_tree(_mem.CAT_CKPT, name,
                                    snap["payload"])]
        host = 0
        if "host_master" in snap:
            host += int(snap["host_master"].nbytes)
        for v in (snap.get("host_adam") or {}).values():
            if isinstance(v, np.ndarray):
                host += int(v.nbytes)
        for v in (snap.get("offload_wire") or {}).values():
            if isinstance(v, np.ndarray):
                host += int(v.nbytes)
        if host:
            tokens.append(led.register(
                _mem.CAT_CKPT, f"{name}#host", host,
                space=_mem.SPACE_HOST))
        return tokens

    def wait_for_checkpoint(self, timeout=None):
        """Barrier for in-flight async saves: returns once every
        submitted checkpoint is durably committed (staging dir renamed,
        `latest` updated) and re-raises the first background write
        error. load_checkpoint calls this implicitly; call it yourself
        before shutdown or before reading checkpoints externally.

        `timeout` (seconds) bounds the wait: on expiry a
        `CheckpointWaitTimeout` is raised carrying the writer's last
        heartbeat age, so a supervisor can abandon a hung writer
        (`abandon_checkpoint_writers`) and rebuild instead of blocking
        teardown on it. (Writer threads stay non-daemon by design —
        the interpreter never exits mid-write — so abandonment frees
        the ENGINE, not final process exit, from a wedged writer.)"""
        if self._ckpt_writer is None:
            return
        if self._ckpt_writer.wait(timeout):
            return
        hb, _ = self.monitor._heartbeat_state()
        age = hb.get("checkpoint")
        pending = self._ckpt_writer.pending()
        raise ckpt_io.CheckpointWaitTimeout(
            f"{pending} async checkpoint save(s) still in flight after "
            f"{timeout}s; writer heartbeat "
            + (f"{age}s ago" if age is not None else "never seen")
            + " — abandon_checkpoint_writers() detaches them (the "
            "committed `latest` tag is unaffected)",
            pending=pending, heartbeat_age_sec=age)

    def abandon_checkpoint_writers(self):
        """Detach in-flight async save jobs: the engine stops tracking
        (and waiting on) them. Running writer threads finish or fail
        on their own — their tag dirs still commit atomically — but an
        abandoned job no longer moves `latest` or rotates: a stale
        writer unwedging AFTER a successor engine committed newer tags
        must not regress the pointer to an older save. Their errors
        are no longer re-raised into the train loop. Returns the
        number of jobs abandoned. The next save_checkpoint builds a
        fresh writer."""
        writer, self._ckpt_writer = self._ckpt_writer, None
        if writer is None:
            return 0
        writer.abandoned.set()
        # remembered so later saves refuse to touch a tag whose
        # staging dir a still-running abandoned job may own
        self._abandoned_ckpt_writers = [
            w for w in getattr(self, "_abandoned_ckpt_writers", [])
            if w.pending()] + [writer]
        abandoned = writer.pending()
        if abandoned:
            logger.warning(
                f"abandoning {abandoned} in-flight async checkpoint "
                "save(s); their tag dirs (if completed) remain atomic "
                "but they will not move `latest`, and their errors "
                "will no longer propagate")
        return abandoned

    def shutdown(self, wait_for_checkpoint=True,
                 checkpoint_timeout=None):
        """Tear down the engine's host-side services so it can be
        dropped and rebuilt (the elastic supervisor's recovery path):
        drain — or, on timeout, abandon — in-flight checkpoint writers,
        then close the monitor (watchdog thread, flight recorder
        disarm, sink flush). Device state is freed by GC once the last
        reference to the engine goes away."""
        if wait_for_checkpoint:
            try:
                self.wait_for_checkpoint(timeout=checkpoint_timeout)
            except ckpt_io.CheckpointWaitTimeout as e:
                logger.warning(f"shutdown: {e}")
                self.abandon_checkpoint_writers()
            except RuntimeError as e:
                # a failed background write must not block teardown
                logger.warning(f"shutdown: pending writer error: {e}")
        self.monitor.close()

    def load_checkpoint(self, load_dir, tag=None,
                        load_module_strict=True,
                        load_optimizer_states=True,
                        load_lr_scheduler_states=True,
                        retries=0):
        # a save of the checkpoint being loaded may still be in flight
        self.wait_for_checkpoint()
        if tag is None:
            tag = read_latest_tag(load_dir, retries=retries)
            if tag is None:
                logger.warning(
                    f"Unable to find latest file at {load_dir}/latest")
                return None, {}
        aux_templates = {"scale": jax.device_get(self.state.scale)}
        if self._offload_enabled():
            aux_templates["host_master"] = self._host_master
            aux_templates["host_adam"] = self._host_adam.state_dict()
            if self._config.zero_config.offload_wire_compressed():
                aux_templates["offload_wire"] = \
                    self._offload_wire_state_dict()
        per_layer = hasattr(self.module, "save_state_dict") and \
            hasattr(self.module, "load_state_dir")
        sd, optim_sd = load_checkpoint_files(
            load_dir, tag, zero_enabled=load_optimizer_states,
            module_template=None if per_layer else self.state.params,
            opt_state_template=self.state.opt_state,
            aux_templates=aux_templates, retries=retries)
        if per_layer and "module" not in sd:
            # template/conversion hooks: engines whose stored layout
            # differs from the module's logical tree (PipelineEngine's
            # per-stage flat layout) translate here
            sd["module"] = self._module_from_ckpt(
                self.module.load_state_dir(
                    os.path.join(load_dir, str(tag)),
                    self._module_ckpt_template()))

        # Under ZeRO-Offload the fp32 master lives in pinned host memory
        # (state.master is None); rebuilding a device master here would
        # defeat offload and risk OOM (mirrors _init_state). SR mode
        # likewise must not materialize an fp32 tree on DEVICE — at
        # 1.5B a 6.2 GB fp32 detour next to the live bf16 state would
        # OOM the 16 GB chip this mode exists for; checkpoint leaves
        # are host numpy here, so cast leaf-wise on upload.
        if self.bf16_sr_mode:
            params = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(
                    jnp.asarray(x, self.compute_dtype), s),
                sd["module"], self._param_shardings)
            master = None
        elif self.mixed_precision or self._offload_enabled():
            params_f32 = jax.tree_util.tree_map(
                lambda x: jnp.asarray(x, jnp.float32), sd["module"])
            params = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(
                    jnp.asarray(x, self.compute_dtype), s),
                params_f32, self._param_shardings)
            master = jax.device_put(
                self.zero_policy.encode(params_f32, self._zero_pad_plan),
                self._master_shardings) if self.mixed_precision else None
        else:
            params_f32 = jax.tree_util.tree_map(
                lambda x: jnp.asarray(x, jnp.float32), sd["module"])
            master = None
            params = jax.device_put(params_f32, self._param_shardings)

        if self._offload_enabled():
            # keep host masters in sync with the restored weights even
            # when optimizer state isn't being loaded
            from jax.flatten_util import ravel_pytree
            flat, _ = ravel_pytree(params_f32)
            self._host_master[:] = np.asarray(jax.device_get(flat))
            if self._config.zero_config.offload_wire_compressed():
                # shadow/device copy resync to the restored masters; a
                # wire state dict loaded below may overwrite this
                self._offload_wire_load_state_dict(None)

        opt_state = self.state.opt_state
        scale = self.state.scale
        if load_optimizer_states and optim_sd is not None and \
                self._offload_enabled():
            if "host_master" in optim_sd:
                self._host_master[:] = optim_sd["host_master"]
                self._host_adam.load_state_dict(optim_sd["host_adam"])
                self._host_scaler.cur_scale = float(
                    np.asarray(optim_sd["scale"][0]))
                scale = make_static_loss_scale_state(
                    self._host_scaler.cur_scale)
                if self._config.zero_config.offload_wire_compressed():
                    # restores the error-feedback residual / param
                    # shadow, or resyncs them to the loaded masters when
                    # the checkpoint was written without wire state
                    self._offload_wire_load_state_dict(
                        optim_sd.get("offload_wire"))
            else:
                # checkpoint written without offload: masters restore
                # from the saved fp32 module weights; moments restart
                logger.warning(
                    "checkpoint has no host-offload optimizer state "
                    "(saved without cpu_offload?); masters restored "
                    "from module weights, Adam moments reset")
        elif load_optimizer_states and optim_sd is not None:
            if optim_sd.get("opt_state") is None:
                # loader's structure-mismatch fallback (checkpoint saved
                # with a different optimizer): keep fresh moments
                logger.warning(
                    "checkpoint optimizer state does not match the "
                    "current optimizer (different type?); optimizer "
                    "moments reset")
            else:
                # checkpoints store true shapes; re-enter the padded
                # layout (computed for the CURRENT dp size — elastic)
                restored = self.zero_policy.encode(
                    jax.tree_util.tree_map(jnp.asarray,
                                           optim_sd["opt_state"]),
                    self._zero_pad_plan, suffix_match=True)
                mismatched = []

                def put(cur, saved):
                    if saved.shape != cur.shape:
                        # per-worker state saved at a different world
                        # size (1-bit Adam worker_error [old_dp, ...]):
                        # keep the fresh init — error feedback is
                        # worker-local and safely restarts from zero
                        mismatched.append((saved.shape, cur.shape))
                        return cur
                    return jax.device_put(saved, cur.sharding)

                opt_state = jax.tree_util.tree_map(
                    put, self.state.opt_state, restored)
                if mismatched:
                    logger.warning(
                        f"{len(mismatched)} optimizer-state leaves were "
                        "saved at a different world size and were reset "
                        f"(e.g. {mismatched[0][0]} vs {mismatched[0][1]})")
            if optim_sd.get("scale") is not None and self.fp16_mode:
                # only fp16 mode unscales grads; restoring a saved
                # scale != 1 into a bf16/fp32 engine (e.g. migrating an
                # fp16 checkpoint) would scale every grad forever
                scale = LossScaleState(*[jnp.asarray(x)
                                         for x in optim_sd["scale"]])

        if self._jit_gas() == 1 and not self._offload_enabled():
            acc_restored = ()
        else:
            # _params_enc_template is abstract (ShapeDtypeStructs in SR
            # mode, where no concrete params_f32 tree exists) and already
            # in the padded/encoded layout — same recipe as _init_state.
            acc_restored = jax.device_put(
                _zeros_like_f32(self._params_enc_template),
                self._acc_shardings)
        self.state = EngineState(
            params=params, master=master, opt_state=opt_state, scale=scale,
            acc_grads=acc_restored,
            skipped=jnp.asarray(sd.get("skipped_steps", 0), jnp.int32),
            global_steps=jnp.asarray(
                sd.get("global_steps", 0) - sd.get("skipped_steps", 0),
                jnp.int32))
        self.micro_steps = sd.get("micro_steps", 0)
        # the checkpoint's global_steps already counts successful +
        # skipped optimizer steps — deriving from micro_steps instead
        # would drift whenever the resuming run uses a different
        # gradient_accumulation_steps than the saving run
        self._host_steps = int(sd.get("global_steps", 0))
        # re-derive the 1-bit Adam phase: the next train_batch re-checks
        # the restored optimizer count (a load with
        # load_optimizer_states=False resets count=0 and correctly
        # re-warms rather than freezing an all-zero variance)
        self._onebit_compressed_active = False
        if "rng" in sd and sd["rng"] is not None:
            self._rng = jnp.asarray(sd["rng"])

        if load_lr_scheduler_states and self.lr_scheduler is not None and \
                sd.get("lr_scheduler") is not None:
            self.lr_scheduler.load_state_dict(sd["lr_scheduler"])

        client_state = {
            k: v for k, v in sd.items()
            if k not in ("module", "module_flat", "global_steps",
                         "skipped_steps", "micro_steps", "dp_world_size",
                         "lr_scheduler", "rng")
        }
        log_dist(f"loaded checkpoint {tag} from {load_dir}", ranks=[0])
        return f"{load_dir}/{tag}", client_state

