"""Version compatibility shims for the jax API surface.

The engine targets the modern top-level `jax.shard_map` (check_vma
keyword); older jaxlib images (e.g. 0.4.x) ship it as
`jax.experimental.shard_map.shard_map` with the keyword spelled
`check_rep`. Import `shard_map` from here instead of from jax so both
work — the call sites keep the modern `check_vma` spelling.
"""

import functools

try:
    from jax import shard_map as _shard_map
    _REPLICATION_KW = "check_vma"
except ImportError:  # jax < 0.6: experimental module, check_rep kw
    from jax.experimental.shard_map import shard_map as _shard_map
    _REPLICATION_KW = "check_rep"


@functools.wraps(_shard_map)
def shard_map(f, mesh=None, in_specs=None, out_specs=None, check_vma=True):
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs,
                      **{_REPLICATION_KW: check_vma})
