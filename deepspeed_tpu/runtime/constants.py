"""JSON config key constants and defaults.

Mirrors the public config surface of the reference
(`deepspeed/runtime/constants.py`) so that user configs written for the
reference work unchanged against the TPU-native runtime. Values are plain
string keys + defaults — the semantics are implemented TPU-first elsewhere.
"""

#############################################
# Routes
#############################################
ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"
ROUTES = (ROUTE_TRAIN, ROUTE_EVAL, ROUTE_PREDICT, ROUTE_ENCODE)

#############################################
# Batch size
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer and lr scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False
SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False

# Optimizer type names accepted in the "optimizer" block.
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
SGD_OPTIMIZER = "sgd"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER,
    ADAMW_OPTIMIZER,
    LAMB_OPTIMIZER,
    ONEBIT_ADAM_OPTIMIZER,
    SGD_OPTIMIZER,
]

#############################################
# FP16 / mixed precision
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 32
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1

# TPU-native extension: bfloat16 block (the natural TPU dtype; no loss
# scaling needed). Accepted as {"bf16": {"enabled": true}}.
BFLOAT16 = "bf16"
BFLOAT16_ALIAS = "bfloat16"
BFLOAT16_ENABLED = "enabled"
BFLOAT16_ENABLED_DEFAULT = False
# master_weights=false drops the fp32 master copy AND fp32 Adam moments
# for bf16 state + stochastic-rounded updates (runtime/bf16_optimizer.py)
# — 6 bytes/param of optimizer-side state instead of 16.
BFLOAT16_MASTER_WEIGHTS = "master_weights"
BFLOAT16_MASTER_WEIGHTS_DEFAULT = True

#############################################
# AMP (accepted for parity; maps onto bf16 autocast semantics on TPU)
#############################################
AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

#############################################
# Gradient handling
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

FP32_ALLREDUCE = "fp32_allreduce"
FP32_ALLREDUCE_DEFAULT = False

ALLREDUCE_ALWAYS_FP32 = FP32_ALLREDUCE

DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

#############################################
# Logging / monitoring
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedJobName"

#############################################
# Monitor block (TPU-native extension): unified async-safe telemetry —
# device-side metric accumulators drained at the async-dispatch sync
# fences, pluggable sinks (JSONL event log / native tfevents), step
# tracing, and a stall watchdog. See deepspeed_tpu/monitor/ and
# docs/monitoring.md.
#   {"monitor": {"enabled": true, "sinks": ["jsonl", "tensorboard"],
#                "output_path": "runs/x/monitor", "flush_interval": 0,
#                "stall_timeout_sec": 120, "stall_probe": false,
#                "all_ranks": false}}
#############################################
MONITOR = "monitor"
MONITOR_ENABLED = "enabled"
MONITOR_ENABLED_DEFAULT = False
MONITOR_SINKS = "sinks"
MONITOR_SINKS_DEFAULT = ("jsonl",)
MONITOR_OUTPUT_PATH = "output_path"
MONITOR_OUTPUT_PATH_DEFAULT = ""
MONITOR_JOB_NAME = "job_name"
MONITOR_JOB_NAME_DEFAULT = ""
MONITOR_FLUSH_INTERVAL = "flush_interval"
MONITOR_FLUSH_INTERVAL_DEFAULT = 0
MONITOR_STALL_TIMEOUT_SEC = "stall_timeout_sec"
MONITOR_STALL_TIMEOUT_SEC_DEFAULT = 0
MONITOR_STALL_PROBE = "stall_probe"
MONITOR_STALL_PROBE_DEFAULT = False
# Terminal stall verdict: after this many CONSECUTIVE watchdog fires
# with no intervening fence, emit one `stall_escalated` event (flight
# dump + sink event) and go quiet for the episode. 0 = off (one fire
# per stall episode, never terminal). The elastic supervisor
# (elasticity/runtime.py) treats the escalated event as "stop waiting,
# recover from the last committed checkpoint".
MONITOR_STALL_ESCALATE_AFTER = "stall_escalate_after"
MONITOR_STALL_ESCALATE_AFTER_DEFAULT = 0
MONITOR_ALL_RANKS = "all_ranks"
MONITOR_ALL_RANKS_DEFAULT = False
# MFU denominator override (FLOP/s per chip). 0 = auto: the chip's
# nominal bf16 peak on real TPUs, None (no MFU) on CPU/virtual meshes.
# Set it to make MFU / tokens_per_sec_per_chip meaningful on
# CPU-virtual-mesh rehearsal runs, or to report against a measured
# (rather than nominal) peak.
MONITOR_PEAK_FLOPS_OVERRIDE = "peak_flops_override"
MONITOR_PEAK_FLOPS_OVERRIDE_DEFAULT = 0.0

# -- monitor.trace: Perfetto/Chrome trace-event export ----------------
#   {"trace": {"enabled": true, "path": "", "max_events": 200000}}
# path defaults to <output_path>/trace_rank<r>.json; the file is
# written at monitor.close(), on a watchdog fire, and on demand via
# engine.monitor.export_trace(). bin/ds_trace merges per-rank shards.
MONITOR_TRACE = "trace"
MONITOR_TRACE_ENABLED = "enabled"
MONITOR_TRACE_ENABLED_DEFAULT = False
MONITOR_TRACE_PATH = "path"
MONITOR_TRACE_PATH_DEFAULT = ""
MONITOR_TRACE_MAX_EVENTS = "max_events"
MONITOR_TRACE_MAX_EVENTS_DEFAULT = 200000

# -- monitor.flight: crash/stall flight recorder ----------------------
#   {"flight": {"enabled": true, "capacity": 256, "path": ""}}
# A bounded in-memory ring of the last `capacity` monitor events +
# per-subsystem heartbeat ages, dumped atomically (tmp+fsync+rename)
# to flight_<ts>.json on watchdog fire, uncaught train_batch
# exception, SIGTERM, or abnormal interpreter exit. Enabled by default
# whenever the monitor is on (the ring is a deque append per event).
MONITOR_FLIGHT = "flight"
MONITOR_FLIGHT_ENABLED = "enabled"
MONITOR_FLIGHT_ENABLED_DEFAULT = True
MONITOR_FLIGHT_CAPACITY = "capacity"
MONITOR_FLIGHT_CAPACITY_DEFAULT = 256
MONITOR_FLIGHT_PATH = "path"
MONITOR_FLIGHT_PATH_DEFAULT = ""

# -- monitor.numerics: device-side numerics health --------------------
#   {"numerics": {"enabled": true}}
# Opt-in per-layer accumulators computed INSIDE the jitted step
# (grad-norm/abs-max/nonfinite per top-level param group, activation
# abs-max/mean/nonfinite at layer boundaries for layer-exposing
# models) and drained in the existing one-device_get-per-fence path —
# zero new per-step host syncs (guard-tested).
MONITOR_NUMERICS = "numerics"
MONITOR_NUMERICS_ENABLED = "enabled"
MONITOR_NUMERICS_ENABLED_DEFAULT = False

# -- monitor.memory: live HBM/host byte ledger ------------------------
#   {"memory": {"enabled": true, "top_buffers": 8}}
# ON by default with the monitor (like flight): every long-lived
# allocation site (engine state groups, offload host state, checkpoint
# snapshot double-buffers, prefetch staging, pipe 1F1B buffers)
# registers its logical bytes from shape metadata; each fence
# reconciles ledger vs device_memory_stats + host RSS into a `memory`
# event (residual = activations/XLA temporaries), tracks the peak
# watermark with the attribution snapshot AT peak, and renders
# Perfetto per-category counter tracks. RESOURCE_EXHAUSTED crashes get
# the ledger + top buffers + actionable hints attached to the flight
# dump. Zero new per-step host syncs (guard-tested).
MONITOR_MEMORY = "memory"
MONITOR_MEMORY_ENABLED = "enabled"
MONITOR_MEMORY_ENABLED_DEFAULT = True
MONITOR_MEMORY_TOP_BUFFERS = "top_buffers"
MONITOR_MEMORY_TOP_BUFFERS_DEFAULT = 8

#############################################
# Progressive layer drop
#############################################
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 1.0
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001

#############################################
# Checkpoint block: tag validation (reference parity) + the TPU-native
# zero-stall async save pipeline.
#   {"checkpoint": {"tag_validation": "Warn", "async_save": true,
#                   "keep_last": 0, "writer_queue_depth": 1,
#                   "queue_policy": "block"}}
# async_save: save_checkpoint costs the train loop only a device-side
#   snapshot (a jitted copy into fresh buffers the donating step
#   functions cannot alias, plus host-side copies of the ZeRO-Offload
#   master/moments/wire state); a background writer thread device_gets
#   and serializes shards into a `<tag>.tmp` staging dir, fsyncs,
#   atomically renames to `<tag>`, and updates `latest` last.
#   `engine.wait_for_checkpoint()` is the barrier (load_checkpoint
#   calls it implicitly).
# keep_last: rotation — keep only the newest N checkpoint dirs in
#   save_dir after each commit (0 = keep all). `latest`'s target is
#   never deleted.
# writer_queue_depth: async saves allowed in flight before
#   backpressure engages.
# queue_policy: what a save over the depth does — "block" waits for
#   the oldest in-flight save, "drop" discards the new save with a
#   warning (save_checkpoint returns False).
#############################################
CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["Warn", "Ignore", "Fail"]
CHECKPOINT_ASYNC_SAVE = "async_save"
CHECKPOINT_ASYNC_SAVE_DEFAULT = True
CHECKPOINT_KEEP_LAST = "keep_last"
CHECKPOINT_KEEP_LAST_DEFAULT = 0
CHECKPOINT_WRITER_QUEUE_DEPTH = "writer_queue_depth"
CHECKPOINT_WRITER_QUEUE_DEPTH_DEFAULT = 1
CHECKPOINT_QUEUE_POLICY = "queue_policy"
CHECKPOINT_QUEUE_POLICY_DEFAULT = "block"
CHECKPOINT_QUEUE_POLICIES = ["block", "drop"]

#############################################
# Pipeline block (dict passed through to PipelineEngine)
#   {"pipeline": {"num_virtual_stages": 2}}
# num_virtual_stages (TPU-native extension): interleaved 1F1B — each
#   physical pipe stage hosts v round-robin model chunks
#   (Megatron-style virtual stages), cutting the fill/drain bubble from
#   (p-1)/(m+p-1) stage-times toward (p-1)/(v*m+p-1) at the cost of
#   more in-flight activations and a ~v-times-larger compiled schedule
#   (compile time grows accordingly — the 1F1B compile warning applies,
#   amplified). Requires pipe>1, gradient_accumulation_steps divisible
#   by the stage count, and at least pipe*v layers.
#############################################
PIPELINE = "pipeline"
PIPELINE_DEFAULT = {}
PIPELINE_NUM_VIRTUAL_STAGES = "num_virtual_stages"
PIPELINE_NUM_VIRTUAL_STAGES_DEFAULT = 1

#############################################
# Sparse attention
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_MODE = "mode"
SPARSE_MODE_DEFAULT = SPARSE_FIXED_MODE
SPARSE_BLOCK = "block"
SPARSE_BLOCK_DEFAULT = 16
SPARSE_DIFFERENT_LAYOUT_PER_HEAD = "different_layout_per_head"
SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT = False
SPARSE_NUM_LOCAL_BLOCKS = "num_local_blocks"
SPARSE_NUM_LOCAL_BLOCKS_DEFAULT = 4
SPARSE_NUM_GLOBAL_BLOCKS = "num_global_blocks"
SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT = 1
SPARSE_ATTENTION_TYPE = "attention"
SPARSE_ATTENTION_TYPE_DEFAULT = "bidirectional"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION = "horizontal_global_attention"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT = False
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS = "num_different_global_patterns"
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT = 1
SPARSE_NUM_RANDOM_BLOCKS = "num_random_blocks"
SPARSE_NUM_RANDOM_BLOCKS_DEFAULT = 0
SPARSE_LOCAL_WINDOW_BLOCKS = "local_window_blocks"
SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT = [4]
SPARSE_GLOBAL_BLOCK_INDICES = "global_block_indices"
SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT = [0]
SPARSE_GLOBAL_BLOCK_END_INDICES = "global_block_end_indices"
SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT = None
SPARSE_NUM_SLIDING_WINDOW_BLOCKS = "num_sliding_window_blocks"
SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT = 3

SPARSE_MODE_VALID = (
    SPARSE_DENSE_MODE,
    SPARSE_FIXED_MODE,
    SPARSE_VARIABLE_MODE,
    SPARSE_BIGBIRD_MODE,
    SPARSE_BSLONGFORMER_MODE,
)
# the full sparse block surface: the block is passed through wholesale
# to the SparsityConfig constructors (ops/sparse_attention), so config
# parsing validates against this list instead of reading each key
SPARSE_ATTENTION_KEYS = (
    SPARSE_MODE,
    SPARSE_BLOCK,
    SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
    SPARSE_NUM_LOCAL_BLOCKS,
    SPARSE_NUM_GLOBAL_BLOCKS,
    SPARSE_ATTENTION_TYPE,
    SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
    SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS,
    SPARSE_NUM_RANDOM_BLOCKS,
    SPARSE_LOCAL_WINDOW_BLOCKS,
    SPARSE_GLOBAL_BLOCK_INDICES,
    SPARSE_GLOBAL_BLOCK_END_INDICES,
    SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
)

#############################################
# Elasticity (ref elasticity/constants.py) + model metadata
#############################################
ELASTICITY = "elasticity"
ELASTICITY_ENABLED = "enabled"
# model metadata consumed by the FLOPS profiler's MFU denominator
VOCABULARY_SIZE = "vocabulary_size"

#############################################
# TPU-native extensions (no reference analogue)
#############################################
# Mesh block: {"mesh": {"data": -1, "model": 1, "pipe": 1, "expert": 1}}.
# -1 = infer. The axis-name constants are the canonical names
# runtime/mesh.py builds the jax Mesh with. The `expert` axis exists
# only when the config names it (3-axis meshes stay byte-identical to
# the pre-MoE layout): batch data shards over (pipe, data, expert) —
# expert-parallel devices ARE data-parallel devices, the DeepSpeed-MoE
# convention — while expert parameters shard their expert dim over it
# (deepspeed_tpu/moe/).
MESH = "mesh"
MESH_DATA_AXIS = "data"
MESH_MODEL_AXIS = "model"
MESH_PIPE_AXIS = "pipe"
MESH_EXPERT_AXIS = "expert"

#############################################
# Mixture-of-Experts block (TPU-native extension; deepspeed_tpu/moe/):
# gated top-k token routing + capacity-factor all-to-all dispatch +
# expert-parallel grouped-GEMM FFNs, wired into supporting models
# (GPT-2 family) as a config-selectable MoE MLP.
#   {"moe": {"enabled": true, "num_experts": 8, "top_k": 2,
#            "capacity_factor": 1.25, "aux_loss_weight": 0.01,
#            "every_n_layers": 2, "jitter_eps": 0.0}}
# enabled: validate the block and wire the runtime knobs into the
#   model's `configure_moe` hook at engine init. The model must be
#   BUILT with a structurally matching moe config (num_experts /
#   every_n_layers change the parameter tree, so they are verified,
#   not applied); router knobs (top_k, capacity_factor,
#   aux_loss_weight, jitter_eps) are applied — they are trace-time
#   behavior, not structure.
# num_experts: experts per MoE layer. Must divide by the mesh `expert`
#   axis size (each expert-parallel device group owns
#   num_experts/expert contiguous experts).
# top_k: experts each token routes to (gate probs renormalized over
#   the selected k).
# capacity_factor: per-expert buffer slots = ceil(cf * top_k * tokens
#   / num_experts); tokens overflowing an expert's capacity are
#   DROPPED (the residual stream carries them unchanged) and counted
#   in the per-fence `router` event.
# aux_loss_weight: weight of the load-balancing auxiliary loss
#   (Switch/GShard form: E * sum_e f_e * P_e) added to the model loss.
# every_n_layers: every n-th transformer block uses the MoE MLP
#   (n_layer must divide evenly); 1 = every block.
# jitter_eps: multiplicative uniform jitter on router logits during
#   training (0 = off).
# fused_dispatch: "on"|"off"|"auto" — swap the one-hot
#   dispatch/combine einsum pair for the fused gather-scatter kernels
#   (moe/fused_dispatch.py). "on" refuses expert-parallel meshes (the
#   einsum pair's sharding constraints ARE the all-to-all there);
#   "auto" fuses on real TPU without an expert mesh axis.
#############################################
MOE = "moe"
MOE_ENABLED = "enabled"
MOE_ENABLED_DEFAULT = False
MOE_NUM_EXPERTS = "num_experts"
MOE_NUM_EXPERTS_DEFAULT = 8
MOE_TOP_K = "top_k"
MOE_TOP_K_DEFAULT = 2
MOE_CAPACITY_FACTOR = "capacity_factor"
MOE_CAPACITY_FACTOR_DEFAULT = 1.25
MOE_AUX_LOSS_WEIGHT = "aux_loss_weight"
MOE_AUX_LOSS_WEIGHT_DEFAULT = 0.01
MOE_EVERY_N_LAYERS = "every_n_layers"
MOE_EVERY_N_LAYERS_DEFAULT = 1
MOE_JITTER_EPS = "jitter_eps"
MOE_JITTER_EPS_DEFAULT = 0.0
MOE_FUSED_DISPATCH = "fused_dispatch"
MOE_FUSED_DISPATCH_DEFAULT = "auto"
MOE_FUSED_DISPATCH_VALID = ("on", "off", "auto")

#############################################
# Async dispatch (TPU-native extension): keep N steps in flight.
#   {"async_dispatch": {"enabled": true, "steps_per_sync": 0,
#                       "prefetch_depth": 2}}
# enabled: compile the LR schedule into the jitted step (device-resident
#   function of the device step counter — no per-step host scalar
#   upload) and drop the per-step fp16 `device_get(overflow)` host sync;
#   the scheduler's overflow-skip semantics moves on-device (skipped
#   steps don't bump `global_steps`). Host-side metrics (lr mirror,
#   loss scale, TensorBoard) are fetched only at sync fences.
#   Disabled automatically under ZeRO-Offload (the host optimizer step
#   is inherently synchronous) and when a client lr_scheduler object is
#   passed (arbitrary host code can't be compiled into the step).
# steps_per_sync: fence cadence in optimizer steps; 0 = follow
#   steps_per_print.
# prefetch_depth: staged batches the background PrefetchLoader
#   (runtime/prefetch.py) keeps in flight ahead of the step loop.
#############################################
ASYNC_DISPATCH = "async_dispatch"
ASYNC_DISPATCH_ENABLED = "enabled"
ASYNC_DISPATCH_ENABLED_DEFAULT = True
ASYNC_DISPATCH_STEPS_PER_SYNC = "steps_per_sync"
ASYNC_DISPATCH_STEPS_PER_SYNC_DEFAULT = 0
ASYNC_DISPATCH_PREFETCH_DEPTH = "prefetch_depth"
ASYNC_DISPATCH_PREFETCH_DEPTH_DEFAULT = 2

#############################################
# ZeRO-Offload compressed wire (TPU-native extension): the host link is
# the bottleneck of the offload round trip, so the wire format is
# configurable under zero_optimization.offload_wire:
#   {"offload_wire": {"grad_bits": 8, "param_bits": 8, "warmup_steps": 0}}
# grad_bits (D2H gradients): 32 = native wire, exactly the legacy
#   behavior (bf16 when computing in bf16, fp32 otherwise); 16 = force
#   bf16; 8 = int8 with a per-block fp32 scale; 1 = sign bits + one
#   per-block scale with on-device error feedback (1-bit Adam's
#   compression, runtime/fp16/onebit_adam.py).
# param_bits (H2D updated params): 32 = native (legacy); 8 = int8
#   param-delta against a device-resident fp32 param copy, with
#   host-side error feedback via a shadow copy.
# warmup_steps: steps that run a full-precision fp32 wire before
#   compression engages (error feedback starts from a settled state).
#############################################
OFFLOAD_WIRE = "offload_wire"
OFFLOAD_WIRE_GRAD_BITS = "grad_bits"
OFFLOAD_WIRE_GRAD_BITS_DEFAULT = 32
OFFLOAD_WIRE_PARAM_BITS = "param_bits"
OFFLOAD_WIRE_PARAM_BITS_DEFAULT = 32
OFFLOAD_WIRE_WARMUP_STEPS = "warmup_steps"
OFFLOAD_WIRE_WARMUP_STEPS_DEFAULT = 0
OFFLOAD_WIRE_GRAD_BITS_VALID = (1, 8, 16, 32)
OFFLOAD_WIRE_PARAM_BITS_VALID = (8, 32)

#############################################
# ZeRO stage-3 runtime (TPU-native extension): the explicit
# gather/release scheduler for sharded compute params
# (runtime/zero/stage3.py), configured under zero_optimization.stage3:
#   {"stage3": {"prefetch_layers": 1, "release_after_use": true,
#               "gather_dtype": null}}
# enabled: weave the scheduler through supporting model apply paths
#   (GPT-2/BERT layer stacks, sequential PipelineModule chains); off =
#   params stay sharded with XLA-implicit gathers (no scheduling
#   control, no live-bytes bound).
# prefetch_layers: all-gathers issued ahead of use — layer k+N's
#   params gather while layer k computes; live full-param memory is
#   bounded by (prefetch_layers + 1) layers. 0 = gather at use.
# release_after_use: false = naive baseline (whole stack gathered up
#   front, held live through fwd+bwd; full stacked grad materializes
#   before one bulk reduce-scatter) — the zero3_overlap bench A/B leg.
# gather_dtype: cast params to this dtype BEFORE the all-gather
#   (null = storage dtype; "bf16" halves gather bytes for fp32 params).
#############################################
STAGE3 = "stage3"
STAGE3_ENABLED = "enabled"
STAGE3_ENABLED_DEFAULT = True
STAGE3_PREFETCH_LAYERS = "prefetch_layers"
STAGE3_PREFETCH_LAYERS_DEFAULT = 1
STAGE3_RELEASE_AFTER_USE = "release_after_use"
STAGE3_RELEASE_AFTER_USE_DEFAULT = True
STAGE3_GATHER_DTYPE = "gather_dtype"
STAGE3_GATHER_DTYPE_DEFAULT = None
STAGE3_GATHER_DTYPE_VALID = (None, "fp32", "bf16", "fp16")

#############################################
# Quantized compute (TPU-native extension): int8 quantized-compute
# forward GEMMs as the third fused-ops epilogue family
# (ops/transformer/quantized_matmul.py) — per-(K-block, N-column)
# weight scales + per-row activation scales, dequant fused into the
# GEMM epilogue, straight-through backward in the compute dtype.
#   {"quantized_compute": {"enabled": true, "mode": "auto",
#                          "block": 128,
#                          "stochastic_rounding": false}}
# enabled: wire the family into supporting models at engine init (the
#   model's configure_quantized_compute hook; models without the hook
#   warn and stay unquantized).
# mode: "auto" quantizes on real TPU only (the fused_ops convention —
#   CPU numerics stay bit-identical by default); "on" forces the path
#   anywhere (XLA fallback reproduces the same quantization
#   numerics); "off" parks the config without unwiring it.
# block: quantization block along the contraction dim. Must be a
#   multiple of 128 on the Pallas path (int8 lane tiling).
# stochastic_rounding: round the int8 quantization stochastically
#   (unbiased) using the per-step "quant" rng stream the engine
#   threads next to "dropout"; also makes the no-quantization bf16
#   fallback use stochastically rounded fp32->bf16 operand casts.
#############################################
QUANTIZED_COMPUTE = "quantized_compute"
QUANTIZED_COMPUTE_ENABLED = "enabled"
QUANTIZED_COMPUTE_ENABLED_DEFAULT = False
QUANTIZED_COMPUTE_MODE = "mode"
QUANTIZED_COMPUTE_MODE_DEFAULT = "auto"
QUANTIZED_COMPUTE_MODE_VALID = ("auto", "on", "off")
QUANTIZED_COMPUTE_BLOCK = "block"
QUANTIZED_COMPUTE_BLOCK_DEFAULT = 128
QUANTIZED_COMPUTE_STOCHASTIC_ROUNDING = "stochastic_rounding"
QUANTIZED_COMPUTE_STOCHASTIC_ROUNDING_DEFAULT = False

#############################################
# Kernel block-size autotuner (TPU-native extension): measured
# grid/block shapes for the Pallas kernels (flash, packed flash,
# fused epilogues, quantized GEMM), persisted as a versioned JSON
# next to the jax compile cache and consulted transparently at trace
# time (ops/autotune.py). Entries carry the kernel module's source
# hash — a kernel edit invalidates them (defaults, one warning).
#   {"autotune": {"enabled": true, "table_path": ""}}
# enabled: consult the table at trace time (searches are explicit —
#   the autotune_flash bench leg or ops.autotune.search; nothing
#   searches inside a training step).
# table_path: "" = next to the jax compilation cache
#   (autotune_table_v2.json), else an explicit JSON path.
#############################################
AUTOTUNE = "autotune"
AUTOTUNE_ENABLED = "enabled"
AUTOTUNE_ENABLED_DEFAULT = True
AUTOTUNE_TABLE_PATH = "table_path"
AUTOTUNE_TABLE_PATH_DEFAULT = ""

#############################################
# Communication/compute overlap runtime (TPU-native extension): the
# shared optimization_barrier discipline (ops/overlap.py) that phrases
# issue-early/consume-late schedules at the MoE all-to-all pair, the
# ring-attention send/recv chain, and ZeRO-3 standalone-leaf gathers.
# Bit-exact by construction — the barriers constrain the schedule,
# never the math.
#   {"overlap": {"enabled": true, "sites": "auto",
#                "issue_distance": 1}}
# enabled: master switch for the discipline (off = every site runs
#   its unscheduled baseline).
# sites: "auto" (default) consults the autotune collective-schedule
#   table per (site, mesh shape, payload bucket); or an explicit list
#   drawn from ["moe_dispatch", "ring", "zero3_leaf"] to pin exactly
#   which sites overlap.
# issue_distance: how many collective windows may stay in flight at
#   the ring site (>= 1); also the default the autotuner's candidates
#   are measured against. In-flight staging bytes are ledgered as the
#   `overlap_inflight` category (docs/monitoring.md).
#############################################
OVERLAP = "overlap"
OVERLAP_ENABLED = "enabled"
OVERLAP_ENABLED_DEFAULT = True
OVERLAP_SITES = "sites"
OVERLAP_SITES_DEFAULT = "auto"
OVERLAP_ISSUE_DISTANCE = "issue_distance"
OVERLAP_ISSUE_DISTANCE_DEFAULT = 1

#############################################
# Inference/serving engine (TPU-native extension): AOT-compiled
# prefill + single-token decode over a device-resident paged KV cache
# with continuous batching (deepspeed_tpu/inference/), configured
# under a top-level "inference" block:
#   {"inference": {"max_slots": 8, "prefill_chunk": 64,
#                  "sync_every": 8, "max_new_tokens": 128,
#                  "max_seq_len": null, "eos_token_id": null,
#                  "top_k_max": 64, "seed": 0,
#                  "weight_bits": 32, "weight_quant_block": 64,
#                  "kv_cache": {"num_pages": 256, "page_size": 16}}}
# max_slots: concurrent decode request slots — the decode program's
#   static batch dimension (iteration-level continuous batching admits
#   queued requests into slots that free up).
# prefill_chunk: prompt tokens processed per prefill program call;
#   long prompts run chunk-by-chunk INTERLEAVED with decode so they
#   never stall the decode batch.
# sync_every: decode iterations dispatched between serving fences (the
#   one device_get per fence; the async_dispatch steps_per_sync
#   convention applied to serving).
# max_new_tokens: per-request generation cap AND the device output
#   buffer width (requests may ask for less, never more).
# max_seq_len: prompt + generated upper bound (null = the model's
#   n_positions, clamped to kv_cache capacity).
# eos_token_id: default end-of-sequence id finishing a request early
#   (null = generate until max_new_tokens; per-request override).
# top_k_max: static top-k sampling cap compiled into the decode
#   program (per-request top_k <= top_k_max).
# seed: base PRNG seed for device-side sampling.
# weight_bits: 32 = serve the params as given; 8 = int8 weight-only
#   quantization at load (per-block-scale, the offload_wire block
#   machinery) with a dequant-in-matmul epilogue.
# weight_quant_block: quantization block along the contraction dim.
# kv_cache.num_pages: physical pages in the preallocated device pool
#   (page 0 is a scratch page for masked writes; num_pages - 1 are
#   allocatable). The pool is a `kv_cache` memory-ledger category.
# kv_cache.page_size: tokens per page.
#############################################
INFERENCE = "inference"
INFERENCE_MAX_SLOTS = "max_slots"
INFERENCE_MAX_SLOTS_DEFAULT = 8
INFERENCE_PREFILL_CHUNK = "prefill_chunk"
INFERENCE_PREFILL_CHUNK_DEFAULT = 64
INFERENCE_SYNC_EVERY = "sync_every"
INFERENCE_SYNC_EVERY_DEFAULT = 8
INFERENCE_MAX_NEW_TOKENS = "max_new_tokens"
INFERENCE_MAX_NEW_TOKENS_DEFAULT = 128
INFERENCE_MAX_SEQ_LEN = "max_seq_len"
INFERENCE_MAX_SEQ_LEN_DEFAULT = None
INFERENCE_EOS_TOKEN_ID = "eos_token_id"
INFERENCE_EOS_TOKEN_ID_DEFAULT = None
INFERENCE_TOP_K_MAX = "top_k_max"
INFERENCE_TOP_K_MAX_DEFAULT = 64
INFERENCE_SEED = "seed"
INFERENCE_SEED_DEFAULT = 0
INFERENCE_WEIGHT_BITS = "weight_bits"
INFERENCE_WEIGHT_BITS_DEFAULT = 32
INFERENCE_WEIGHT_BITS_VALID = (8, 32)
INFERENCE_WEIGHT_QUANT_BLOCK = "weight_quant_block"
INFERENCE_WEIGHT_QUANT_BLOCK_DEFAULT = 64
INFERENCE_KV_CACHE = "kv_cache"
INFERENCE_KV_NUM_PAGES = "num_pages"
INFERENCE_KV_NUM_PAGES_DEFAULT = 256
INFERENCE_KV_PAGE_SIZE = "page_size"
INFERENCE_KV_PAGE_SIZE_DEFAULT = 16
#############################################
# Serving observability (ISSUE 14, monitor/serving.py).
# observability.enabled: build the per-request lifecycle tracker when
#   a monitor block is enabled on the same config (default true; the
#   monitor.flight / monitor.memory convention — no monitor, no
#   tracker). The tracker stamps request phases from host dispatch
#   timestamps at the existing serving fences only: zero new per-token
#   host syncs (the HOTSYNC contract).
# observability.slo_ttft_ms / slo_token_ms: latency targets for the
#   goodput split (tokens from requests meeting every configured
#   target vs all tokens). 0 = no target (goodput == throughput).
#############################################
INFERENCE_OBSERVABILITY = "observability"
INFERENCE_OBS_ENABLED = "enabled"
INFERENCE_OBS_ENABLED_DEFAULT = True
INFERENCE_OBS_SLO_TTFT_MS = "slo_ttft_ms"
INFERENCE_OBS_SLO_TTFT_MS_DEFAULT = 0.0
INFERENCE_OBS_SLO_TOKEN_MS = "slo_token_ms"
INFERENCE_OBS_SLO_TOKEN_MS_DEFAULT = 0.0
#############################################
# Speculative decoding (ISSUE 18, inference/speculative.py).
#   {"inference": {"speculative": {"enabled": false,
#                                  "draft_model": "truncate:1",
#                                  "k": 4,
#                                  "k_min": 1,
#                                  "adaptive": true}}}
# speculative.enabled: propose tokens with a cheap draft model and
#   verify k+1 positions per flagship launch (lossless: greedy
#   prefix-match at temperature 0, modified rejection sampling above —
#   the output distribution is exactly the vanilla decode one). The
#   default false leaves the engine's two compiled programs and its
#   outputs byte-for-byte unchanged.
# speculative.draft_model: where the draft comes from. "truncate:N"
#   derives it from the flagship's first N transformer layers (shared
#   embeddings / final LN / tied head — zero extra checkpoint);
#   "external" uses the draft_params/draft_model_config pair passed to
#   the InferenceEngine constructor.
# speculative.k: drafted tokens per round — the verify program's
#   static width is k+1 positions per slot.
# speculative.k_min: adaptive back-off floor (1 = degenerate to one
#   drafted token per round on hostile prompts).
# speculative.adaptive: per-slot k adaptation — a slot that accepts a
#   full round grows its k toward `k`, a slot whose acceptance EMA
#   drops below the back-off threshold shrinks toward `k_min`; the
#   host dispatches max(live k) draft steps per round, so a batch
#   whose drafts are all being rejected stops paying for them.
#############################################
INFERENCE_SPECULATIVE = "speculative"
INFERENCE_SPEC_ENABLED = "enabled"
INFERENCE_SPEC_ENABLED_DEFAULT = False
INFERENCE_SPEC_DRAFT_MODEL = "draft_model"
INFERENCE_SPEC_DRAFT_MODEL_DEFAULT = "truncate:1"
INFERENCE_SPEC_K = "k"
INFERENCE_SPEC_K_DEFAULT = 4
INFERENCE_SPEC_K_MIN = "k_min"
INFERENCE_SPEC_K_MIN_DEFAULT = 1
INFERENCE_SPEC_ADAPTIVE = "adaptive"
INFERENCE_SPEC_ADAPTIVE_DEFAULT = True
