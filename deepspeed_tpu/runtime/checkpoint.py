"""Sharded checkpoint file I/O (no pickle, no full-state host gather).

Preserves the reference's on-disk layout (ref `engine.py:1255-1273`,
`engine.py:1522-1531`):

    <save_dir>/<tag>/mp_rank_00_model_states.npz (+ .json manifest)
    <save_dir>/<tag>/zero_pp_rank_{k}_mp_rank_00optim_states.npz (+ .json)
    <save_dir>/<tag>/zero_pp_rank_{k}_mp_rank_00model_states.npz (+ .json)
    <save_dir>/latest                      (pointer file)

Semantics, TPU-native:

* **Per-shard files, no gather.** Every device that owns a primary
  (replica_id == 0) shard of a sharded array contributes it to the
  bucket file of that device's dp ordinal — the single-controller
  equivalent of "every dp rank writes its own zero_pp_rank_N file with
  barriers" (ref `engine.py:1522-1531`).  Each process writes only its
  *addressable* shards, so a 13B multi-host save never materialises a
  full array on any host (the round-1 `_fetch_to_host` allgather is
  gone from the save path).
* **Streamed npz + JSON manifests instead of pickle** — loadable
  without arbitrary code execution, versioned (`format_version`).
* **Elastic by construction.** Leaves are reassembled per-leaf on load
  and re-placed under the *current* mesh sharding, so reloading onto a
  different mesh/world size just works — subsuming the reference's
  elastic-vs-rigid ZeRO-1 formats (`stage1.py:825-1024`).

Legacy (round-1) pickle checkpoints are still readable, with a warning.
"""

import contextlib
import json
import os
import pickle
import re
import shutil
import threading
import time
import zipfile

import jax
import numpy as np

FORMAT_VERSION = 2

MODEL_STATES_FMT = "mp_rank_{:02d}_model_states"
OPTIM_SHARD_FMT = "zero_pp_rank_{}_mp_rank_{:02d}optim_states"
MODEL_SHARD_FMT = "zero_pp_rank_{}_mp_rank_{:02d}model_states"
LATEST_FILE = "latest"

# Suffix of the in-progress staging directory an async (or crashed)
# save writes into before the atomic rename to `<tag>`. Readers must
# never treat one as a checkpoint.
STAGING_SUFFIX = ".tmp"

_SHARD_RE = re.compile(
    r"zero_pp_rank_(\d+)_mp_rank_(\d+)(optim|model)_states\.npz$")


# ----------------------------------------------------------------------
# error taxonomy (the elastic supervisor acts on the distinction)
# ----------------------------------------------------------------------
class CheckpointNotFoundError(FileNotFoundError):
    """No checkpoint exists under the requested tag at all — nothing
    was ever saved (or rotation removed it). Recovery action: start
    fresh, or pick a different tag."""


class CheckpointStagingOnlyError(FileNotFoundError):
    """The tag exists ONLY as a `<tag>.tmp` staging dir: a save was
    killed before its atomic commit. The staging dir must never be
    loaded. Recovery action: load an earlier committed tag (the
    `latest` pointer only ever names committed saves)."""


class CheckpointWaitTimeout(TimeoutError):
    """wait_for_checkpoint(timeout=...) expired with a writer still in
    flight. Carries the writer's last heartbeat age so the caller can
    tell a slow-but-alive writer from a wedged one before abandoning
    it (engine.abandon_checkpoint_writers). Note: abandonment unblocks
    in-process teardown/rebuild; writer threads stay non-daemon by
    design (the interpreter will not EXIT mid-write), so a truly
    wedged writer still blocks final process exit."""

    def __init__(self, msg, pending=0, heartbeat_age_sec=None):
        super().__init__(msg)
        self.pending = pending
        self.heartbeat_age_sec = heartbeat_age_sec


# Transient read failures worth retrying: a checkpoint dir mid-commit
# (two-rename window of commit_staging_dir), NFS attribute-cache
# flutter, or a reader racing rotation. Structural corruption
# (coverage mismatch, future format) is NOT retried.
_TRANSIENT_READ_ERRORS = (OSError, zipfile.BadZipFile)


def _retry_read(fn, retries, backoff_sec, describe):
    """Run fn() with bounded retries on transient read errors.
    CheckpointNotFoundError passes straight through — retrying cannot
    create a checkpoint that was never saved. CheckpointStagingOnlyError
    IS retried: a reader racing a same-tag RESAVE's two-rename commit
    window (old `<tag>` moved aside, new `<tag>.tmp` not yet renamed)
    sees exactly the staging-only signature for a few milliseconds;
    only after the retries exhaust is it the terminal interrupted-save
    verdict."""
    attempt = 0
    while True:
        try:
            return fn()
        except CheckpointNotFoundError:
            raise
        except _TRANSIENT_READ_ERRORS as e:
            attempt += 1
            if attempt > retries:
                raise
            from deepspeed_tpu.utils.logging import logger
            logger.warning(
                f"transient checkpoint read error ({describe}, attempt "
                f"{attempt}/{retries}): {e}; retrying in "
                f"{backoff_sec * attempt:.2f}s")
            time.sleep(backoff_sec * attempt)


# ----------------------------------------------------------------------
# npz-safe dtype encoding (np.savez silently degrades ml_dtypes arrays
# — bf16 etc. — to raw void records; store them as same-width uints and
# record the logical dtype in the manifest / shard meta)
# ----------------------------------------------------------------------
def _np_dtype(name):
    """np.dtype from a string, resolving ml_dtypes names ("bfloat16",
    "float8_e4m3fn", ...) that plain numpy does not know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _npz_encode(arr):
    """array -> (npz-native array, logical dtype string or None)."""
    arr = np.asarray(arr)
    try:
        np.dtype(arr.dtype.name)   # round-trippable by plain numpy?
        if arr.dtype.kind != "V":
            return arr, None
    except TypeError:
        pass
    uint = np.dtype(f"u{arr.dtype.itemsize}")
    return arr.view(uint), arr.dtype.name


def _npz_decode(arr, dtype_name):
    if dtype_name is None:
        return arr
    return arr.view(_np_dtype(dtype_name))


# ----------------------------------------------------------------------
# pytree <-> flat path/leaf maps
# ----------------------------------------------------------------------
def tree_to_entries(tree, prefix=""):
    """[(path_string, leaf)] with jax.tree_util paths (stable across
    save/load as long as the tree structure matches)."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [(prefix + jax.tree_util.keystr(path), leaf)
            for path, leaf in flat]


def entries_to_tree(template, flat, prefix=""):
    """Rebuild leaves of `template`'s structure from a {path: array}
    map (missing keys raise KeyError with the offending path)."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, _ in paths:
        key = prefix + jax.tree_util.keystr(path)
        if key not in flat:
            raise KeyError(f"checkpoint is missing entry {key!r}")
        leaves.append(flat[key])
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _is_array(x):
    return isinstance(x, (jax.Array, np.ndarray))


def _dp_ordinal(sharding, device):
    """Stable ordinal of `device` within the sharding's device set —
    the dp-rank analog that names the bucket file."""
    ids = sorted(d.id for d in sharding.device_set)
    return ids.index(device.id)


# ----------------------------------------------------------------------
# save
# ----------------------------------------------------------------------
def _ckpt_dir(save_dir, tag):
    return os.path.join(save_dir, str(tag))


def model_states_path(save_dir, tag, mp_rank=0):
    return os.path.join(_ckpt_dir(save_dir, tag),
                        MODEL_STATES_FMT.format(mp_rank) + ".npz")


def _split_shards(entries):
    """Split entries into (replicated, sharded).  `replicated` leaves
    are written once by process 0; `sharded` leaves contribute one
    piece per primary shard to per-ordinal bucket files."""
    replicated, sharded = [], []
    for key, leaf in entries:
        if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding") and \
                not leaf.sharding.is_fully_replicated:
            sharded.append((key, leaf))
        else:
            replicated.append((key, leaf))
    return replicated, sharded


def _write_shard_buckets(ckpt_dir, fmt, sharded, mp_rank=0):
    """Write each primary shard of each sharded leaf into the bucket
    file of its owning device's dp ordinal.  Only addressable shards
    are touched — multi-host safe, no cross-host traffic."""
    buckets = {}       # ordinal -> {npz_name: np.ndarray}
    bucket_meta = {}   # ordinal -> [entry meta]
    for key, leaf in sharded:
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue
            ordinal = _dp_ordinal(leaf.sharding, shard.device)
            name = f"s{len(bucket_meta.get(ordinal, []))}"
            start = [0 if sl.start is None else int(sl.start)
                     for sl in shard.index]
            piece, enc = _npz_encode(np.asarray(shard.data))
            buckets.setdefault(ordinal, {})[name] = piece
            bucket_meta.setdefault(ordinal, []).append({
                "name": name, "key": key, "start": start,
                "global_shape": list(leaf.shape), "dtype": str(leaf.dtype),
                "npz_dtype": enc,
            })
    for ordinal, arrays in buckets.items():
        base = os.path.join(ckpt_dir, fmt.format(ordinal, mp_rank))
        np.savez(base + ".npz", **arrays)
        with open(base + ".json", "w") as f:
            json.dump({"format_version": FORMAT_VERSION,
                       "entries": bucket_meta[ordinal]}, f)


def _json_safe(obj):
    """Recursively convert checkpoint metadata to JSON-able values;
    numpy scalars/arrays become lists (small metadata only)."""
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, (np.generic,)):
        return obj.item()
    if isinstance(obj, (np.ndarray, jax.Array)):
        return {"__ndarray__": np.asarray(obj).tolist(),
                "dtype": str(np.asarray(obj).dtype)}
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    from deepspeed_tpu.utils.logging import logger
    logger.warning(
        f"checkpoint metadata value of type {type(obj).__name__} is not "
        "JSON-serializable; storing its repr (round-trip lossy)")
    return {"__unserializable__": repr(obj)}


def _json_restore(obj):
    if isinstance(obj, dict):
        if "__ndarray__" in obj:
            return np.asarray(obj["__ndarray__"],
                              dtype=np.dtype(obj["dtype"]))
        return {k: _json_restore(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_json_restore(v) for v in obj]
    return obj


def save_checkpoint_files(save_dir, tag, model_sd, optim_sd, mp_rank=0,
                          ckpt_dir=None):
    """Write a sharded checkpoint.

    `model_sd` — dict with a "module" pytree of (possibly sharded) jax
    arrays plus JSON-able metadata entries.  `optim_sd` — dict with an
    "opt_state" pytree plus metadata; may be None.  All processes must
    call this (each writes its own shards); process 0 writes manifests.
    `ckpt_dir` overrides the destination directory (the async writer
    points it at the `<tag>.tmp` staging dir and renames on commit).
    """
    if ckpt_dir is None:
        ckpt_dir = _ckpt_dir(save_dir, tag)
    os.makedirs(ckpt_dir, exist_ok=True)

    module = model_sd.get("module", {})
    mod_entries = tree_to_entries(module, "module")
    mod_repl, mod_sharded = _split_shards(mod_entries)
    _write_shard_buckets(ckpt_dir, MODEL_SHARD_FMT, mod_sharded, mp_rank)

    opt_repl, opt_sharded = [], []
    opt_meta = {}
    if optim_sd is not None:
        opt_entries = []
        for k, v in optim_sd.items():
            if k == "opt_state":
                opt_entries += tree_to_entries(v, "optim")
            elif _is_array(v) or (isinstance(v, (tuple, list)) and
                                  any(_is_array(x) for x in
                                      jax.tree_util.tree_leaves(v))):
                opt_entries += tree_to_entries(v, f"aux/{k}")
            else:
                opt_meta[k] = v
        opt_repl, opt_sharded = _split_shards(opt_entries)
        _write_shard_buckets(ckpt_dir, OPTIM_SHARD_FMT, opt_sharded,
                             mp_rank)

    if jax.process_index() != 0:
        return

    meta = {k: v for k, v in model_sd.items() if k != "module"}
    main = {}
    npz_dtypes = {}
    for key, leaf in mod_repl + opt_repl:
        arr, enc = _npz_encode(np.asarray(jax.device_get(leaf)))
        main[key] = arr
        if enc is not None:
            npz_dtypes[key] = enc
    base = os.path.join(ckpt_dir, MODEL_STATES_FMT.format(mp_rank))
    np.savez(base + ".npz", **main)
    with open(base + ".json", "w") as f:
        json.dump({
            "format_version": FORMAT_VERSION,
            "meta": _json_safe(meta),
            "optim_meta": _json_safe(opt_meta),
            "npz_dtypes": npz_dtypes,
            "has_optim": optim_sd is not None,
        }, f)


# ----------------------------------------------------------------------
# load
# ----------------------------------------------------------------------
def _assemble(flat, shard_entries):
    """Reassemble sharded leaves on host, one leaf at a time (peak host
    memory = one global leaf, not the whole tree). Coverage is
    verified: the primary shards of a leaf tile it exactly, so any
    missing/unreadable bucket file shows up as covered != global and
    raises instead of silently zero-filling the hole."""
    by_key = {}
    for npz, entry in shard_entries:
        by_key.setdefault(entry["key"], []).append((npz, entry))
    for key, pieces in by_key.items():
        _, first = pieces[0]
        out = np.zeros(first["global_shape"],
                       dtype=_np_dtype(first["dtype"]))
        covered = 0
        for npz, entry in pieces:
            piece = _npz_decode(npz[entry["name"]],
                                entry.get("npz_dtype"))
            idx = tuple(slice(s, s + d) for s, d in
                        zip(entry["start"], piece.shape))
            out[idx] = piece
            covered += int(np.prod(piece.shape))
        total = int(np.prod(first["global_shape"]))
        if covered != total:
            raise ValueError(
                f"checkpoint shard coverage mismatch for {key!r}: "
                f"{covered} of {total} elements present — a "
                "zero_pp_rank shard file is missing or truncated")
        flat[key] = out
    return flat


def _load_legacy_pickle(load_dir, tag, mp_rank, dp_rank):
    from deepspeed_tpu.utils.logging import logger
    logger.warning(
        "loading legacy (round-1) pickle checkpoint; resave to upgrade "
        "to the sharded npz format")
    legacy_model = os.path.join(
        _ckpt_dir(load_dir, tag), f"mp_rank_{mp_rank:02d}_model_states.pt")
    with open(legacy_model, "rb") as f:
        model_sd = pickle.load(f)
    optim_sd = None
    legacy_opt = os.path.join(
        _ckpt_dir(load_dir, tag),
        f"zero_pp_rank_{dp_rank}_mp_rank_{mp_rank:02d}optim_states.pt")
    if os.path.exists(legacy_opt):
        with open(legacy_opt, "rb") as f:
            optim_sd = pickle.load(f)
    return model_sd, optim_sd, True


def load_checkpoint_flat(load_dir, tag, mp_rank=0, retries=0,
                         backoff_sec=0.05):
    """Read a sharded checkpoint into ({path: np.array}, meta,
    optim_meta, has_optim).  Paths are prefixed "module"/"optim"/"aux".

    `retries` bounds retry-with-backoff on TRANSIENT read errors
    (OSError/BadZipFile — a reader racing a commit's rename window or
    rotation). Missing checkpoints fail immediately with a distinct,
    actionable error: `CheckpointStagingOnlyError` when only the
    `<tag>.tmp` staging dir of an interrupted save exists,
    `CheckpointNotFoundError` when there is nothing at all."""
    return _retry_read(
        lambda: _load_checkpoint_flat_once(load_dir, tag, mp_rank),
        retries, backoff_sec, f"tag '{tag}' in {load_dir}")


def _load_checkpoint_flat_once(load_dir, tag, mp_rank=0):
    ckpt_dir = _ckpt_dir(load_dir, tag)
    base = os.path.join(ckpt_dir, MODEL_STATES_FMT.format(mp_rank))
    if not os.path.exists(base + ".json"):
        legacy = os.path.join(ckpt_dir,
                              f"mp_rank_{mp_rank:02d}_model_states.pt")
        if os.path.isdir(staging_dir(load_dir, tag)):
            # `<tag>.tmp` without the manifest: an interrupted save —
            # or, transiently, a same-tag resave mid-commit (the
            # two-rename window); _retry_read retries this verdict
            # before it becomes terminal
            raise CheckpointStagingOnlyError(
                f"checkpoint tag '{tag}' in {load_dir} only exists as "
                f"an incomplete staging dir ('{tag}{STAGING_SUFFIX}') "
                "left by an interrupted save; load an earlier tag (see "
                "the 'latest' pointer)")
        if not os.path.isdir(ckpt_dir):
            raise CheckpointNotFoundError(
                f"no checkpoint tag '{tag}' under {load_dir}: the tag "
                "directory does not exist (never saved, or removed by "
                "keep_last rotation)")
        # dir present, manifest absent: terminal, not a transient to
        # burn retries on. A legacy pickle dir gets an actionable
        # message (this flat loader never read the .pt format — the
        # pickle path lives in load_checkpoint_files).
        if os.path.exists(legacy):
            raise CheckpointNotFoundError(
                f"checkpoint dir {ckpt_dir} holds a legacy pickle "
                "checkpoint (mp_rank_*.pt) with no npz manifest; load "
                "it through load_checkpoint_files / "
                "engine.load_checkpoint")
        raise CheckpointNotFoundError(
            f"checkpoint dir {ckpt_dir} exists but has no manifest "
            f"{os.path.basename(base)}.json (mp_rank mismatch, or a "
            "corrupted/partially deleted checkpoint)")
    with open(base + ".json") as f:
        manifest = json.load(f)
    version = manifest.get("format_version", 1)
    if version > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint {ckpt_dir} has format_version {version}, but "
            f"this build reads up to {FORMAT_VERSION} — upgrade "
            "deepspeed_tpu to load it")
    npz_dtypes = manifest.get("npz_dtypes", {})
    flat = {}
    with np.load(base + ".npz") as main:
        for key in main.files:
            flat[key] = _npz_decode(main[key], npz_dtypes.get(key))

    shard_entries = []
    opened = []
    try:
        for fname in sorted(os.listdir(ckpt_dir)):
            m = _SHARD_RE.match(fname)
            if not m or int(m.group(2)) != mp_rank:
                continue
            npz = np.load(os.path.join(ckpt_dir, fname))
            opened.append(npz)
            with open(os.path.join(
                    ckpt_dir, fname[:-len(".npz")] + ".json")) as f:
                bucket = json.load(f)
            for entry in bucket["entries"]:
                shard_entries.append((npz, entry))
        _assemble(flat, shard_entries)
    finally:
        for npz in opened:
            npz.close()
    return (flat, _json_restore(manifest.get("meta", {})),
            _json_restore(manifest.get("optim_meta", {})),
            manifest.get("has_optim", False))


def load_checkpoint_files(load_dir, tag, zero_enabled=True, mp_rank=0,
                          dp_rank=0, module_template=None,
                          opt_state_template=None, aux_templates=None,
                          retries=0):
    """Engine-facing loader.  Returns (model_sd, optim_sd) shaped like
    the save-side inputs: model_sd["module"] is a pytree when
    `module_template` is given (otherwise the flat {path: array} map
    under model_sd["module_flat"]); likewise optim_sd["opt_state"].
    `zero_enabled` gates whether optimizer state is assembled at all.
    `retries` bounds transient-read retries (see load_checkpoint_flat)."""
    legacy_marker = os.path.join(
        _ckpt_dir(load_dir, tag), f"mp_rank_{mp_rank:02d}_model_states.pt")
    npz_marker = model_states_path(load_dir, tag, mp_rank)
    if not os.path.exists(npz_marker) and os.path.exists(legacy_marker):
        model_sd, optim_sd, _ = _load_legacy_pickle(load_dir, tag, mp_rank,
                                                    dp_rank)
        return model_sd, optim_sd

    flat, meta, opt_meta, has_optim = load_checkpoint_flat(
        load_dir, tag, mp_rank, retries=retries)

    model_sd = dict(meta)
    if module_template is not None:
        model_sd["module"] = entries_to_tree(module_template, flat,
                                             "module")
    else:
        model_sd["module_flat"] = {
            k: v for k, v in flat.items() if k.startswith("module")}

    optim_sd = None
    if has_optim and zero_enabled:
        optim_sd = dict(opt_meta)
        if opt_state_template is not None:
            try:
                optim_sd["opt_state"] = entries_to_tree(
                    opt_state_template, flat, "optim")
            except KeyError:
                optim_sd["opt_state"] = None
        for name, template in (aux_templates or {}).items():
            try:
                optim_sd[name] = entries_to_tree(template, flat,
                                                 f"aux/{name}")
            except KeyError:
                pass
    return model_sd, optim_sd


# ----------------------------------------------------------------------
# durability: fsync helpers, staging-dir commit, latest tag, rotation
# ----------------------------------------------------------------------
def _fsync_path(path):
    """fsync a file (or directory) by descriptor; directory fsync is
    best-effort — not all filesystems support it."""
    flags = os.O_RDONLY
    if os.path.isdir(path) and hasattr(os, "O_DIRECTORY"):
        flags |= os.O_DIRECTORY
    try:
        fd = os.open(path, flags)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def staging_dir(save_dir, tag):
    """The `<tag>.tmp` directory an in-progress save writes into."""
    return _ckpt_dir(save_dir, tag) + STAGING_SUFFIX


def is_staging_name(name):
    return str(name).endswith(STAGING_SUFFIX)


def commit_staging_dir(save_dir, tag):
    """Durably publish `<tag>.tmp` as `<tag>`: fsync every file in the
    staging dir, atomically rename it over the final name, fsync the
    parent.  A crash at any point leaves either the old `<tag>` (or
    nothing) or the new one — never a half-written visible checkpoint."""
    src = staging_dir(save_dir, tag)
    dst = _ckpt_dir(save_dir, tag)
    for root, _, files in os.walk(src):
        for fname in files:
            _fsync_path(os.path.join(root, fname))
    _fsync_path(src)
    trash = None
    if os.path.exists(dst):
        # resave of an existing tag: move the old dir aside by RENAME
        # (microseconds) rather than rmtree'ing it in place (seconds
        # for a large checkpoint), so the window with no `<tag>`
        # visible is two renames wide. The trash name carries the
        # staging suffix so readers and rotation skip it, and a crash
        # inside the window leaves BOTH complete dirs (`<tag>.old.tmp`
        # and the fsynced `<tag>.tmp`) recoverable by hand.
        trash = dst + ".old" + STAGING_SUFFIX
        if os.path.exists(trash):
            shutil.rmtree(trash)
        os.replace(dst, trash)
    os.replace(src, dst)
    # stamp COMMIT time on the dir: rotation ranks by mtime, and a
    # slow writer finishing its file writes late must not make an
    # earlier-submitted checkpoint look newer than a later one
    os.utime(dst, None)
    _fsync_path(save_dir)
    if trash is not None:
        shutil.rmtree(trash, ignore_errors=True)


def checkpoint_dirs_bit_identical(d1, d2):
    """True when two checkpoint dirs are byte-identical: same file
    names, every npz entry equal in dtype and raw bytes, every json
    manifest equal.  Used by tests and the async_checkpoint bench to
    prove async and sync saves of the same state match exactly."""
    f1, f2 = sorted(os.listdir(d1)), sorted(os.listdir(d2))
    if f1 != f2:
        return False
    for name in f1:
        p1, p2 = os.path.join(d1, name), os.path.join(d2, name)
        if name.endswith(".npz"):
            with np.load(p1) as a, np.load(p2) as b:
                if sorted(a.files) != sorted(b.files):
                    return False
                for k in a.files:
                    if a[k].dtype != b[k].dtype or \
                            a[k].tobytes() != b[k].tobytes():
                        return False
        elif name.endswith(".json"):
            with open(p1) as fa, open(p2) as fb:
                if json.load(fa) != json.load(fb):
                    return False
    return True


def is_checkpoint_dir(path):
    """True when `path` looks like a completed checkpoint directory
    (has a model-states file or per-layer files); staging dirs and
    unrelated directories are excluded."""
    if not os.path.isdir(path) or is_staging_name(path):
        return False
    try:
        names = os.listdir(path)
    except OSError:
        return False
    return any("model_states" in n or n.startswith("layer_")
               for n in names)


def rotate_checkpoints(save_dir, keep_last, protect=()):
    """Delete all but the newest `keep_last` checkpoint dirs under
    `save_dir` (by mtime).  `latest`'s target and `protect` tags are
    never deleted; `.tmp` staging dirs are never counted or touched.
    Returns the list of deleted tags."""
    if not keep_last or keep_last <= 0:
        return []
    keep = {str(t) for t in protect}
    latest = read_latest_tag(save_dir)
    if latest is not None:
        keep.add(latest)
    entries = []
    for name in os.listdir(save_dir):
        full = os.path.join(save_dir, name)
        if is_checkpoint_dir(full):
            try:
                entries.append((os.path.getmtime(full), name))
            except OSError:
                continue   # vanished concurrently (shared save_dir)
    entries.sort(reverse=True)
    deleted = []
    for _, name in entries[keep_last:]:
        if name in keep:
            continue
        shutil.rmtree(os.path.join(save_dir, name), ignore_errors=True)
        deleted.append(name)
    return deleted


class AsyncCheckpointWriter:
    """Background checkpoint writer: one non-daemon thread per save job
    (the interpreter cannot exit with a write half-done), a bounded
    in-flight window for backpressure, and error propagation into the
    training loop at the next submit/wait.

    queue_depth: saves allowed in flight before backpressure engages.
    queue_policy: "block" — a submit over the depth waits for the
    oldest job; "drop" — the new save is discarded with a warning
    (the snapshot is released, nothing is written).

    Jobs may SERIALIZE concurrently (queue_depth >= 2) but COMMIT in
    submission order via the gate submit() hands to each job — so
    `latest` and keep_last rotation can never regress to an older save
    whose writer happened to finish last.
    """

    def __init__(self, queue_depth=1, queue_policy="block"):
        assert queue_depth >= 1, queue_depth
        assert queue_policy in ("block", "drop"), queue_policy
        self._depth = queue_depth
        self._policy = queue_policy
        # set when the engine detaches this writer (wedged-writer
        # recovery): jobs still commit their tag dirs atomically, but
        # must no longer move `latest` or rotate — a stale writer
        # unwedging AFTER a successor engine committed newer tags
        # would otherwise regress the pointer to an older save
        self.abandoned = threading.Event()
        self._jobs = []          # [(thread, tag)]
        self._lock = threading.Lock()
        self._error = None
        self._seq_next = 0       # submission-order ticket
        self._commit_turn = 0    # ticket currently allowed to commit
        self._done_seqs = set()  # finished out-of-order, turn not theirs yet
        self._commit_cv = threading.Condition()

    def _reap(self):
        with self._lock:
            self._jobs = [(t, tag) for t, tag in self._jobs
                          if t.is_alive()]
            return list(self._jobs)

    def queue_depth(self):
        """Saves currently in flight (the monitor's checkpoint
        queue-depth gauge)."""
        return len(self._reap())

    def tag_in_flight(self, tag):
        """True while a live job of THIS writer holds `tag` (and so
        owns its `<tag>.tmp` staging dir). Successor writers consult
        this on abandoned predecessors before touching the same tag —
        two writers sharing one staging dir would corrupt the
        commit."""
        tag = str(tag)
        return any(jt == tag for _, jt in self._reap())

    def _raise_pending(self):
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise RuntimeError(
                "background checkpoint write failed") from err

    def _warn_drop(self, tag):
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            f"async checkpoint '{tag}' dropped: "
            f"{self._depth} save(s) already in flight "
            "(checkpoint.queue_policy=drop)")

    def admit(self, tag):
        """Cheap pre-snapshot check: False when queue_policy="drop"
        would discard a submit right now, letting the caller skip
        building the snapshot entirely (for offload engines that is a
        full host copy of masters and moments).  Under "block" always
        True — submit() provides the backpressure."""
        if self._policy != "drop":
            return True
        jobs = self._reap()
        tag = str(tag)
        # a same-tag job in flight would force submit() to block on it
        # (shared staging dir) — under "drop" that save drops instead
        if len(jobs) < self._depth and \
                not any(jt == tag for _, jt in jobs):
            return True
        self._warn_drop(tag)
        return False

    def _mark_done(self, seq):
        """Job `seq` no longer needs its commit turn (it committed or
        died).  Advance the turn across contiguously-finished seqs
        ONLY — jumping past a still-running earlier job would strand
        its writer at the gate forever."""
        with self._commit_cv:
            if seq < self._commit_turn:
                return           # turn already consumed (gate path ran)
            self._done_seqs.add(seq)
            while self._commit_turn in self._done_seqs:
                self._done_seqs.discard(self._commit_turn)
                self._commit_turn += 1
            self._commit_cv.notify_all()

    def submit(self, fn, tag, on_done=None):
        """Run fn(commit_gate) on a writer thread; `commit_gate` is a
        context manager the job must hold around its commit section
        (rename + `latest` + rotation) — gates open in submission
        order.  Returns True when the job was accepted, False when
        queue_policy="drop" rejected it.  `on_done` (optional, must
        not raise meaningfully) runs on the writer thread after the
        job finishes — success OR failure — e.g. releasing the
        snapshot's memory-ledger entries: the double-buffers are gone
        once the writer is, however the write ended."""
        self._raise_pending()
        tag = str(tag)
        # two writers on one tag would share a `<tag>.tmp` staging dir
        # (the second rmtrees it out from under the first): serialize
        # same-tag jobs regardless of queue depth
        while True:
            same = [t for t, jt in self._reap() if jt == tag]
            if not same:
                break
            if self._policy == "drop":
                # blocking on the shared staging dir would violate
                # drop's never-stall contract
                self._warn_drop(tag)
                return False
            same[0].join()
        while True:
            jobs = self._reap()
            if len(jobs) < self._depth:
                break
            if self._policy == "drop":
                self._warn_drop(tag)
                return False
            # join via the snapshot — another thread's concurrent
            # _reap() may swap self._jobs out from under an index
            jobs[0][0].join()
        seq = self._seq_next
        self._seq_next += 1

        @contextlib.contextmanager
        def commit_gate():
            with self._commit_cv:
                while self._commit_turn != seq:
                    self._commit_cv.wait()
            try:
                yield
            finally:
                self._mark_done(seq)

        def run():
            try:
                fn(commit_gate)
            except BaseException as e:  # noqa: BLE001 — must not die silent
                from deepspeed_tpu.utils.logging import logger
                import traceback
                logger.error("async checkpoint write failed:\n"
                             + traceback.format_exc())
                with self._lock:
                    if self._error is None:
                        self._error = e
            finally:
                # a job that died before (or without) taking its gate
                # must still release its turn or later jobs deadlock
                self._mark_done(seq)
                if on_done is not None:
                    try:
                        on_done()
                    except Exception:
                        # the hook releases ledger entries etc.; its
                        # failure must not kill the writer thread but
                        # must leave evidence
                        from deepspeed_tpu.utils.logging import logger
                        import traceback
                        logger.warning(
                            "checkpoint on_done hook failed:\n"
                            + traceback.format_exc())

        t = threading.Thread(target=run, daemon=False,
                             name=f"ckpt-writer-{tag}")
        with self._lock:
            self._jobs.append((t, tag))
        t.start()
        return True

    def wait(self, timeout=None):
        """Barrier: block until every in-flight save has committed;
        re-raise the first writer error, if any.  With a `timeout`
        (seconds, across ALL in-flight jobs) returns True when drained
        and False when the deadline expired with a writer still alive
        — pending errors are re-raised either way, so a wedged writer
        cannot mask an earlier failed one."""
        deadline = None if timeout is None else \
            time.monotonic() + float(timeout)
        while True:
            with self._lock:
                jobs = list(self._jobs)
            if not jobs:
                break
            for t, _ in jobs:
                if deadline is None:
                    t.join()
                else:
                    t.join(max(0.0, deadline - time.monotonic()))
                    if t.is_alive():
                        self._raise_pending()
                        return False
            self._reap()
        self._raise_pending()
        return True

    def pending(self):
        return len(self._reap())


# ----------------------------------------------------------------------
# latest tag + tag validation
# ----------------------------------------------------------------------
def write_latest_tag(save_dir, tag):
    """Crash-atomic `latest` pointer: write a tmp file, fsync, then
    os.replace — a reader (or a restart after a kill) sees either the
    previous tag or the new one, never a torn write."""
    os.makedirs(save_dir, exist_ok=True)
    path = os.path.join(save_dir, LATEST_FILE)
    # unique tmp name: concurrent writer threads (queue_depth >= 2)
    # must not truncate each other's tmp file between write and rename
    tmp = (f"{path}.{os.getpid()}.{threading.get_ident()}"
           f"{STAGING_SUFFIX}")
    with open(tmp, "w") as f:
        f.write(str(tag))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_path(save_dir)


def read_latest_tag(load_dir, retries=0, backoff_sec=0.05):
    """Read the `latest` pointer (None when absent). `retries` bounds
    retry-with-backoff on transient OSErrors (a reader racing the
    pointer's atomic replace on a laggy network filesystem)."""
    def once():
        path = os.path.join(load_dir, LATEST_FILE)
        if not os.path.exists(path):
            return None
        with open(path, "r") as f:
            return f.read().strip()

    tag = _retry_read(once, retries, backoff_sec,
                      f"latest pointer in {load_dir}")
    if tag is None:
        return None
    if not tag or is_staging_name(tag):
        # a staging name can only reach `latest` by hand-editing; treat
        # it as absent rather than load a possibly half-written dir
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            f"{os.path.join(load_dir, LATEST_FILE)} points at staging "
            f"entry {tag!r}; ignoring it")
        return None
    return tag


def validate_checkpoint_tag(tag, fail_on_mismatch=False):
    """Cross-process tag consistency vote (ref `engine.py:1448-1463`:
    sha1 min/max allreduce).  Returns True when all processes agree."""
    import hashlib
    digest = np.frombuffer(hashlib.sha1(str(tag).encode()).digest(),
                           dtype=np.uint8).astype(np.int32)
    if jax.process_count() == 1:
        return True
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(digest)
    valid = bool((gathered == gathered[0]).all())
    msg = (f"checkpoint tag '{tag}' is not consistent across all "
           "processes; rank-unique tags break restores at different "
           "world sizes")
    if fail_on_mismatch:
        if not valid:
            raise ValueError(msg)
    elif not valid:
        from deepspeed_tpu.utils.logging import logger
        logger.warning(msg)
    return valid
