"""Checkpoint file I/O.

Preserves the reference's on-disk layout (ref `engine.py:1255-1273`):

    <save_dir>/<tag>/mp_rank_00_model_states.pt
    <save_dir>/<tag>/zero_pp_rank_0_mp_rank_00optim_states.pt
    <save_dir>/latest                      (pointer file)

with one deliberate upgrade: state is always saved as *full* (unpartitioned)
arrays, so every checkpoint is an "elastic checkpoint" — loading onto a
different mesh/world size just re-applies the current sharding
(`jax.device_put`), subsuming the reference's elastic-vs-rigid ZeRO-1
formats (`stage1.py:825-1024`) and its topology-change restrictions.

Serialization: numpy-pytree pickle (no torch). On multi-host, only process
0 writes; arrays must be fully addressable or fully replicated (single-
controller JAX guarantees this for state created through the engine).
"""

import os
import pickle

import jax
import numpy as np


MODEL_STATES_FMT = "mp_rank_{:02d}_model_states.pt"
OPTIM_STATES_FMT = "zero_pp_rank_{}_mp_rank_{:02d}optim_states.pt"
LATEST_FILE = "latest"


def _to_numpy(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)),
                                  tree)


def _ckpt_dir(save_dir, tag):
    return os.path.join(save_dir, str(tag))


def model_states_path(save_dir, tag, mp_rank=0):
    return os.path.join(_ckpt_dir(save_dir, tag),
                        MODEL_STATES_FMT.format(mp_rank))


def optim_states_path(save_dir, tag, dp_rank=0, mp_rank=0):
    return os.path.join(_ckpt_dir(save_dir, tag),
                        OPTIM_STATES_FMT.format(dp_rank, mp_rank))


def save_checkpoint_files(save_dir, tag, model_sd, optim_sd,
                          zero_enabled=False, mp_rank=0, dp_rank=0):
    if jax.process_index() != 0:
        return
    os.makedirs(_ckpt_dir(save_dir, tag), exist_ok=True)
    with open(model_states_path(save_dir, tag, mp_rank), "wb") as f:
        pickle.dump(_to_numpy(model_sd), f, protocol=pickle.HIGHEST_PROTOCOL)
    if optim_sd is not None:
        with open(optim_states_path(save_dir, tag, dp_rank, mp_rank),
                  "wb") as f:
            pickle.dump(_to_numpy(optim_sd), f,
                        protocol=pickle.HIGHEST_PROTOCOL)


def load_checkpoint_files(load_dir, tag, zero_enabled=True, mp_rank=0,
                          dp_rank=0):
    with open(model_states_path(load_dir, tag, mp_rank), "rb") as f:
        model_sd = pickle.load(f)
    optim_sd = None
    opt_path = optim_states_path(load_dir, tag, dp_rank, mp_rank)
    if os.path.exists(opt_path):
        with open(opt_path, "rb") as f:
            optim_sd = pickle.load(f)
    return model_sd, optim_sd


def write_latest_tag(save_dir, tag):
    os.makedirs(save_dir, exist_ok=True)
    with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
        f.write(str(tag))


def read_latest_tag(load_dir):
    path = os.path.join(load_dir, LATEST_FILE)
    if not os.path.exists(path):
        return None
    with open(path, "r") as f:
        return f.read().strip()
