"""LR schedules: LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR.

Parity with `deepspeed/runtime/lr_schedules.py:301,408,677,761` (same
schedule math and JSON param names), re-homed for a functional runtime: a
schedule wraps an optimizer-like object exposing `param_groups` (the TPU
engine provides a single-group shim) and the engine reads the scalar lr
each step and feeds it to the jitted update as a traced argument, so lr
changes never trigger recompilation.

Each schedule also has a DEVICE-RESIDENT form (`device_schedule_fn`): a
pure `jnp` function of the step counter, compiled straight into the
engine's fused train step. Under async dispatch the engine evaluates it
on the device-side `global_steps` counter, so no host scalar is computed
or uploaded per step — and because overflow-skipped fp16 steps don't
bump `global_steps`, the reference's "scheduler doesn't advance past an
overflow step" semantics needs no host rewind (and no per-step
`device_get`). `device_schedule_fn(name, params)(step)` equals the host
class's `get_lr()[0]` evaluated at `last_batch_iteration == step`
(fp32 math on device vs float64 on host — parity to ~1e-6 relative).
"""

import math
import argparse

from deepspeed_tpu.utils.logging import logger

LR_SCHEDULE = 'lr_schedule'
LR_RANGE_TEST = 'LRRangeTest'
ONE_CYCLE = 'OneCycle'
WARMUP_LR = 'WarmupLR'
WARMUP_DECAY_LR = 'WarmupDecayLR'
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

LR_RANGE_TEST_MIN_LR = 'lr_range_test_min_lr'
LR_RANGE_TEST_STEP_RATE = 'lr_range_test_step_rate'
LR_RANGE_TEST_STEP_SIZE = 'lr_range_test_step_size'
LR_RANGE_TEST_STAIRCASE = 'lr_range_test_staircase'

EDGE_VALUE = 'edge_value'
MID_VALUE = 'mid_value'

CYCLE_FIRST_STEP_SIZE = 'cycle_first_step_size'
CYCLE_FIRST_STAIR_COUNT = 'cycle_first_stair_count'
CYCLE_SECOND_STEP_SIZE = 'cycle_second_step_size'
CYCLE_SECOND_STAIR_COUNT = 'cycle_second_stair_count'
DECAY_STEP_SIZE = 'decay_step_size'

CYCLE_MIN_LR = 'cycle_min_lr'
CYCLE_MAX_LR = 'cycle_max_lr'
DECAY_LR_RATE = 'decay_lr_rate'

CYCLE_MIN_MOM = 'cycle_min_mom'
CYCLE_MAX_MOM = 'cycle_max_mom'
DECAY_MOM_RATE = 'decay_mom_rate'

WARMUP_MIN_LR = 'warmup_min_lr'
WARMUP_MAX_LR = 'warmup_max_lr'
WARMUP_NUM_STEPS = 'warmup_num_steps'
TOTAL_NUM_STEPS = 'total_num_steps'


def add_tuning_arguments(parser):
    group = parser.add_argument_group('Convergence Tuning',
                                      'Convergence tuning configurations')
    group.add_argument('--lr_schedule', type=str, default=None,
                       help='LR schedule for training.')
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_staircase", type=bool, default=False)
    group.add_argument("--cycle_first_step_size", type=int, default=1000)
    group.add_argument("--cycle_first_stair_count", type=int, default=1)
    group.add_argument("--cycle_second_step_size", type=int, default=-1)
    group.add_argument("--cycle_second_stair_count", type=int, default=-1)
    group.add_argument("--decay_step_size", type=int, default=1000)
    group.add_argument("--cycle_min_lr", type=float, default=0.01)
    group.add_argument("--cycle_max_lr", type=float, default=0.1)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--cycle_momentum", type=bool, default=False)
    group.add_argument("--cycle_min_mom", type=float, default=0.8)
    group.add_argument("--cycle_max_mom", type=float, default=0.9)
    group.add_argument("--decay_mom_rate", type=float, default=0.0)
    group.add_argument('--warmup_min_lr', type=float, default=0)
    group.add_argument('--warmup_max_lr', type=float, default=0.001)
    group.add_argument('--warmup_num_steps', type=int, default=1000)
    return parser


def parse_arguments():
    parser = argparse.ArgumentParser()
    parser = add_tuning_arguments(parser)
    lr_sched_args, unknown_args = parser.parse_known_args()
    return lr_sched_args, unknown_args


def get_config_from_args(args):
    if not hasattr(args, LR_SCHEDULE) or args.lr_schedule is None:
        return None, '--{} not specified on command line'.format(LR_SCHEDULE)
    if args.lr_schedule not in VALID_LR_SCHEDULES:
        return None, '{} is not supported LR schedule'.format(args.lr_schedule)

    config = {'type': args.lr_schedule, 'params': {}}
    if args.lr_schedule == LR_RANGE_TEST:
        keys = [LR_RANGE_TEST_MIN_LR, LR_RANGE_TEST_STEP_RATE,
                LR_RANGE_TEST_STEP_SIZE, LR_RANGE_TEST_STAIRCASE]
    elif args.lr_schedule == ONE_CYCLE:
        keys = [CYCLE_MIN_LR, CYCLE_MAX_LR, DECAY_LR_RATE,
                CYCLE_FIRST_STEP_SIZE, CYCLE_FIRST_STAIR_COUNT,
                CYCLE_SECOND_STEP_SIZE, CYCLE_SECOND_STAIR_COUNT,
                DECAY_STEP_SIZE, CYCLE_MIN_MOM, CYCLE_MAX_MOM, DECAY_MOM_RATE]
    else:
        keys = [WARMUP_MIN_LR, WARMUP_MAX_LR, WARMUP_NUM_STEPS]
        if args.lr_schedule == WARMUP_DECAY_LR:
            keys.append(TOTAL_NUM_STEPS)
    for key in keys:
        if hasattr(args, key):
            config['params'][key] = getattr(args, key)
    return config, None


class _OptimizerShim:
    """Minimal optimizer-like object with `param_groups` for schedulers
    operating standalone (the engine passes its own shim)."""

    def __init__(self, lr=0.0, momentum=0.9, betas=(0.9, 0.999)):
        self.param_groups = [{'lr': lr, 'momentum': momentum, 'betas': betas}]


def get_lr_compatible_optimizer(optimizer):
    if optimizer is None:
        return _OptimizerShim()
    if hasattr(optimizer, 'param_groups'):
        return optimizer
    raise TypeError(f'{type(optimizer).__name__} is not an Optimizer')


class _BaseSchedule:
    """Shared step/state_dict plumbing for all schedules."""

    def __init__(self, optimizer, last_batch_iteration=-1):
        self.optimizer = get_lr_compatible_optimizer(optimizer)
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self):
        raise NotImplementedError

    def get_last_lr(self):
        assert getattr(self, '_last_lr', None) is not None, \
            "need to call step() first"
        return self._last_lr

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        for param_group, lr in zip(self.optimizer.param_groups, self.get_lr()):
            param_group['lr'] = lr
        self._last_lr = [group['lr'] for group in self.optimizer.param_groups]

    def state_dict(self):
        return {'last_batch_iteration': self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd['last_batch_iteration']

    def _format_param(self, optimizer, param_value, param_name):
        if isinstance(param_value, (list, tuple)):
            if len(param_value) != len(optimizer.param_groups):
                raise ValueError("expected {} value for {}, got {}".format(
                    len(optimizer.param_groups), param_name, param_value))
            return list(param_value)
        return [param_value] * len(optimizer.param_groups)


class LRRangeTest(_BaseSchedule):
    """LR range test (Smith 2018): lr grows from min_lr by step_rate per
    interval, continuously or staircase."""

    def __init__(self,
                 optimizer,
                 lr_range_test_min_lr: float = 1e-3,
                 lr_range_test_step_size: int = 2000,
                 lr_range_test_step_rate: float = 1.0,
                 lr_range_test_staircase: bool = False,
                 last_batch_iteration: int = -1):
        super().__init__(optimizer, last_batch_iteration)
        self.min_lr = self._format_param(self.optimizer, lr_range_test_min_lr,
                                         'lr_range_test_min_lr')
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase
        self.interval_fn = self._staircase_interval if lr_range_test_staircase \
            else self._continuous_interval
        if last_batch_iteration == -1:
            self._update_optimizer(self.min_lr)

    def _staircase_interval(self):
        return math.floor(float(self.last_batch_iteration + 1) / self.step_size)

    def _continuous_interval(self):
        return float(self.last_batch_iteration + 1) / self.step_size

    def _get_increase(self):
        return (1 + self.step_rate * self.interval_fn())

    def get_lr(self):
        lr_increase = self._get_increase()
        return [lr_range_test_min_lr * lr_increase
                for lr_range_test_min_lr in self.min_lr]

    def _update_optimizer(self, group_lrs):
        for param_group, lr in zip(self.optimizer.param_groups, group_lrs):
            param_group['lr'] = lr


class OneCycle(_BaseSchedule):
    """1-cycle policy (Smith 2018): lr ramps min→max over the first phase,
    max→min over the second, then decays; momentum cycles inversely."""

    def __init__(self,
                 optimizer,
                 cycle_min_lr,
                 cycle_max_lr,
                 decay_lr_rate=0.,
                 cycle_first_step_size=2000,
                 cycle_second_step_size=None,
                 cycle_first_stair_count=0,
                 cycle_second_stair_count=None,
                 decay_step_size=0,
                 cycle_momentum=True,
                 cycle_min_mom=0.8,
                 cycle_max_mom=0.9,
                 decay_mom_rate=0.,
                 last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self._initialize_cycle(cycle_first_step_size, cycle_second_step_size,
                               cycle_first_stair_count,
                               cycle_second_stair_count, decay_step_size)
        self._initialize_lr(self.optimizer, cycle_min_lr, cycle_max_lr,
                            decay_lr_rate, last_batch_iteration)
        self.cycle_momentum = cycle_momentum
        if cycle_momentum:
            self._initialize_momentum(self.optimizer, cycle_min_mom,
                                      cycle_max_mom, decay_mom_rate,
                                      last_batch_iteration)

    def _initialize_cycle(self, cycle_first_step_size, cycle_second_step_size,
                          cycle_first_stair_count, cycle_second_stair_count,
                          decay_step_size):
        cycle_first_step_size = float(cycle_first_step_size)
        cycle_second_step_size = float(cycle_second_step_size) \
            if cycle_second_step_size is not None else cycle_first_step_size

        self.total_size = cycle_first_step_size + cycle_second_step_size
        self.step_ratio = cycle_first_step_size / self.total_size
        self.first_stair_count = cycle_first_stair_count
        self.second_stair_count = cycle_first_stair_count \
            if cycle_second_stair_count is None else cycle_second_stair_count
        self.decay_step_size = decay_step_size

    def _initialize_lr(self, optimizer, cycle_min_lr, cycle_max_lr,
                       decay_lr_rate, last_batch_iteration):
        self.min_lrs = [cycle_min_lr] * len(optimizer.param_groups)
        if last_batch_iteration == -1:
            for lr, group in zip(self.min_lrs, optimizer.param_groups):
                group['lr'] = lr
        self.max_lrs = [cycle_max_lr] * len(optimizer.param_groups)
        self.decay_lr_rate = decay_lr_rate

    def _initialize_momentum(self, optimizer, cycle_min_mom, cycle_max_mom,
                             decay_mom_rate, last_batch_iteration):
        if 'betas' not in optimizer.param_groups[0] and \
                'momentum' not in optimizer.param_groups[0]:
            optimizer_name = type(optimizer).__name__
            logger.warning(
                f"cycle_momentum is disabled because optimizer "
                f"{optimizer_name} does not support momentum")
            self.cycle_momentum = False
            return
        self.decay_mom_rate = decay_mom_rate
        self.min_moms = [(cycle_min_mom, 0.99)] * len(optimizer.param_groups)
        self.max_moms = [(cycle_max_mom, 0.99)] * len(optimizer.param_groups)
        if last_batch_iteration == -1:
            for momentum, group in zip(self.min_moms, optimizer.param_groups):
                group['betas'] = momentum

    def _get_scale_factor(self):
        batch_iteration = (self.last_batch_iteration + 1)
        cycle = math.floor(1 + batch_iteration / self.total_size)
        x = 1. + batch_iteration / self.total_size - cycle
        if x <= self.step_ratio:
            scale_factor = x / self.step_ratio
        else:
            scale_factor = (x - 1) / (self.step_ratio - 1)
        return scale_factor

    def _get_cycle_mom(self):
        scale_factor = self._get_scale_factor()
        momentums = []
        for base_betas, max_betas in zip(self.min_moms, self.max_moms):
            cycle_min_mom = base_betas[0]
            cycle_max_mom = max_betas[0]
            base_height = (cycle_max_mom - cycle_min_mom) * scale_factor
            momentum = cycle_max_mom - base_height
            momentums.append((momentum, base_betas[1]))
        return momentums

    def _get_cycle_lr(self):
        scale_factor = self._get_scale_factor()
        lrs = []
        for cycle_min_lr, cycle_max_lr in zip(self.min_lrs, self.max_lrs):
            base_height = (cycle_max_lr - cycle_min_lr) * scale_factor
            lr = cycle_min_lr + base_height
            lrs.append(lr)
        return lrs

    def _get_decay_mom(self, decay_batch_iteration):
        decay_interval = decay_batch_iteration / self.decay_step_size
        mom_decay_factor = (1 + self.decay_mom_rate * decay_interval)
        return [(beta0 * mom_decay_factor, beta1)
                for beta0, beta1 in self.max_moms]

    def _get_decay_lr(self, decay_batch_iteration):
        decay_interval = decay_batch_iteration / self.decay_step_size
        lr_decay_factor = (1 + self.decay_lr_rate * decay_interval)
        return [cycle_min_lr / lr_decay_factor for cycle_min_lr in self.min_lrs]

    def get_lr(self):
        if self.last_batch_iteration < self.total_size:
            return self._get_cycle_lr()
        return self._get_decay_lr(self.last_batch_iteration - self.total_size + 1)

    def get_mom(self):
        if not self.cycle_momentum:
            return None
        if self.last_batch_iteration < self.total_size:
            return self._get_cycle_mom()
        return self._get_decay_mom(self.last_batch_iteration - self.total_size + 1)

    def step(self, batch_iteration=None):
        if batch_iteration is None:
            batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = batch_iteration
        for param_group, lr in zip(self.optimizer.param_groups, self.get_lr()):
            param_group['lr'] = lr
        self._last_lr = [group['lr'] for group in self.optimizer.param_groups]
        if self.cycle_momentum:
            momentums = self.get_mom()
            for param_group, momentum in zip(self.optimizer.param_groups,
                                             momentums):
                param_group['betas'] = momentum


class WarmupLR(_BaseSchedule):
    """Log-warmup from min_lr to max_lr over warmup_num_steps, then flat."""

    def __init__(self,
                 optimizer,
                 warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001,
                 warmup_num_steps: int = 1000,
                 last_batch_iteration: int = -1):
        super().__init__(optimizer, last_batch_iteration)
        self.min_lrs = self._format_param(self.optimizer, warmup_min_lr,
                                          "min_lr")
        self.max_lrs = self._format_param(self.optimizer, warmup_max_lr,
                                          "max_lr")
        self.delta_lrs = [big - small
                          for big, small in zip(self.max_lrs, self.min_lrs)]
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)

    def get_lr(self):
        if self.last_batch_iteration < 0:
            logger.warning("Attempting to get learning rate from scheduler "
                           "before it has started")
            return [0.0]
        gamma = self._get_gamma()
        return [min_lr + (delta_lr * gamma)
                for min_lr, delta_lr in zip(self.min_lrs, self.delta_lrs)]

    def _get_gamma(self):
        if self.last_batch_iteration < self.warmup_num_steps:
            return self.inverse_log_warm_up * \
                math.log(self.last_batch_iteration + 1)
        return 1.0


def device_schedule_fn(name, params=None, base_lr=None):
    """Device-resident schedule: a pure-jnp `lr(step)` for compiling
    into a jitted train step.

    `step` is the count of prior SUCCESSFUL optimizer steps (the
    engine's device `global_steps` counter), which equals the host
    scheduler's `last_batch_iteration` at lr-evaluation time: the host
    path calls `step()` (incrementing -1→0 on the first step) before
    reading the lr, and rewinds on overflow.

    name=None returns a constant-`base_lr` schedule (or None when
    base_lr is None — client optimizers keep their own lr). `params`
    uses the JSON scheduler-param keys; defaults match the host
    classes. Accepts scalar or array `step` (the parity sweep
    evaluates whole ranges at once).
    """
    import jax.numpy as jnp

    if name is None:
        if base_lr is None:
            return None
        const = float(base_lr)
        return lambda step: jnp.full(jnp.shape(step), const, jnp.float32)
    if name not in VALID_LR_SCHEDULES:
        raise ValueError(f"Unknown scheduler {name}")
    p = dict(params or {})

    def f32(x):
        return jnp.asarray(x, jnp.float32)

    if name == LR_RANGE_TEST:
        min_lr = float(p.get(LR_RANGE_TEST_MIN_LR, 1e-3))
        step_size = float(p.get(LR_RANGE_TEST_STEP_SIZE, 2000))
        step_rate = float(p.get(LR_RANGE_TEST_STEP_RATE, 1.0))
        staircase = bool(p.get(LR_RANGE_TEST_STAIRCASE, False))

        def lr_range_test(step):
            interval = (f32(step) + 1.0) / step_size
            if staircase:
                interval = jnp.floor(interval)
            return f32(min_lr * (1.0 + step_rate * interval))
        return lr_range_test

    if name == ONE_CYCLE:
        cycle_min_lr = float(p[CYCLE_MIN_LR])
        cycle_max_lr = float(p[CYCLE_MAX_LR])
        decay_lr_rate = float(p.get(DECAY_LR_RATE, 0.0))
        first = float(p.get(CYCLE_FIRST_STEP_SIZE, 2000))
        second = p.get(CYCLE_SECOND_STEP_SIZE)
        second = float(second) if second is not None else first
        total_size = first + second
        step_ratio = first / total_size
        decay_step_size = float(p.get(DECAY_STEP_SIZE, 0))
        # the decay branch divides by decay_step_size; guard the traced
        # (always-evaluated) branch — selected only past total_size,
        # where the host class requires a positive decay_step_size too
        decay_div = max(decay_step_size, 1.0)

        def one_cycle(step):
            step = f32(step)
            bi = step + 1.0
            cycle = jnp.floor(1.0 + bi / total_size)
            x = 1.0 + bi / total_size - cycle
            scale = jnp.where(x <= step_ratio, x / step_ratio,
                              (x - 1.0) / (step_ratio - 1.0))
            cycle_lr = cycle_min_lr + \
                (cycle_max_lr - cycle_min_lr) * scale
            decay_interval = (step - total_size + 1.0) / decay_div
            decay_lr = cycle_min_lr / \
                (1.0 + decay_lr_rate * decay_interval)
            return f32(jnp.where(step < total_size, cycle_lr, decay_lr))
        return one_cycle

    # WarmupLR / WarmupDecayLR
    warmup_min_lr = float(p.get(WARMUP_MIN_LR, 0.0))
    warmup_max_lr = float(p.get(WARMUP_MAX_LR, 0.001))
    warmup_num_steps = max(2, int(p.get(WARMUP_NUM_STEPS, 1000)))
    delta_lr = warmup_max_lr - warmup_min_lr
    inv_log_warmup = 1.0 / math.log(warmup_num_steps)
    total_num_steps = int(p[TOTAL_NUM_STEPS]) \
        if name == WARMUP_DECAY_LR else None

    def warmup_lr(step):
        step = f32(step)
        warm_gamma = inv_log_warmup * jnp.log(step + 1.0)
        if total_num_steps is None:
            post = 1.0
        else:
            post = jnp.maximum(
                0.0, (total_num_steps - step) /
                max(1.0, float(total_num_steps - warmup_num_steps)))
        gamma = jnp.where(step < warmup_num_steps, warm_gamma, post)
        return f32(warmup_min_lr + delta_lr * gamma)
    return warmup_lr


class WarmupDecayLR(WarmupLR):
    """WarmupLR followed by linear decay to 0 at total_num_steps."""

    def __init__(self,
                 optimizer,
                 total_num_steps: int,
                 warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001,
                 warmup_num_steps: int = 1000,
                 last_batch_iteration: int = -1):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr,
                         warmup_num_steps, last_batch_iteration)
        if self.total_num_steps < self.warmup_num_steps:
            logger.warning(
                'total_num_steps {} is less than warmup_num_steps {}'.format(
                    total_num_steps, warmup_num_steps))

    def _get_gamma(self):
        if self.last_batch_iteration < self.warmup_num_steps:
            return self.inverse_log_warm_up * \
                math.log(self.last_batch_iteration + 1)
        return max(
            0.0,
            float(self.total_num_steps - self.last_batch_iteration) /
            float(max(1.0, self.total_num_steps - self.warmup_num_steps)))
