"""DeepSpeedConfig — JSON config parsing + batch-triple resolution.

Parity with `deepspeed/runtime/config.py:515`:
  * accepts a JSON file path or a dict
  * batch triple: train_batch_size = micro_batch_per_gpu × grad_accum ×
    data-parallel world size; any two determine the third
    (ref `config.py:655-728`)
  * subconfigs: fp16, zero_optimization, activation_checkpointing,
    flops_profiler, tensorboard, pld, sparse_attention, pipeline
  * elasticity: recomputes the batch triple from
    DEEPSPEED_ELASTICITY_CONFIG env (ref `elasticity.py:207-237`)

TPU-native additions: a `bf16` block (the natural TPU precision) and a
`mesh` block naming device-mesh axis sizes.
"""

import os

from deepspeed_tpu.runtime.constants import *  # noqa: F401,F403
from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config_utils import (
    get_scalar_param,
    load_config_dict,
)
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.activation_checkpointing.config import (
    DeepSpeedActivationCheckpointingConfig, )
from deepspeed_tpu.profiling.config import DeepSpeedFlopsProfilerConfig
from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


def get_fp16_enabled(param_dict):
    if C.FP16 in param_dict:
        return get_scalar_param(param_dict[C.FP16], C.FP16_ENABLED,
                                C.FP16_ENABLED_DEFAULT)
    return False


def get_bfloat16_enabled(param_dict):
    # Accept both the canonical "bf16" key and the "bfloat16" spelling.
    for key in (C.BFLOAT16, C.BFLOAT16_ALIAS):
        if key in param_dict:
            return get_scalar_param(param_dict[key], C.BFLOAT16_ENABLED,
                                    C.BFLOAT16_ENABLED_DEFAULT)
    return False


def get_bfloat16_master_weights(param_dict):
    for key in (C.BFLOAT16, C.BFLOAT16_ALIAS):
        if key in param_dict:
            return get_scalar_param(param_dict[key],
                                    C.BFLOAT16_MASTER_WEIGHTS,
                                    C.BFLOAT16_MASTER_WEIGHTS_DEFAULT)
    return C.BFLOAT16_MASTER_WEIGHTS_DEFAULT


def get_loss_scale(param_dict):
    if get_fp16_enabled(param_dict):
        return get_scalar_param(param_dict[C.FP16], C.FP16_LOSS_SCALE,
                                C.FP16_LOSS_SCALE_DEFAULT)
    return C.FP16_LOSS_SCALE_DEFAULT


def get_initial_dynamic_scale(param_dict):
    if get_fp16_enabled(param_dict):
        initial_scale_power = get_scalar_param(param_dict[C.FP16],
                                               C.FP16_INITIAL_SCALE_POWER,
                                               C.FP16_INITIAL_SCALE_POWER_DEFAULT)
    else:
        initial_scale_power = C.FP16_INITIAL_SCALE_POWER_DEFAULT
    return 2**initial_scale_power


def get_dynamic_loss_scale_args(param_dict):
    loss_scale_args = None
    if get_fp16_enabled(param_dict):
        fp16_dict = param_dict[C.FP16]
        dynamic_props = [
            C.FP16_INITIAL_SCALE_POWER, C.FP16_LOSS_SCALE_WINDOW,
            C.FP16_MIN_LOSS_SCALE, C.FP16_HYSTERESIS
        ]
        if any(p in fp16_dict for p in dynamic_props):
            init_scale = get_scalar_param(fp16_dict, C.FP16_INITIAL_SCALE_POWER,
                                          C.FP16_INITIAL_SCALE_POWER_DEFAULT)
            scale_window = get_scalar_param(fp16_dict, C.FP16_LOSS_SCALE_WINDOW,
                                            C.FP16_LOSS_SCALE_WINDOW_DEFAULT)
            delayed_shift = get_scalar_param(fp16_dict, C.FP16_HYSTERESIS,
                                             C.FP16_HYSTERESIS_DEFAULT)
            min_loss_scale = get_scalar_param(fp16_dict, C.FP16_MIN_LOSS_SCALE,
                                              C.FP16_MIN_LOSS_SCALE_DEFAULT)
            loss_scale_args = {
                "init_scale": 2**init_scale,
                "scale_window": scale_window,
                "delayed_shift": delayed_shift,
                "min_scale": min_loss_scale,
            }
    return loss_scale_args


def get_gradient_accumulation_steps(param_dict):
    return get_scalar_param(param_dict, C.GRADIENT_ACCUMULATION_STEPS,
                            C.GRADIENT_ACCUMULATION_STEPS_DEFAULT)


def get_sparse_gradients_enabled(param_dict):
    return get_scalar_param(param_dict, C.SPARSE_GRADIENTS,
                            C.SPARSE_GRADIENTS_DEFAULT)


def get_gradient_clipping(param_dict):
    return get_scalar_param(param_dict, C.GRADIENT_CLIPPING,
                            C.GRADIENT_CLIPPING_DEFAULT)


def get_sparse_attention(param_dict):
    if C.SPARSE_ATTENTION in param_dict:
        sparsity = param_dict[C.SPARSE_ATTENTION]
        mode = get_scalar_param(sparsity, C.SPARSE_MODE, C.SPARSE_MODE_DEFAULT)
        if mode not in C.SPARSE_MODE_VALID:
            raise DeepSpeedConfigError(
                f"sparse_attention.mode must be one of "
                f"{list(C.SPARSE_MODE_VALID)}, got {mode!r}")
        # the block passes through wholesale to the SparsityConfig
        # constructors; an unknown key would otherwise surface as a
        # TypeError deep inside ops/sparse_attention
        unknown = set(sparsity) - set(C.SPARSE_ATTENTION_KEYS)
        if unknown:
            logger.warning(
                f"sparse_attention: ignoring unknown key(s) "
                f"{sorted(unknown)}; known keys: "
                f"{list(C.SPARSE_ATTENTION_KEYS)}")
        sparsity = {k: v for k, v in sparsity.items()
                    if k in C.SPARSE_ATTENTION_KEYS}
        sparsity[C.SPARSE_MODE] = mode
        return sparsity
    return None


def get_optimizer_name(param_dict):
    if C.OPTIMIZER in param_dict and C.TYPE in param_dict[C.OPTIMIZER]:
        return param_dict[C.OPTIMIZER][C.TYPE]
    return C.OPTIMIZER_TYPE_DEFAULT


def get_optimizer_params(param_dict):
    if get_optimizer_name(param_dict) is not None and \
            C.OPTIMIZER_PARAMS in param_dict[C.OPTIMIZER]:
        return param_dict[C.OPTIMIZER][C.OPTIMIZER_PARAMS]
    return None


def get_optimizer_gradient_clipping(param_dict):
    optimizer_params = get_optimizer_params(param_dict)
    if optimizer_params is not None and C.MAX_GRAD_NORM in optimizer_params:
        return optimizer_params[C.MAX_GRAD_NORM]
    return None


def get_optimizer_legacy_fusion(param_dict):
    if C.OPTIMIZER in param_dict and C.LEGACY_FUSION in param_dict[C.OPTIMIZER]:
        return param_dict[C.OPTIMIZER][C.LEGACY_FUSION]
    return C.LEGACY_FUSION_DEFAULT


def get_zero_allow_untested_optimizer(param_dict):
    return get_scalar_param(param_dict, C.ZERO_ALLOW_UNTESTED_OPTIMIZER,
                            C.ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT)


def get_scheduler_name(param_dict):
    if C.SCHEDULER in param_dict and C.TYPE in param_dict[C.SCHEDULER]:
        return param_dict[C.SCHEDULER][C.TYPE]
    return C.SCHEDULER_TYPE_DEFAULT


def get_scheduler_params(param_dict):
    if get_scheduler_name(param_dict) is not None and \
            C.SCHEDULER_PARAMS in param_dict[C.SCHEDULER]:
        return param_dict[C.SCHEDULER][C.SCHEDULER_PARAMS]
    return None


def get_train_batch_size(param_dict):
    return get_scalar_param(param_dict, C.TRAIN_BATCH_SIZE,
                            C.TRAIN_BATCH_SIZE_DEFAULT)


def get_train_micro_batch_size_per_gpu(param_dict):
    return get_scalar_param(param_dict, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                            C.TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)


def get_steps_per_print(param_dict):
    return get_scalar_param(param_dict, C.STEPS_PER_PRINT,
                            C.STEPS_PER_PRINT_DEFAULT)


def get_wall_clock_breakdown(param_dict):
    return get_scalar_param(param_dict, C.WALL_CLOCK_BREAKDOWN,
                            C.WALL_CLOCK_BREAKDOWN_DEFAULT)


def get_memory_breakdown(param_dict):
    return get_scalar_param(param_dict, C.MEMORY_BREAKDOWN,
                            C.MEMORY_BREAKDOWN_DEFAULT)


def get_dump_state(param_dict):
    return get_scalar_param(param_dict, C.DUMP_STATE, C.DUMP_STATE_DEFAULT)


def get_prescale_gradients(param_dict):
    return get_scalar_param(param_dict, C.PRESCALE_GRADIENTS,
                            C.PRESCALE_GRADIENTS_DEFAULT)


def get_gradient_predivide_factor(param_dict):
    return get_scalar_param(param_dict, C.GRADIENT_PREDIVIDE_FACTOR,
                            C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)


def get_allreduce_always_fp32(param_dict):
    return get_scalar_param(param_dict, C.FP32_ALLREDUCE,
                            C.FP32_ALLREDUCE_DEFAULT)


def get_disable_allgather(param_dict):
    return get_scalar_param(param_dict, C.DISABLE_ALLGATHER,
                            C.DISABLE_ALLGATHER_DEFAULT)


def get_tensorboard_enabled(param_dict):
    if C.TENSORBOARD in param_dict:
        return get_scalar_param(param_dict[C.TENSORBOARD], C.TENSORBOARD_ENABLED,
                                C.TENSORBOARD_ENABLED_DEFAULT)
    return False


def get_tensorboard_output_path(param_dict):
    if get_tensorboard_enabled(param_dict):
        return get_scalar_param(param_dict[C.TENSORBOARD],
                                C.TENSORBOARD_OUTPUT_PATH,
                                C.TENSORBOARD_OUTPUT_PATH_DEFAULT)
    return C.TENSORBOARD_OUTPUT_PATH_DEFAULT


def get_tensorboard_job_name(param_dict):
    if get_tensorboard_enabled(param_dict):
        return get_scalar_param(param_dict[C.TENSORBOARD],
                                C.TENSORBOARD_JOB_NAME,
                                C.TENSORBOARD_JOB_NAME_DEFAULT)
    return C.TENSORBOARD_JOB_NAME_DEFAULT


def get_checkpoint_tag_validation(param_dict):
    checkpoint_dict = param_dict.get(C.CHECKPOINT, {})
    mode = get_scalar_param(checkpoint_dict, C.CHECKPOINT_TAG_VALIDATION,
                            C.CHECKPOINT_TAG_VALIDATION_DEFAULT)
    mode = mode.capitalize()
    if mode not in C.CHECKPOINT_TAG_VALIDATION_MODES:
        raise DeepSpeedConfigError(
            f"checkpoint.tag_validation mode {mode} not one of "
            f"{C.CHECKPOINT_TAG_VALIDATION_MODES}")
    return mode


def get_checkpoint_async_save(param_dict):
    block = param_dict.get(C.CHECKPOINT, {})
    return bool(get_scalar_param(block, C.CHECKPOINT_ASYNC_SAVE,
                                 C.CHECKPOINT_ASYNC_SAVE_DEFAULT))


def get_checkpoint_keep_last(param_dict):
    block = param_dict.get(C.CHECKPOINT, {})
    val = get_scalar_param(block, C.CHECKPOINT_KEEP_LAST,
                           C.CHECKPOINT_KEEP_LAST_DEFAULT)
    if val < 0:
        raise DeepSpeedConfigError(
            f"checkpoint.keep_last must be >= 0 (0 = keep all), got {val}")
    return int(val)


def get_checkpoint_writer_queue_depth(param_dict):
    block = param_dict.get(C.CHECKPOINT, {})
    val = get_scalar_param(block, C.CHECKPOINT_WRITER_QUEUE_DEPTH,
                           C.CHECKPOINT_WRITER_QUEUE_DEPTH_DEFAULT)
    if val < 1:
        raise DeepSpeedConfigError(
            f"checkpoint.writer_queue_depth must be >= 1, got {val}")
    return int(val)


def get_checkpoint_queue_policy(param_dict):
    block = param_dict.get(C.CHECKPOINT, {})
    val = get_scalar_param(block, C.CHECKPOINT_QUEUE_POLICY,
                           C.CHECKPOINT_QUEUE_POLICY_DEFAULT)
    if val not in C.CHECKPOINT_QUEUE_POLICIES:
        raise DeepSpeedConfigError(
            f"checkpoint.queue_policy {val!r} not one of "
            f"{C.CHECKPOINT_QUEUE_POLICIES}")
    return val


def get_pld_enabled(param_dict):
    if C.PROGRESSIVE_LAYER_DROP in param_dict:
        return get_scalar_param(param_dict[C.PROGRESSIVE_LAYER_DROP],
                                C.PLD_ENABLED, C.PLD_ENABLED_DEFAULT)
    return False


def get_pld_params(param_dict):
    if C.PROGRESSIVE_LAYER_DROP in param_dict:
        block = param_dict[C.PROGRESSIVE_LAYER_DROP]
        # pass through ONLY the declared keys, and only when present:
        # absent keys fall to ProgressiveLayerDrop's constructor
        # defaults (theta=0.5) — substituting C.PLD_THETA_DEFAULT
        # (1.0, the reference constants value) here would silently
        # turn PLD into a no-op for configs that just set enabled
        unknown = set(block) - {C.PLD_ENABLED, C.PLD_THETA,
                                C.PLD_GAMMA}
        if unknown:
            logger.warning(
                f"progressive_layer_drop: ignoring unknown key(s) "
                f"{sorted(unknown)}")
        return {k: block[k] for k in (C.PLD_THETA, C.PLD_GAMMA)
                if k in block}
    return False


def get_pipeline_config(param_dict):
    pipeline = get_scalar_param(param_dict, C.PIPELINE,
                                dict(C.PIPELINE_DEFAULT))
    if not isinstance(pipeline, dict):
        raise DeepSpeedConfigError(
            f'"pipeline" must be a dict, got {pipeline!r}')
    v = pipeline.get(C.PIPELINE_NUM_VIRTUAL_STAGES,
                     C.PIPELINE_NUM_VIRTUAL_STAGES_DEFAULT)
    if not isinstance(v, int) or isinstance(v, bool) or v < 1:
        raise DeepSpeedConfigError(
            f"pipeline.num_virtual_stages must be an int >= 1, got "
            f"{v!r}")
    return pipeline


def get_mesh_config(param_dict):
    return get_scalar_param(param_dict, C.MESH, None)


def get_async_dispatch_enabled(param_dict):
    block = param_dict.get(C.ASYNC_DISPATCH, {})
    return get_scalar_param(block, C.ASYNC_DISPATCH_ENABLED,
                            C.ASYNC_DISPATCH_ENABLED_DEFAULT)


def get_async_dispatch_steps_per_sync(param_dict):
    block = param_dict.get(C.ASYNC_DISPATCH, {})
    val = get_scalar_param(block, C.ASYNC_DISPATCH_STEPS_PER_SYNC,
                           C.ASYNC_DISPATCH_STEPS_PER_SYNC_DEFAULT)
    if val < 0:
        raise DeepSpeedConfigError(
            f"async_dispatch.steps_per_sync must be >= 0 (0 = follow "
            f"steps_per_print), got {val}")
    return int(val)


def get_async_dispatch_prefetch_depth(param_dict):
    block = param_dict.get(C.ASYNC_DISPATCH, {})
    val = get_scalar_param(block, C.ASYNC_DISPATCH_PREFETCH_DEPTH,
                           C.ASYNC_DISPATCH_PREFETCH_DEPTH_DEFAULT)
    if val < 1:
        raise DeepSpeedConfigError(
            f"async_dispatch.prefetch_depth must be >= 1, got {val}")
    return int(val)


def get_quantized_compute_config(param_dict):
    """Validated `quantized_compute` block -> dict(enabled, mode,
    block, stochastic_rounding)."""
    block = param_dict.get(C.QUANTIZED_COMPUTE, {})
    if not isinstance(block, dict):
        raise DeepSpeedConfigError(
            f'"quantized_compute" must be a dict, got {block!r}')
    enabled = bool(get_scalar_param(
        block, C.QUANTIZED_COMPUTE_ENABLED,
        C.QUANTIZED_COMPUTE_ENABLED_DEFAULT))
    mode = get_scalar_param(block, C.QUANTIZED_COMPUTE_MODE,
                            C.QUANTIZED_COMPUTE_MODE_DEFAULT)
    if mode not in C.QUANTIZED_COMPUTE_MODE_VALID:
        raise DeepSpeedConfigError(
            f"quantized_compute.mode must be one of "
            f"{list(C.QUANTIZED_COMPUTE_MODE_VALID)}, got {mode!r}")
    qblock = get_scalar_param(block, C.QUANTIZED_COMPUTE_BLOCK,
                              C.QUANTIZED_COMPUTE_BLOCK_DEFAULT)
    if not isinstance(qblock, int) or isinstance(qblock, bool) or \
            qblock < 1:
        raise DeepSpeedConfigError(
            f"quantized_compute.block must be an int >= 1, got "
            f"{qblock!r}")
    sr = bool(get_scalar_param(
        block, C.QUANTIZED_COMPUTE_STOCHASTIC_ROUNDING,
        C.QUANTIZED_COMPUTE_STOCHASTIC_ROUNDING_DEFAULT))
    return {"enabled": enabled, "mode": mode, "block": qblock,
            "stochastic_rounding": sr}


def get_moe_config(param_dict):
    """Validated `moe` block -> dict(enabled, num_experts, top_k,
    capacity_factor, aux_loss_weight, every_n_layers, jitter_eps).
    Structural keys (num_experts, every_n_layers) are later VERIFIED
    against the built model by the engine's configure_moe hook; the
    router knobs are applied (deepspeed_tpu/moe/)."""
    block = param_dict.get(C.MOE, {})
    if not isinstance(block, dict):
        raise DeepSpeedConfigError(
            f'"moe" must be a dict, got {block!r}')
    enabled = bool(get_scalar_param(block, C.MOE_ENABLED,
                                    C.MOE_ENABLED_DEFAULT))
    num_experts = get_scalar_param(block, C.MOE_NUM_EXPERTS,
                                   C.MOE_NUM_EXPERTS_DEFAULT)
    if not isinstance(num_experts, int) or \
            isinstance(num_experts, bool) or num_experts < 2:
        raise DeepSpeedConfigError(
            f"moe.num_experts must be an int >= 2, got {num_experts!r}")
    top_k = get_scalar_param(block, C.MOE_TOP_K, C.MOE_TOP_K_DEFAULT)
    if not isinstance(top_k, int) or isinstance(top_k, bool) or \
            not 1 <= top_k <= num_experts:
        raise DeepSpeedConfigError(
            f"moe.top_k must be an int in [1, num_experts="
            f"{num_experts}], got {top_k!r}")
    cf = get_scalar_param(block, C.MOE_CAPACITY_FACTOR,
                          C.MOE_CAPACITY_FACTOR_DEFAULT)
    if not isinstance(cf, (int, float)) or isinstance(cf, bool) or \
            cf <= 0:
        raise DeepSpeedConfigError(
            f"moe.capacity_factor must be > 0, got {cf!r}")
    aux = get_scalar_param(block, C.MOE_AUX_LOSS_WEIGHT,
                           C.MOE_AUX_LOSS_WEIGHT_DEFAULT)
    if not isinstance(aux, (int, float)) or isinstance(aux, bool) or \
            aux < 0:
        raise DeepSpeedConfigError(
            f"moe.aux_loss_weight must be >= 0, got {aux!r}")
    every = get_scalar_param(block, C.MOE_EVERY_N_LAYERS,
                             C.MOE_EVERY_N_LAYERS_DEFAULT)
    if not isinstance(every, int) or isinstance(every, bool) or \
            every < 1:
        raise DeepSpeedConfigError(
            f"moe.every_n_layers must be an int >= 1, got {every!r}")
    jitter = get_scalar_param(block, C.MOE_JITTER_EPS,
                              C.MOE_JITTER_EPS_DEFAULT)
    if not isinstance(jitter, (int, float)) or \
            isinstance(jitter, bool) or jitter < 0:
        raise DeepSpeedConfigError(
            f"moe.jitter_eps must be >= 0, got {jitter!r}")
    fused = get_scalar_param(block, C.MOE_FUSED_DISPATCH,
                             C.MOE_FUSED_DISPATCH_DEFAULT)
    if fused is True:
        fused = "on"
    elif fused is False:
        fused = "off"
    if fused not in C.MOE_FUSED_DISPATCH_VALID:
        raise DeepSpeedConfigError(
            "moe.fused_dispatch must be one of "
            f"{list(C.MOE_FUSED_DISPATCH_VALID)}, got {fused!r}")
    known = {C.MOE_ENABLED, C.MOE_NUM_EXPERTS, C.MOE_TOP_K,
             C.MOE_CAPACITY_FACTOR, C.MOE_AUX_LOSS_WEIGHT,
             C.MOE_EVERY_N_LAYERS, C.MOE_JITTER_EPS,
             C.MOE_FUSED_DISPATCH}
    unknown = set(block) - known
    if unknown:
        logger.warning(
            f"moe: ignoring unknown key(s) {sorted(unknown)}; known "
            f"keys: {sorted(known)}")
    return {"enabled": enabled, "num_experts": num_experts,
            "top_k": top_k, "capacity_factor": float(cf),
            "aux_loss_weight": float(aux), "every_n_layers": every,
            "jitter_eps": float(jitter), "fused_dispatch": fused}


def get_overlap_config(param_dict):
    """Validated `overlap` block -> dict(enabled, sites,
    issue_distance). Site names are validated against
    ops/overlap.py's registry so a typo fails at config load, not
    silently at trace time."""
    block = param_dict.get(C.OVERLAP, {})
    if not isinstance(block, dict):
        raise DeepSpeedConfigError(
            f'"overlap" must be a dict, got {block!r}')
    enabled = bool(get_scalar_param(block, C.OVERLAP_ENABLED,
                                    C.OVERLAP_ENABLED_DEFAULT))
    sites = block.get(C.OVERLAP_SITES, C.OVERLAP_SITES_DEFAULT)
    if not (isinstance(sites, str) or
            (isinstance(sites, (list, tuple)) and
             all(isinstance(s, str) for s in sites))):
        raise DeepSpeedConfigError(
            'overlap.sites must be "auto" or a list of site names, '
            f"got {sites!r}")
    from deepspeed_tpu.ops import overlap as _overlap
    try:
        _overlap._normalize_sites(sites)
    except ValueError as e:
        raise DeepSpeedConfigError(str(e))
    dist = get_scalar_param(block, C.OVERLAP_ISSUE_DISTANCE,
                            C.OVERLAP_ISSUE_DISTANCE_DEFAULT)
    if not isinstance(dist, int) or isinstance(dist, bool) or dist < 1:
        raise DeepSpeedConfigError(
            f"overlap.issue_distance must be an int >= 1, got {dist!r}")
    known = {C.OVERLAP_ENABLED, C.OVERLAP_SITES,
             C.OVERLAP_ISSUE_DISTANCE}
    unknown = set(block) - known
    if unknown:
        logger.warning(
            f"overlap: ignoring unknown key(s) {sorted(unknown)}; "
            f"known keys: {sorted(known)}")
    return {"enabled": enabled,
            "sites": list(sites) if not isinstance(sites, str)
            else sites,
            "issue_distance": dist}


def get_autotune_config(param_dict):
    """Validated `autotune` block -> dict(enabled, table_path)."""
    block = param_dict.get(C.AUTOTUNE, {})
    if not isinstance(block, dict):
        raise DeepSpeedConfigError(
            f'"autotune" must be a dict, got {block!r}')
    enabled = bool(get_scalar_param(block, C.AUTOTUNE_ENABLED,
                                    C.AUTOTUNE_ENABLED_DEFAULT))
    path = get_scalar_param(block, C.AUTOTUNE_TABLE_PATH,
                            C.AUTOTUNE_TABLE_PATH_DEFAULT)
    if not isinstance(path, str):
        raise DeepSpeedConfigError(
            f"autotune.table_path must be a string, got {path!r}")
    return {"enabled": enabled, "table_path": path}


class DeepSpeedConfigWriter:
    """Minimal key-value holder used by tests/tools to compose configs."""

    def __init__(self, data=None):
        self.data = data if data is not None else {}

    def add_config(self, key, value):
        self.data[key] = value

    def load_config(self, filename):
        self.data = load_config_dict(filename)

    def write_config(self, filename):
        import json
        with open(filename, "w") as outfile:
            json.dump(self.data, outfile)


class DeepSpeedConfig:
    def __init__(self, json_file_or_dict, mpu=None, param_dict=None,
                 world_size=None):
        if param_dict is None:
            self._param_dict = load_config_dict(json_file_or_dict)
        else:
            self._param_dict = param_dict

        # Data-parallel world size. On TPU this is the size of the `data`
        # mesh axis; default = all addressable devices (single-axis DP).
        if world_size is not None:
            self.world_size = world_size
        elif mpu is not None:
            self.world_size = mpu.get_data_parallel_world_size()
        else:
            self.world_size = self._infer_world_size()

        # Elasticity: env-provided config overrides the batch triple.
        self.elasticity_enabled = False
        ec = self._param_dict.get(C.ELASTICITY, None)
        if ec is not None and ec.get(C.ELASTICITY_ENABLED, False):
            self._apply_elasticity(ec)

        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    @staticmethod
    def _infer_world_size():
        try:
            import jax
            return jax.device_count()
        except Exception:  # ds-lint: allow[BROADEXC] config parsing must work without an initialized backend; world size defaults to 1
            return 1

    def _apply_elasticity(self, ec):
        from deepspeed_tpu import elasticity as el
        from deepspeed_tpu.version import __version__
        self.elasticity_enabled = True

        # Explicit batch settings conflict with elasticity unless the user
        # opts out (ref elasticity behavior: ignore_non_elastic_batch_info).
        ignore = ec.get(el.IGNORE_NON_ELASTIC_BATCH_INFO,
                        el.IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)
        batch_keys = [C.TRAIN_BATCH_SIZE, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                      C.GRADIENT_ACCUMULATION_STEPS]
        if not ignore:
            present = [k for k in batch_keys if k in self._param_dict]
            if present:
                raise el.ElasticityConfigError(
                    f"Elasticity is enabled but batch parameters {present} "
                    f"are also set; remove them or set "
                    f"'{el.IGNORE_NON_ELASTIC_BATCH_INFO}': true")

        final_batch_size, valid_gpus, micro_batch_size = \
            el.compute_elastic_config(
                ds_config=self._param_dict,
                target_deepspeed_version=__version__,
                world_size=self.world_size)
        if os.environ.get(el.DEEPSPEED_ELASTICITY_CONFIG) is not None:
            el.ensure_immutable_elastic_config(runtime_elastic_config_dict=ec)
        self._param_dict[C.TRAIN_BATCH_SIZE] = final_batch_size
        self._param_dict[C.TRAIN_MICRO_BATCH_SIZE_PER_GPU] = micro_batch_size
        self._param_dict.pop(C.GRADIENT_ACCUMULATION_STEPS, None)

    def _initialize_params(self, param_dict):
        self.train_batch_size = get_train_batch_size(param_dict)
        self.train_micro_batch_size_per_gpu = \
            get_train_micro_batch_size_per_gpu(param_dict)
        self.gradient_accumulation_steps = \
            get_gradient_accumulation_steps(param_dict)
        self.steps_per_print = get_steps_per_print(param_dict)
        self.dump_state = get_dump_state(param_dict)

        self.disable_allgather = get_disable_allgather(param_dict)
        self.allreduce_always_fp32 = get_allreduce_always_fp32(param_dict)
        self.prescale_gradients = get_prescale_gradients(param_dict)
        self.gradient_predivide_factor = get_gradient_predivide_factor(param_dict)
        self.sparse_gradients_enabled = get_sparse_gradients_enabled(param_dict)

        self.zero_config = DeepSpeedZeroConfig(param_dict)
        self.zero_optimization_stage = self.zero_config.stage
        self.zero_enabled = self.zero_optimization_stage > 0

        self.activation_checkpointing_config = \
            DeepSpeedActivationCheckpointingConfig(param_dict)
        self.flops_profiler_config = DeepSpeedFlopsProfilerConfig(param_dict)

        self.fp16_enabled = get_fp16_enabled(param_dict)
        self.bfloat16_enabled = get_bfloat16_enabled(param_dict)
        self.bfloat16_master_weights = get_bfloat16_master_weights(
            param_dict)
        # Apex AMP parity (ref config.py:66-77): meaningless on TPU —
        # map "amp": {"enabled": true} to bf16 mixed precision, which
        # is the hardware's native fast dtype
        amp_dict = param_dict.get(C.AMP)
        if amp_dict is not None and not isinstance(amp_dict, dict):
            raise DeepSpeedConfigError(
                f'"amp" must be a dict like {{"enabled": true}}, '
                f"got {amp_dict!r}")
        amp_dict = amp_dict or {}
        self.amp_enabled = bool(amp_dict.get(C.AMP_ENABLED,
                                             C.AMP_ENABLED_DEFAULT))
        self.amp_params = {k: v for k, v in amp_dict.items()
                           if k != C.AMP_ENABLED}
        if self.amp_enabled:
            # ref config asserts amp and fp16 are mutually exclusive
            assert not self.fp16_enabled, \
                "amp and fp16 modes cannot be simultaneously enabled"
            from deepspeed_tpu.utils.logging import logger
            logger.warning(
                "amp.enabled maps to bf16 mixed precision on TPU "
                "(Apex AMP does not exist here); amp params "
                f"{list(self.amp_params)} are ignored")
            self.bfloat16_enabled = True
        assert not (self.fp16_enabled and self.bfloat16_enabled), \
            "fp16 and bf16 modes are mutually exclusive"
        self.loss_scale = get_loss_scale(param_dict)
        self.initial_dynamic_scale = get_initial_dynamic_scale(param_dict)
        self.dynamic_loss_scale_args = get_dynamic_loss_scale_args(param_dict)

        self.gradient_clipping = get_gradient_clipping(param_dict)

        self.optimizer_name = get_optimizer_name(param_dict)
        if self.optimizer_name is not None and \
                self.optimizer_name.lower() in C.DEEPSPEED_OPTIMIZERS:
            self.optimizer_name = self.optimizer_name.lower()
        self.optimizer_params = get_optimizer_params(param_dict)
        self.optimizer_legacy_fusion = get_optimizer_legacy_fusion(param_dict)
        self.zero_allow_untested_optimizer = \
            get_zero_allow_untested_optimizer(param_dict)

        self.scheduler_name = get_scheduler_name(param_dict)
        self.scheduler_params = get_scheduler_params(param_dict)

        self.wall_clock_breakdown = get_wall_clock_breakdown(param_dict)
        self.memory_breakdown = get_memory_breakdown(param_dict)
        from deepspeed_tpu.monitor.config import DeepSpeedMonitorConfig
        self.monitor_config = DeepSpeedMonitorConfig(param_dict)
        self.tensorboard_enabled = get_tensorboard_enabled(param_dict)
        self.tensorboard_output_path = get_tensorboard_output_path(param_dict)
        self.tensorboard_job_name = get_tensorboard_job_name(param_dict)

        self.sparse_attention = get_sparse_attention(param_dict)
        self.pipeline = get_pipeline_config(param_dict)
        self.mesh = get_mesh_config(param_dict)

        self.async_dispatch_enabled = get_async_dispatch_enabled(param_dict)
        self.async_dispatch_steps_per_sync = \
            get_async_dispatch_steps_per_sync(param_dict)
        self.async_dispatch_prefetch_depth = \
            get_async_dispatch_prefetch_depth(param_dict)

        self.quantized_compute = get_quantized_compute_config(param_dict)
        self.autotune = get_autotune_config(param_dict)
        self.overlap = get_overlap_config(param_dict)
        self.moe = get_moe_config(param_dict)

        self.pld_enabled = get_pld_enabled(param_dict)
        self.pld_params = get_pld_params(param_dict)

        checkpoint_tag_validation_mode = get_checkpoint_tag_validation(param_dict)
        self.checkpoint_tag_validation_enabled = \
            checkpoint_tag_validation_mode != "Ignore"
        self.checkpoint_tag_validation_fail = \
            checkpoint_tag_validation_mode == "Fail"
        self.checkpoint_async_save = get_checkpoint_async_save(param_dict)
        self.checkpoint_keep_last = get_checkpoint_keep_last(param_dict)
        self.checkpoint_writer_queue_depth = \
            get_checkpoint_writer_queue_depth(param_dict)
        self.checkpoint_queue_policy = get_checkpoint_queue_policy(param_dict)

    def _batch_assertion(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps

        assert train_batch > 0, \
            f"Train batch size: {train_batch} has to be greater than 0"
        assert micro_batch > 0, \
            f"Micro batch size per gpu: {micro_batch} has to be greater than 0"
        assert grad_acc > 0, \
            f"Gradient accumulation steps: {grad_acc} has to be greater than 0"
        assert train_batch == micro_batch * grad_acc * self.world_size, (
            f"Check batch related parameters. train_batch_size is not equal"
            f" to micro_batch_per_gpu * gradient_acc_step * world_size"
            f" {train_batch} != {micro_batch} * {grad_acc} * {self.world_size}")

    def _set_batch_related_parameters(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps

        # All three provided — assertion below checks consistency.
        if train_batch is not None and micro_batch is not None and \
                grad_acc is not None:
            return
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= self.world_size
            self.gradient_accumulation_steps = grad_acc
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // self.world_size
            micro_batch //= grad_acc
            self.train_micro_batch_size_per_gpu = micro_batch
        elif micro_batch is not None and grad_acc is not None:
            train_batch = micro_batch * grad_acc * self.world_size
            self.train_batch_size = train_batch
        elif train_batch is not None:
            self.gradient_accumulation_steps = 1
            self.train_micro_batch_size_per_gpu = train_batch // self.world_size
        elif micro_batch is not None:
            self.train_batch_size = micro_batch * self.world_size
            self.gradient_accumulation_steps = 1
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu "
                "needs to be provided")

    def _configure_train_batch_size(self):
        self._set_batch_related_parameters()
        self._batch_assertion()

    def _do_sanity_check(self):
        self._do_error_check()
        self._do_warning_check()

    def _do_error_check(self):
        assert self.train_micro_batch_size_per_gpu, \
            f"DeepSpeedConfig: {C.TRAIN_MICRO_BATCH_SIZE_PER_GPU} is not defined"
        assert self.gradient_accumulation_steps, \
            f"DeepSpeedConfig: {C.GRADIENT_ACCUMULATION_STEPS} is not defined"
        if self.zero_enabled:
            from deepspeed_tpu.runtime.zero.config import MAX_STAGE_ZERO_OPTIMIZATION
            assert self.zero_optimization_stage <= MAX_STAGE_ZERO_OPTIMIZATION

    def _do_warning_check(self):
        if self.zero_config.offload_wire_compressed() and \
                not self.zero_config.cpu_offload:
            logger.warning(
                "DeepSpeedConfig: zero_optimization.offload_wire "
                "compresses the ZeRO-Offload host link and has no effect "
                "without cpu_offload: true")
        fp16_enabled = self.fp16_enabled or self.zero_enabled
        vocabulary_size = self._param_dict.get(C.VOCABULARY_SIZE, None)
        if vocabulary_size and vocabulary_size % 8 != 0:
            logger.warning(
                "DeepSpeedConfig: vocabulary size should be aligned to 8 for "
                "good MXU utilization")
        if self.optimizer_params is not None and \
                C.MAX_GRAD_NORM in self.optimizer_params and \
                self.optimizer_params[C.MAX_GRAD_NORM] > 0:
            if fp16_enabled:
                logger.warning(
                    "DeepSpeedConfig: In FP16 mode, DeepSpeed will pass "
                    f"{C.MAX_GRAD_NORM} to FP16 wrapper")
            else:
                logger.warning(
                    f"DeepSpeedConfig: In FP32 mode, DeepSpeed does not permit "
                    f"{C.MAX_GRAD_NORM} in the optimizer config")

    def print(self, name):
        logger.info("{}:".format(name))
        for arg in sorted(vars(self)):
            if arg != "_param_dict":
                dots = "." * (29 - len(arg))
                logger.info("  {} {} {}".format(arg, dots, getattr(self, arg)))
