"""ZeRO stages 1/2/3 as GSPMD sharding policies.

TPU-native redesign of `deepspeed/runtime/zero/stage1.py` (1121 LoC) and
`stage2.py` (1855 LoC).  The reference implements partitioning imperatively:
flattened fp32 sub-partitions, per-param backward hooks filling contiguous
IPG buckets, hand-rolled async reduce-scatter to partition owners, and a
post-step sharded all-gather.  Under XLA/GSPMD every one of those behaviors
is a *sharding annotation*:

  stage 1  optimizer state (fp32 masters + moments) carries a
           PartitionSpec over the `data` axis → XLA reduce-scatters grads
           into the update and all-gathers updated params, exactly the
           stage-1 comm pattern (ref `stage1.py:572,624`), scheduled and
           overlapped by the XLA latency-hiding scheduler (replacing
           `overlap_comm` side streams, ref `stage2.py:676-682`).
  stage 2  gradient-accumulation buffers also carry the data-axis spec, so
           cross-microbatch grads live sharded — the IPG-bucket machinery
           (ref `stage2.py:613-738`) with none of the hooks.
  stage 3  parameters themselves are stored sharded and all-gathered
           on use (FSDP); the reference never shipped this
           (`engine.py:709-710` raises NotImplementedError) — on TPU it
           falls out of the same annotation mechanism.

The policy below picks, per array, the largest dimension divisible by the
data-axis size. Leaves with NO divisible dimension get *padded* on their
largest free dimension up to the next dp multiple (the TPU-native form of
the reference's sub-partition alignment padding, `stage1.py:198-261`):
the engine stores master weights / optimizer moments in the padded
("encoded") layout so they genuinely shard, and slices the padding off
("decode") when writing back compute-dtype params or checkpoints.
Tiny leaves (numel < 2*dp) stay replicated — the shard would be smaller
than the bookkeeping.
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.runtime.mesh import DATA_AXIS, MODEL_AXIS


def _best_shard_dim(shape, axis_size) -> Optional[int]:
    """Largest dim evenly divisible by axis_size; None if no dim qualifies."""
    best, best_size = None, 0
    for d, s in enumerate(shape):
        if s % axis_size == 0 and s >= axis_size and s > best_size:
            best, best_size = d, s
    return best


def leaf_data_spec(leaf, axis_size, existing_spec=None) -> PartitionSpec:
    """PartitionSpec sharding one dim of `leaf` over the data axis,
    composing with an existing (e.g. tensor-parallel) spec if given."""
    shape = np.shape(leaf)
    base = list(existing_spec) if existing_spec is not None else []
    base += [None] * (len(shape) - len(base))
    if axis_size <= 1:
        return PartitionSpec(*base)
    # Only consider dims not already taken by another axis.
    candidates = [(d, s) for d, s in enumerate(shape)
                  if base[d] is None and s % axis_size == 0 and s >= axis_size]
    if not candidates:
        return PartitionSpec(*base)
    d = max(candidates, key=lambda t: t[1])[0]
    base[d] = DATA_AXIS
    return PartitionSpec(*base)


class ZeroShardingPolicy:
    """Maps ZeRO stage → shardings for each state group.

    param_specs: optional pytree of PartitionSpecs carrying tensor-parallel
    placement (model axis); data-axis sharding composes on top.
    """

    def __init__(self, mesh: Mesh, stage: int, param_specs=None):
        # ValueError, not assert: a bad stage must fail loudly under
        # `python -O` too, and the message must carry the value
        if not isinstance(stage, (int, np.integer)) or \
                not 0 <= stage <= 3:
            raise ValueError(
                f"zero_optimization.stage must be an integer in "
                f"[0, 3], got {stage!r}")
        self.mesh = mesh
        self.stage = stage
        self.dp_size = mesh.shape[DATA_AXIS]
        self.param_specs = param_specs
        self._warned_replicated_fallback = False
        self._warned_compose_fallback = False

    # -- spec builders ----------------------------------------------------
    def _tp_spec_for(self, path_spec, leaf):
        if path_spec is None:
            return PartitionSpec(*([None] * np.ndim(leaf)))
        return path_spec

    def _specs(self, params, shard_over_data: bool):
        mp_size = self.mesh.shape.get(MODEL_AXIS, 1)
        fallback_elems = [0]   # numel that silently stays replicated
        compose_failed = [0]   # …of which a (model, data) compose missed

        def one(leaf, tp_spec):
            if np.ndim(leaf) == 0:
                return PartitionSpec()
            if shard_over_data:
                spec = leaf_data_spec(leaf, self.dp_size, tp_spec)
                if self.dp_size > 1 and not any(
                        s == DATA_AXIS for s in spec):
                    # No free dim: compose onto a model-sharded dim as
                    # (model, data) — e.g. the pipeline's [S, F] flat
                    # buffers where dim 0 is pipe and dim 1 model, so
                    # masters/moments divide by pipe*model*data.
                    base = list(spec)
                    shape = np.shape(leaf)
                    had_model_dim = False
                    for d, s in enumerate(base):
                        if s == MODEL_AXIS:
                            had_model_dim = True
                            if shape[d] % (mp_size * self.dp_size) == 0:
                                base[d] = (MODEL_AXIS, DATA_AXIS)
                                return PartitionSpec(*base)
                    # still nothing took DATA_AXIS: this leaf's
                    # masters/moments will be data-REPLICATED (the
                    # pad-plan may re-shard it later, but e.g. a
                    # StageFlatLayout built without align=model*data
                    # loses the pipe*model*data memory division here)
                    if int(np.prod(shape)) >= 2 * self.dp_size:
                        fallback_elems[0] += int(np.prod(shape))
                        if had_model_dim:
                            compose_failed[0] += int(np.prod(shape))
                return spec
            return self._tp_spec_for(tp_spec, leaf)

        if self.param_specs is None:
            out = jax.tree_util.tree_map(lambda l: one(l, None), params)
        else:
            out = jax.tree_util.tree_map(one, params, self.param_specs)
        if compose_failed[0] and not self._warned_compose_fallback:
            # ADVICE r5: the (MODEL_AXIS, DATA_AXIS) compose is how pipe
            # flat buffers get the pipe*model*data memory division — a
            # divisibility miss there is invisible in numerics and only
            # shows up as per-device memory that stopped dividing by dp.
            self._warned_compose_fallback = True
            from deepspeed_tpu.utils.logging import logger
            logger.warning(
                f"ZeRO: {compose_failed[0] / 1e6:.1f}M elements sit on a "
                f"model-sharded dim that is NOT divisible by mp*dp="
                f"{mp_size * self.dp_size}, so the (model, data) "
                "composition fell back to data-REPLICATED masters/"
                "moments — the model*data memory division is lost for "
                "these leaves. Align flat layouts to a multiple of "
                f"model*data (e.g. StageFlatLayout align={mp_size} * "
                f"{self.dp_size}) to restore it")
        if fallback_elems[0] and not self._warned_replicated_fallback:
            self._warned_replicated_fallback = True
            from deepspeed_tpu.utils.logging import logger
            logger.warning(
                f"ZeRO: {fallback_elems[0] / 1e6:.1f}M elements have no "
                f"dimension divisible by dp={self.dp_size} and fall back "
                "to data-REPLICATED optimizer state unless the pad-plan "
                "re-shards them — per-device memory will not divide by "
                "the data axis for these leaves (pad to a dp multiple, "
                "or align flat layouts by model*data)")
        return out

    # -- public: per-group PartitionSpec pytrees -------------------------
    def param_pspecs(self, params):
        """Compute-dtype parameters: sharded only at stage 3 (FSDP)."""
        return self._specs(params, shard_over_data=self.stage >= 3)

    def master_pspecs(self, params):
        """fp32 master copies + optimizer moments: sharded at stage >= 1."""
        return self._specs(params, shard_over_data=self.stage >= 1)

    def grad_accum_pspecs(self, params):
        """Cross-microbatch gradient accumulators: sharded at stage >= 2."""
        return self._specs(params, shard_over_data=self.stage >= 2)

    # -- NamedSharding versions ------------------------------------------
    def _named(self, pspecs):
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), pspecs,
            is_leaf=lambda x: isinstance(x, PartitionSpec))

    def param_shardings(self, params):
        return self._named(self.param_pspecs(params))

    def master_shardings(self, params):
        return self._named(self.master_pspecs(params))

    def grad_accum_shardings(self, params):
        return self._named(self.grad_accum_pspecs(params))

    # -- padding plan for non-divisible leaves ---------------------------
    def pad_plan(self, params):
        """{param_path_keystr: (dim, padded_size, true_size)} for every
        leaf that has no data-divisible free dimension but is big enough
        to be worth sharding. Empty dict when nothing needs padding (the
        common case for power-of-two model dims at moderate dp)."""
        plan = {}
        if self.dp_size <= 1 or self.stage < 1:
            return plan
        flat, _ = jax.tree_util.tree_flatten_with_path(params)
        spec_flat = None
        if self.param_specs is not None:
            spec_flat = jax.tree_util.tree_leaves(
                self.param_specs,
                is_leaf=lambda x: isinstance(x, PartitionSpec))
        for i, (path, leaf) in enumerate(flat):
            shape = np.shape(leaf)
            if not shape or int(np.prod(shape)) < 2 * self.dp_size:
                continue
            tp = list(spec_flat[i]) if spec_flat is not None else []
            tp += [None] * (len(shape) - len(tp))
            free = [(d, s) for d, s in enumerate(shape) if tp[d] is None]
            if not free:
                continue
            if any(s % self.dp_size == 0 and s >= self.dp_size
                   for _, s in free):
                continue  # leaf_data_spec will shard it unpadded
            d, s = max(free, key=lambda t: t[1])
            plan[jax.tree_util.keystr(path)] = (
                d, math.ceil(s / self.dp_size) * self.dp_size, s)
        return plan

    @staticmethod
    def _plan_entry(plan, keys, ks, suffix_match):
        entry = plan.get(ks)
        if entry is None and suffix_match:
            for k in keys:  # longest suffix wins
                if ks.endswith(k):
                    return plan[k]
        return entry

    def _tree_apply_plan(self, tree, plan, fn, suffix_match):
        """Apply fn(leaf, (dim, padded, true)) to leaves whose path
        matches the plan. suffix_match: optimizer-state trees (mu/nu/...
        reuse the param tree structure, so their keystr ENDS with the
        param's keystr)."""
        if not plan:
            return tree
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        keys = sorted(plan, key=len, reverse=True)
        leaves = []
        for path, leaf in flat:
            entry = self._plan_entry(plan, keys,
                                     jax.tree_util.keystr(path),
                                     suffix_match)
            leaves.append(leaf if entry is None else fn(leaf, entry))
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def encode(self, tree, plan, suffix_match=False):
        """Pad plan leaves to their data-divisible shapes (with zeros —
        grad norms and optimizer moments are unaffected). Abstract
        leaves (ShapeDtypeStruct templates, e.g. the SR-mode fp32
        template that is never materialized) get padded shapes only."""
        def pad(leaf, entry):
            d, padded, true = entry
            if isinstance(leaf, jax.ShapeDtypeStruct):
                if d >= len(leaf.shape) or leaf.shape[d] != true:
                    return leaf
                shape = list(leaf.shape)
                shape[d] = padded
                return jax.ShapeDtypeStruct(tuple(shape), leaf.dtype)
            if d >= leaf.ndim or leaf.shape[d] != true:
                return leaf  # already padded, or not a moment-like leaf
            pads = [(0, 0)] * leaf.ndim
            pads[d] = (0, padded - true)
            return jnp.pad(leaf, pads)
        return self._tree_apply_plan(tree, plan, pad, suffix_match)

    def decode(self, tree, plan, suffix_match=False):
        """Slice padded leaves back to their true shapes."""
        def unpad(leaf, entry):
            d, padded, true = entry
            if d >= leaf.ndim or leaf.shape[d] != padded:
                return leaf
            return jax.lax.slice_in_dim(leaf, 0, true, axis=d)
        return self._tree_apply_plan(tree, plan, unpad, suffix_match)

    # -- per-device byte accounting (memory ledger / plan validation) --
    def _spec_fraction(self, spec):
        """Fraction of a leaf ONE device holds under `spec` (1 / the
        product of named-axis sizes; tuple entries like (model, data)
        multiply). Pure metadata math — no arrays touched."""
        frac = 1.0
        for axis in spec:
            if axis is None:
                continue
            axes = axis if isinstance(axis, tuple) else (axis,)
            for a in axes:
                frac /= self.mesh.shape.get(a, 1)
        return frac

    def sharded_nbytes(self, tree, pspecs, bytes_per_elem):
        """Per-device bytes of a state group: each leaf's element
        count x bytes_per_elem x the fraction its PartitionSpec leaves
        on one device. `tree` may be abstract (eval_shape output)."""
        total = 0.0
        for leaf, spec in zip(
                jax.tree_util.tree_leaves(tree),
                jax.tree_util.tree_leaves(
                    pspecs,
                    is_leaf=lambda x: isinstance(x, PartitionSpec))):
            total += int(np.prod(np.shape(leaf))) * bytes_per_elem * \
                self._spec_fraction(spec)
        return int(total)

    def memory_plan(self, shapes, compute_bytes=2, sr_mode=False,
                    gas=1):
        """Planned per-device bytes per memory-ledger category for a
        parameter tree of `shapes` (abstract ok) under this policy:

          params     compute-dtype params (sharded only at stage 3)
          master     fp32 masters (absent in SR mode — no fp32 store)
          opt_state  two Adam moments (fp32, or compute-dtype in SR
                     mode), sharded like the masters
          grads      the persistent fp32 accumulator (only when the
                     fused step keeps one, i.e. gas > 1)

        Uses the ENCODED (pad-plan) layout for the sharded groups —
        the bytes the engine actually stores. This is the closed-form
        the memory ledger and the 13B feasibility plan validate
        against (`monitor/memory.py::plan_vs_measured`)."""
        enc = self.encode(shapes, self.pad_plan(shapes))
        plan = {
            "params": self.sharded_nbytes(
                shapes, self.param_pspecs(shapes), compute_bytes),
            "master": 0 if sr_mode else self.sharded_nbytes(
                enc, self.master_pspecs(enc), 4),
            "opt_state": 2 * self.sharded_nbytes(
                enc, self.master_pspecs(enc),
                compute_bytes if sr_mode else 4),
            "grads": self.sharded_nbytes(
                enc, self.grad_accum_pspecs(enc), 4) if gas > 1 else 0,
        }
        return plan

    def opt_state_shardings(self, opt_state, params):
        """Optimizer state: leaves that mirror a param shape get that
        param's master sharding; everything else (counts, scalars) is
        replicated."""
        master = self.master_pspecs(params)
        shape_to_spec = {}
        for spec, leaf in zip(jax.tree_util.tree_leaves(
                master, is_leaf=lambda x: isinstance(x, PartitionSpec)),
                jax.tree_util.tree_leaves(params)):
            shape_to_spec.setdefault(np.shape(leaf), spec)

        def one(leaf):
            spec = shape_to_spec.get(np.shape(leaf), PartitionSpec())
            return NamedSharding(self.mesh, spec)

        return jax.tree_util.tree_map(one, opt_state)
