"""ZeRO stages 1/2/3 as GSPMD sharding policies.

TPU-native redesign of `deepspeed/runtime/zero/stage1.py` (1121 LoC) and
`stage2.py` (1855 LoC).  The reference implements partitioning imperatively:
flattened fp32 sub-partitions, per-param backward hooks filling contiguous
IPG buckets, hand-rolled async reduce-scatter to partition owners, and a
post-step sharded all-gather.  Under XLA/GSPMD every one of those behaviors
is a *sharding annotation*:

  stage 1  optimizer state (fp32 masters + moments) carries a
           PartitionSpec over the `data` axis → XLA reduce-scatters grads
           into the update and all-gathers updated params, exactly the
           stage-1 comm pattern (ref `stage1.py:572,624`), scheduled and
           overlapped by the XLA latency-hiding scheduler (replacing
           `overlap_comm` side streams, ref `stage2.py:676-682`).
  stage 2  gradient-accumulation buffers also carry the data-axis spec, so
           cross-microbatch grads live sharded — the IPG-bucket machinery
           (ref `stage2.py:613-738`) with none of the hooks.
  stage 3  parameters themselves are stored sharded and all-gathered
           on use (FSDP); the reference never shipped this
           (`engine.py:709-710` raises NotImplementedError) — on TPU it
           falls out of the same annotation mechanism.

The policy below picks, per array, the largest dimension divisible by the
data-axis size (GSPMD requires no padding bookkeeping — the reference's
alignment/padding logic, `stage1.py:198-261`, has no analogue here).
Leaves too small to shard stay replicated, mirroring the reference's
handling of sub-partition remainders.
"""

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.runtime.mesh import DATA_AXIS


def _best_shard_dim(shape, axis_size) -> Optional[int]:
    """Largest dim evenly divisible by axis_size; None if no dim qualifies."""
    best, best_size = None, 0
    for d, s in enumerate(shape):
        if s % axis_size == 0 and s >= axis_size and s > best_size:
            best, best_size = d, s
    return best


def leaf_data_spec(leaf, axis_size, existing_spec=None) -> PartitionSpec:
    """PartitionSpec sharding one dim of `leaf` over the data axis,
    composing with an existing (e.g. tensor-parallel) spec if given."""
    shape = np.shape(leaf)
    base = list(existing_spec) if existing_spec is not None else []
    base += [None] * (len(shape) - len(base))
    if axis_size <= 1:
        return PartitionSpec(*base)
    # Only consider dims not already taken by another axis.
    candidates = [(d, s) for d, s in enumerate(shape)
                  if base[d] is None and s % axis_size == 0 and s >= axis_size]
    if not candidates:
        return PartitionSpec(*base)
    d = max(candidates, key=lambda t: t[1])[0]
    base[d] = DATA_AXIS
    return PartitionSpec(*base)


class ZeroShardingPolicy:
    """Maps ZeRO stage → shardings for each state group.

    param_specs: optional pytree of PartitionSpecs carrying tensor-parallel
    placement (model axis); data-axis sharding composes on top.
    """

    def __init__(self, mesh: Mesh, stage: int, param_specs=None):
        assert 0 <= stage <= 3
        self.mesh = mesh
        self.stage = stage
        self.dp_size = mesh.shape[DATA_AXIS]
        self.param_specs = param_specs

    # -- spec builders ----------------------------------------------------
    def _tp_spec_for(self, path_spec, leaf):
        if path_spec is None:
            return PartitionSpec(*([None] * np.ndim(leaf)))
        return path_spec

    def _specs(self, params, shard_over_data: bool):
        def one(leaf, tp_spec):
            if np.ndim(leaf) == 0:
                return PartitionSpec()
            if shard_over_data:
                return leaf_data_spec(leaf, self.dp_size, tp_spec)
            return self._tp_spec_for(tp_spec, leaf)

        if self.param_specs is None:
            return jax.tree_util.tree_map(lambda l: one(l, None), params)
        return jax.tree_util.tree_map(one, params, self.param_specs)

    # -- public: per-group PartitionSpec pytrees -------------------------
    def param_pspecs(self, params):
        """Compute-dtype parameters: sharded only at stage 3 (FSDP)."""
        return self._specs(params, shard_over_data=self.stage >= 3)

    def master_pspecs(self, params):
        """fp32 master copies + optimizer moments: sharded at stage >= 1."""
        return self._specs(params, shard_over_data=self.stage >= 1)

    def grad_accum_pspecs(self, params):
        """Cross-microbatch gradient accumulators: sharded at stage >= 2."""
        return self._specs(params, shard_over_data=self.stage >= 2)

    # -- NamedSharding versions ------------------------------------------
    def _named(self, pspecs):
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), pspecs,
            is_leaf=lambda x: isinstance(x, PartitionSpec))

    def param_shardings(self, params):
        return self._named(self.param_pspecs(params))

    def master_shardings(self, params):
        return self._named(self.master_pspecs(params))

    def grad_accum_shardings(self, params):
        return self._named(self.grad_accum_pspecs(params))

    def opt_state_shardings(self, opt_state, params):
        """Optimizer state: leaves that mirror a param shape get that
        param's master sharding; everything else (counts, scalars) is
        replicated."""
        master = self.master_pspecs(params)
        shape_to_spec = {}
        for spec, leaf in zip(jax.tree_util.tree_leaves(
                master, is_leaf=lambda x: isinstance(x, PartitionSpec)),
                jax.tree_util.tree_leaves(params)):
            shape_to_spec.setdefault(np.shape(leaf), spec)

        def one(leaf):
            spec = shape_to_spec.get(np.shape(leaf), PartitionSpec())
            return NamedSharding(self.mesh, spec)

        return jax.tree_util.tree_map(one, opt_state)
