"""ZeRO config block.

Parity with `deepspeed/runtime/zero/config.py:12` + `zero/constants.py`.
On TPU the stages are realized as GSPMD sharding policies over the `data`
mesh axis (see `deepspeed_tpu/runtime/zero/partition.py`):

  stage 0: replicated everything, grads all-reduced (psum)
  stage 1: optimizer state (fp32 master + moments) sharded over `data`
  stage 2: + gradient accumulation buffers sharded (reduce-scatter)
  stage 3: + parameters sharded (FSDP-style all-gather on use)

Bucket-size knobs are accepted for config compatibility; XLA's collective
scheduler replaces manual bucketing, so they act as hints only.
"""

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config_utils import get_scalar_param

ZERO_OPTIMIZATION = "zero_optimization"

ZERO_OPTIMIZATION_DISABLED = 0
ZERO_OPTIMIZATION_OPTIMIZER_STATES = 1
ZERO_OPTIMIZATION_GRADIENTS = 2
ZERO_OPTIMIZATION_WEIGHTS = 3
MAX_STAGE_ZERO_OPTIMIZATION = ZERO_OPTIMIZATION_WEIGHTS

ZERO_OPTIMIZATION_STAGE = "stage"
ZERO_OPTIMIZATION_STAGE_DEFAULT = ZERO_OPTIMIZATION_DISABLED

ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS = "allgather_partitions"
ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT = True

ZERO_OPTIMIZATION_REDUCE_SCATTER = "reduce_scatter"
ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT = True

ZERO_OPTIMIZATION_OVERLAP_COMM = "overlap_comm"
ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT = False

ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS = "contiguous_gradients"
ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT = False

ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE = "reduce_bucket_size"
ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT = 500000000

ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE = "allgather_bucket_size"
ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT = 500000000

ZERO_OPTIMIZATION_CPU_OFFLOAD = "cpu_offload"
ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT = False

ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT = "elastic_checkpoint"
ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT = True

ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS = "load_from_fp32_weights"
ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT = True

ZERO_OPTIMIZATION_DEFAULT = {
    ZERO_OPTIMIZATION_STAGE: ZERO_OPTIMIZATION_STAGE_DEFAULT,
}


class DeepSpeedZeroConfig:
    def __init__(self, param_dict):
        self.stage = None
        self.contiguous_gradients = None
        self.reduce_scatter = None
        self.reduce_bucket_size = None
        self.allgather_partitions = None
        self.allgather_bucket_size = None
        self.overlap_comm = None
        self.load_from_fp32_weights = None
        self.cpu_offload = None
        self.elastic_checkpoint = None
        self.offload_wire_grad_bits = None
        self.offload_wire_param_bits = None
        self.offload_wire_warmup_steps = None
        self.stage3_enabled = None
        self.stage3_prefetch_layers = None
        self.stage3_release_after_use = None
        self.stage3_gather_dtype = None

        if ZERO_OPTIMIZATION in param_dict:
            zero_config_dict = param_dict[ZERO_OPTIMIZATION]
            if isinstance(zero_config_dict, bool):
                zero_config_dict = {
                    ZERO_OPTIMIZATION_STAGE:
                    1 if zero_config_dict else 0
                }
        else:
            zero_config_dict = ZERO_OPTIMIZATION_DEFAULT
        self._initialize(zero_config_dict)

    def _initialize(self, d):
        self.stage = get_scalar_param(d, ZERO_OPTIMIZATION_STAGE,
                                      ZERO_OPTIMIZATION_STAGE_DEFAULT)
        assert 0 <= self.stage <= MAX_STAGE_ZERO_OPTIMIZATION, \
            f"zero_optimization.stage must be in [0,{MAX_STAGE_ZERO_OPTIMIZATION}]"
        self.contiguous_gradients = get_scalar_param(
            d, ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS,
            ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT)
        self.reduce_bucket_size = get_scalar_param(
            d, ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE,
            ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT)
        self.reduce_scatter = get_scalar_param(
            d, ZERO_OPTIMIZATION_REDUCE_SCATTER,
            ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT)
        self.overlap_comm = get_scalar_param(
            d, ZERO_OPTIMIZATION_OVERLAP_COMM,
            ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT)
        self.allgather_partitions = get_scalar_param(
            d, ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS,
            ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT)
        self.allgather_bucket_size = get_scalar_param(
            d, ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE,
            ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT)
        self.load_from_fp32_weights = get_scalar_param(
            d, ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS,
            ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT)
        self.cpu_offload = get_scalar_param(
            d, ZERO_OPTIMIZATION_CPU_OFFLOAD,
            ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT)
        self.elastic_checkpoint = get_scalar_param(
            d, ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT,
            ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT)
        self._initialize_offload_wire(d.get(C.OFFLOAD_WIRE) or {})
        self._initialize_stage3(d.get(C.STAGE3) or {})

    def _initialize_offload_wire(self, w):
        """zero_optimization.offload_wire: compressed wire format for the
        ZeRO-Offload round trip (see runtime/constants.py for semantics;
        runtime/zero/offload.py implements it). Defaults reproduce the
        uncompressed legacy wire exactly."""
        assert isinstance(w, dict), \
            f"zero_optimization.{C.OFFLOAD_WIRE} must be a dict, got {w!r}"
        self.offload_wire_grad_bits = int(get_scalar_param(
            w, C.OFFLOAD_WIRE_GRAD_BITS, C.OFFLOAD_WIRE_GRAD_BITS_DEFAULT))
        self.offload_wire_param_bits = int(get_scalar_param(
            w, C.OFFLOAD_WIRE_PARAM_BITS,
            C.OFFLOAD_WIRE_PARAM_BITS_DEFAULT))
        self.offload_wire_warmup_steps = int(get_scalar_param(
            w, C.OFFLOAD_WIRE_WARMUP_STEPS,
            C.OFFLOAD_WIRE_WARMUP_STEPS_DEFAULT))
        assert self.offload_wire_grad_bits in \
            C.OFFLOAD_WIRE_GRAD_BITS_VALID, (
                f"{C.OFFLOAD_WIRE}.{C.OFFLOAD_WIRE_GRAD_BITS} must be one "
                f"of {C.OFFLOAD_WIRE_GRAD_BITS_VALID}, got "
                f"{self.offload_wire_grad_bits}")
        assert self.offload_wire_param_bits in \
            C.OFFLOAD_WIRE_PARAM_BITS_VALID, (
                f"{C.OFFLOAD_WIRE}.{C.OFFLOAD_WIRE_PARAM_BITS} must be one "
                f"of {C.OFFLOAD_WIRE_PARAM_BITS_VALID}, got "
                f"{self.offload_wire_param_bits}")
        assert self.offload_wire_warmup_steps >= 0, (
            f"{C.OFFLOAD_WIRE}.{C.OFFLOAD_WIRE_WARMUP_STEPS} must be >= 0")

    def _initialize_stage3(self, s):
        """zero_optimization.stage3: knobs of the explicit stage-3
        gather/release runtime (runtime/zero/stage3.py). Validation
        raises ValueError with the offending value — a bare assert
        would vanish under `python -O` and let a bad config train."""
        if not isinstance(s, dict):
            raise ValueError(
                f"zero_optimization.{C.STAGE3} must be a dict, got {s!r}")
        self.stage3_enabled = bool(get_scalar_param(
            s, C.STAGE3_ENABLED, C.STAGE3_ENABLED_DEFAULT))
        self.stage3_prefetch_layers = int(get_scalar_param(
            s, C.STAGE3_PREFETCH_LAYERS, C.STAGE3_PREFETCH_LAYERS_DEFAULT))
        if self.stage3_prefetch_layers < 0:
            raise ValueError(
                f"zero_optimization.{C.STAGE3}.{C.STAGE3_PREFETCH_LAYERS} "
                f"must be >= 0, got {self.stage3_prefetch_layers}")
        self.stage3_release_after_use = bool(get_scalar_param(
            s, C.STAGE3_RELEASE_AFTER_USE,
            C.STAGE3_RELEASE_AFTER_USE_DEFAULT))
        self.stage3_gather_dtype = get_scalar_param(
            s, C.STAGE3_GATHER_DTYPE, C.STAGE3_GATHER_DTYPE_DEFAULT)
        if self.stage3_gather_dtype not in C.STAGE3_GATHER_DTYPE_VALID:
            raise ValueError(
                f"zero_optimization.{C.STAGE3}.{C.STAGE3_GATHER_DTYPE} "
                f"must be one of {C.STAGE3_GATHER_DTYPE_VALID}, got "
                f"{self.stage3_gather_dtype!r}")

    def offload_wire_compressed(self):
        """True when any leg of the wire differs from the legacy format."""
        return (self.offload_wire_grad_bits != 32 or
                self.offload_wire_param_bits != 32)

    def repr(self):
        return dict(stage=self.stage,
                    contiguous_gradients=self.contiguous_gradients,
                    reduce_scatter=self.reduce_scatter,
                    reduce_bucket_size=self.reduce_bucket_size,
                    allgather_partitions=self.allgather_partitions,
                    allgather_bucket_size=self.allgather_bucket_size,
                    overlap_comm=self.overlap_comm,
                    load_from_fp32_weights=self.load_from_fp32_weights,
                    cpu_offload=self.cpu_offload,
                    elastic_checkpoint=self.elastic_checkpoint,
                    offload_wire=dict(
                        grad_bits=self.offload_wire_grad_bits,
                        param_bits=self.offload_wire_param_bits,
                        warmup_steps=self.offload_wire_warmup_steps),
                    stage3=dict(
                        enabled=self.stage3_enabled,
                        prefetch_layers=self.stage3_prefetch_layers,
                        release_after_use=self.stage3_release_after_use,
                        gather_dtype=self.stage3_gather_dtype))

    def __repr__(self):
        return str(self.repr())
